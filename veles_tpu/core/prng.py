"""Reproducible keyed PRNG streams over JAX counter-based keys.

TPU-native re-design of reference ``veles/prng/random_generator.py``. The
reference guarantees reproducibility by owning saved numpy RNG states per
named stream and save/restoring global numpy state around every call
(``random_generator.py:52-106``), persisting seeds to
``cache/random_seed_<key>.npy``. JAX's splittable threefry keys make this
radically simpler and *stronger*: a stream is (seed, counter); any draw is a
pure function of them, so reproducibility survives resharding, elastic
slave requeue and snapshot/resume by just recording two integers.

Each named ``RandomGenerator`` owns:
- a ``jax.random`` key chain for device-side randomness (weight init,
  dropout, on-device uniform fills — replacing the xorshift1024* kernels in
  reference ``ocl/random.cl``/``cuda/random.cu``);
- a numpy ``Generator`` for host-side randomness (index shuffles in loaders),
  re-seedable and state-capturable for snapshots.

A global keyed registry (``get(key)``) mirrors reference
``random_generator.py:289``.
"""

import os
import threading

import numpy
import jax

from veles_tpu.core.config import root
from veles_tpu.core.logger import Logger


class RandomGenerator(Logger):
    """A named reproducible random stream (reference
    ``prng/random_generator.py:64``)."""

    def __init__(self, key):
        super().__init__(logger_name="prng.%s" % key)
        self.key = key
        self._lock = threading.Lock()
        self.seed(None)

    # -- seeding ------------------------------------------------------------
    def seed(self, seed, dtype=None, count=None):
        """Seed this stream. ``seed`` may be an int, bytes, a numpy array
        (hashed), a path to a seed file, or None (persisted seed or
        entropy). ``dtype``/``count`` accepted for CLI parity with the
        reference's ``file:dtype:count`` seed specs (``__main__.py:483-537``).
        """
        if seed is None:
            seed = self._load_or_create_persisted_seed()
        elif isinstance(seed, str):
            with open(seed, "rb") as fin:
                data = numpy.frombuffer(
                    fin.read((count or 16) * numpy.dtype(
                        dtype or numpy.uint8).itemsize),
                    dtype=dtype or numpy.uint8)
            seed = self._hash_to_int(data)
        elif isinstance(seed, (bytes, bytearray)):
            seed = self._hash_to_int(numpy.frombuffer(seed, numpy.uint8))
        elif isinstance(seed, numpy.ndarray):
            seed = self._hash_to_int(seed)
        self.initial_seed = int(seed) & 0xFFFFFFFFFFFFFFFF
        self._counter = 0
        self._jax_key = jax.random.key(
            numpy.uint64(self.initial_seed).astype(numpy.int64))
        self._numpy = numpy.random.Generator(
            numpy.random.PCG64(self.initial_seed))
        return self

    @staticmethod
    def _hash_to_int(array):
        import hashlib
        return int.from_bytes(
            hashlib.sha256(array.tobytes()).digest()[:8], "little")

    def _load_or_create_persisted_seed(self):
        """Reference persists seeds per key under the cache dir
        (``random_generator.py:106``) so re-runs stay reproducible."""
        cache = root.common.dirs.cache
        path = os.path.join(cache, "random_seed_%s.npy" % self.key)
        try:
            return int(numpy.load(path))
        except (OSError, ValueError):
            seed = int.from_bytes(os.urandom(8), "little")
            try:
                os.makedirs(cache, exist_ok=True)
                numpy.save(path, numpy.uint64(seed))
            except OSError:
                pass
            return seed

    # -- device-side (jax) --------------------------------------------------
    def next_key(self):
        """Return a fresh jax PRNG key; advances the stream counter."""
        with self._lock:
            self._counter += 1
            return jax.random.fold_in(self._jax_key, self._counter)

    def key_at(self, counter):
        """Key for an explicit counter value — used to *replay* randomness,
        e.g. when a failed minibatch is requeued to another slave
        (reference ``loader/base.py:679-687`` semantics)."""
        return jax.random.fold_in(self._jax_key, counter)

    def fill_uniform(self, shape, vle, dtype=None):
        """Device-side symmetric uniform fill U(-vle, vle) — the Znicz
        weight-init pattern (replaces the xorshift1024* fill kernels)."""
        import jax.numpy as jnp
        from veles_tpu.ops.rng import fill_uniform
        return fill_uniform(self.next_key(), shape, vle,
                            dtype or jnp.float32)

    # -- host-side (numpy) --------------------------------------------------
    @property
    def numpy_rng(self):
        return self._numpy

    def shuffle(self, arr):
        with self._lock:
            self._numpy.shuffle(arr)

    def permutation(self, n):
        with self._lock:
            return self._numpy.permutation(n)

    def randint(self, low, high=None, size=None):
        with self._lock:
            return self._numpy.integers(low, high, size)

    def random_sample(self, size=None):
        with self._lock:
            return self._numpy.random(size)

    def normal(self, loc=0.0, scale=1.0, size=None):
        with self._lock:
            return self._numpy.normal(loc, scale, size)

    def fill(self, arr, vmin=-1.0, vmax=1.0):
        """Uniformly fill a numpy array in place (reference
        ``random_generator.py`` fill)."""
        with self._lock:
            arr[...] = self._numpy.uniform(vmin, vmax, arr.shape)

    # -- snapshot support ---------------------------------------------------
    def __getstate__(self):
        return {
            "key": self.key,
            "initial_seed": self.initial_seed,
            "counter": self._counter,
            "numpy_state": self._numpy.bit_generator.state,
        }

    def __setstate__(self, state):
        Logger.__init__(self, logger_name="prng.%s" % state["key"])
        self.key = state["key"]
        self._lock = threading.Lock()
        self.seed(state["initial_seed"])
        self._counter = state["counter"]
        self._numpy.bit_generator.state = state["numpy_state"]


_registry = {}
_registry_lock = threading.Lock()


def get(key="default"):
    """Global keyed stream registry (reference
    ``random_generator.py:289``)."""
    with _registry_lock:
        rg = _registry.get(key)
        if rg is None:
            rg = _registry[key] = RandomGenerator(key)
        return rg


def streams_state():
    """Capture all stream states for whole-workflow snapshots."""
    with _registry_lock:
        return {k: v.__getstate__() for k, v in _registry.items()}


def restore_streams(state):
    with _registry_lock:
        for key, st in state.items():
            rg = _registry.get(key)
            if rg is None:
                rg = _registry[key] = RandomGenerator.__new__(RandomGenerator)
            rg.__setstate__(st)
