"""Interface verification for units.

Reference ``veles/verified.py:36-66`` ran zope ``verifyObject`` +
``verifyClass`` on every unit at construction (IUnit, IDistributable,
ILoader...). The TPU re-design keeps the capability without the zope
dependency: an interface is a contract dict of method names →
(min_positional_args) that :func:`verify_interface` checks structurally —
the method exists, is callable, and accepts the required arity — raising
one descriptive error instead of a far-away AttributeError/TypeError at
runtime.

Workflow.initialize verifies IUNIT always and IDISTRIBUTABLE when the run
is not standalone (the reference skipped distributed verification in
standalone mode too, ``workflow.py:299-345``).
"""

import inspect

from veles_tpu.core.errors import VelesError


class InterfaceError(VelesError):
    pass


#: method -> minimum positional parameters AFTER self
IUNIT = {"initialize": 0, "run": 0, "stop": 0}

#: arities are the CALL-SITE arg counts (workflow.py fleet paths), so an
#: implementation missing the slave parameter fails HERE, not mid-update
IDISTRIBUTABLE = {
    "generate_data_for_master": 0,
    "generate_data_for_slave": 1,   # (slave)
    "apply_data_from_master": 1,    # (data)
    "apply_data_from_slave": 2,     # (data, slave)
    "drop_slave": 1,                # (slave)
}

ILOADER = {"load_data": 0, "create_minibatch_data": 0,
           "fill_minibatch": 2}


def _accepts(fn, n_args):
    """True when ``fn(*n_args values)`` is a valid call: capacity covers
    n_args, no MORE than n_args are required, and no default-less
    keyword-only parameters exist (call sites pass positionally)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return True  # builtins/C funcs: cannot introspect, trust them
    capacity = 0
    required = 0
    has_var = False
    for param in sig.parameters.values():
        if param.kind in (param.POSITIONAL_ONLY,
                          param.POSITIONAL_OR_KEYWORD):
            capacity += 1
            if param.default is param.empty:
                required += 1
        elif param.kind == param.VAR_POSITIONAL:
            has_var = True
        elif param.kind == param.KEYWORD_ONLY \
                and param.default is param.empty:
            return False
    return (has_var or capacity >= n_args) and required <= n_args


def verify_interface(obj, interface, name="interface"):
    """Raise InterfaceError listing every contract violation at once."""
    problems = []
    for method, n_args in interface.items():
        fn = getattr(obj, method, None)
        if fn is None:
            problems.append("missing method %s()" % method)
        elif not callable(fn):
            problems.append("%s is not callable" % method)
        elif not _accepts(fn, n_args):
            problems.append("%s() is not callable with %d argument(s)"
                            % (method, n_args))
    if problems:
        raise InterfaceError(
            "%s does not implement %s: %s"
            % (getattr(obj, "name", type(obj).__name__), name,
               "; ".join(problems)))
