"""Class-mixin logging with ANSI colors, file duplication and event spans.

TPU-native re-design of reference ``veles/logger.py:59-332``. Kept: the
``Logger`` mixin giving every object a per-class logger, ``setup_logging``
with a colored console formatter, redirecting/duplicating all logging to a
file, and the ``event()`` span API used by the observability stack. Changed:
event spans are written to a local JSONL file (consumed by the web-status
timeline) instead of MongoDB — no database dependency on a TPU pod host.
"""

import json
import logging
import logging.handlers
import os
import sys
import threading
import time


class ColorFormatter(logging.Formatter):
    """ANSI color console formatter (reference ``logger.py:66-114``)."""

    COLORS = {
        logging.DEBUG: "\033[1;34m",     # blue
        logging.INFO: "\033[1;32m",      # green
        logging.WARNING: "\033[1;33m",   # yellow
        logging.ERROR: "\033[1;31m",     # red
        logging.CRITICAL: "\033[1;41m",  # red background
    }
    RESET = "\033[0m"

    def __init__(self, colorize=True):
        super().__init__(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s",
            "%H:%M:%S")
        self.colorize = colorize

    def format(self, record):
        text = super().format(record)
        if self.colorize:
            color = self.COLORS.get(record.levelno)
            if color:
                return "%s%s%s" % (color, text, self.RESET)
        return text


class Logger:
    """Mixin: every instance gets ``self.logger`` named after its class and
    debug/info/warning/error helpers (reference ``logger.py:59``)."""

    def __init__(self, **kwargs):
        logger_name = kwargs.pop("logger_name", type(self).__name__)
        self._logger_ = logging.getLogger(logger_name)
        super().__init__()

    @property
    def logger(self):
        try:
            if self._logger_ is not None:
                return self._logger_
        except AttributeError:
            pass
        # objects restored from pickle rebuild their logger lazily
        self._logger_ = logging.getLogger(type(self).__name__)
        return self._logger_

    @logger.setter
    def logger(self, value):
        self._logger_ = value

    def change_log_name(self, name):
        self._logger_ = logging.getLogger(name)

    def debug(self, msg, *args, **kwargs):
        self.logger.debug(msg, *args, **kwargs)

    def info(self, msg, *args, **kwargs):
        self.logger.info(msg, *args, **kwargs)

    def warning(self, msg, *args, **kwargs):
        self.logger.warning(msg, *args, **kwargs)

    def error(self, msg, *args, **kwargs):
        self.logger.error(msg, *args, **kwargs)

    def exception(self, msg="Exception", *args, **kwargs):
        self.logger.exception(msg, *args, **kwargs)

    # -- event span API (reference logger.py:264-289) -----------------------
    def event(self, name, etype, **attrs):
        """Record a span event: ``etype`` is "begin", "end" or "single"."""
        assert etype in ("begin", "end", "single"), etype
        get_event_recorder().record(
            name=name, etype=etype, source=type(self).__name__, **attrs)
        # the always-on black box keeps the last events too; lazy
        # import — observe.tracing imports THIS module at its top
        from veles_tpu.observe.flight import get_flight_recorder
        get_flight_recorder().note("event", name=name, etype=etype,
                                   source=type(self).__name__)


_setup_done = False


def setup_logging(level=logging.INFO, colorize=None):
    """Install the colored stderr handler on the root logger
    (reference ``logger.py:116-185``)."""
    global _setup_done
    if colorize is None:
        colorize = sys.stderr.isatty()
    rl = logging.getLogger()
    rl.setLevel(level)
    if not _setup_done:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(ColorFormatter(colorize))
        rl.addHandler(handler)
        _setup_done = True
    return rl


def duplicate_all_logging_to_file(path, level=logging.DEBUG):
    """Add a file handler mirroring everything (reference ``logger.py:187``)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    handler = logging.FileHandler(path)
    handler.setLevel(level)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    logging.getLogger().addHandler(handler)
    return handler


class MongoLogHandler(logging.Handler):
    """Duplicate every log record into a MongoDB collection (reference
    ``MongoLogHandler``, ``logger.py:292`` — the web dashboard's
    ``logs.html`` read these). pymongo is NOT a hard dependency: the
    default ``client_factory`` imports it lazily and raises a clear
    error if absent; tests and alternative drivers inject their own
    factory returning any object with
    ``client[db][collection].insert_one(doc)``."""

    def __init__(self, addr="127.0.0.1:27017", docid=None,
                 database="veles", collection="logs",
                 client_factory=None, level=logging.DEBUG):
        super().__init__(level)
        if client_factory is None:
            def client_factory(address):
                try:
                    import pymongo
                except ImportError:
                    raise RuntimeError(
                        "MongoDB log duplication needs pymongo installed "
                        "(the JSONL event recorder needs nothing — see "
                        "enable_event_recording)") from None
                return pymongo.MongoClient("mongodb://%s" % address)
        self.docid = docid or "%d" % os.getpid()
        self._collection = client_factory(addr)[database][collection]
        self._emitting = threading.local()
        self.on_close = None  # duplicate_all_logging_to_mongo's detach

    def close(self):
        detach = self.on_close
        self.on_close = None
        if detach is not None:
            detach()
        super().close()

    def emit(self, record):
        # pymongo 4.8+ itself logs DEBUG records during insert_one
        # (command/connection monitoring): without the re-entrancy guard
        # and driver filter, mirroring its records would recurse forever
        if record.name.startswith("pymongo") \
                or getattr(self._emitting, "active", False):
            return
        self._emitting.active = True
        try:
            self._collection.insert_one({
                "session": self.docid,
                "time": record.created,
                "level": record.levelname,
                "logger": record.name,
                "message": record.getMessage(),
            })
        except Exception:
            self.handleError(record)
        finally:
            self._emitting.active = False


def duplicate_all_logging_to_mongo(addr, docid=None, client_factory=None,
                                   background=True):
    """Mirror the root logger into MongoDB (reference ``logger.py:210``)
    and route event spans there too (collection ``events``), correlated
    by the same session docid as the log records.

    ``background=True`` (default) emits through a
    ``QueueHandler``/``QueueListener`` pair so the per-record network
    round trip happens on a listener thread, never blocking the caller
    (a slow/unreachable server would otherwise stall every log call on
    the driver's timeout, serialized through the handler lock).

    Tear down with ``handler.close()`` on the RETURNED handler: it
    detaches the root-logger handler, stops the listener (flushing
    queued records), and unregisters the event sink."""
    handler = MongoLogHandler(addr, docid=docid,
                              client_factory=client_factory)
    root_logger = logging.getLogger()
    listener = queue_handler = event_worker = event_queue = None
    events = handler._collection.database["events"]

    # override the recorder's pid-based session with the handler's docid
    # so veles.logs and veles.events join on the same key (the
    # reference's dashboard correlated them per session)
    if background:
        import queue as queue_mod
        from logging.handlers import QueueHandler, QueueListener

        queue_handler = QueueHandler(queue_mod.SimpleQueue())
        listener = QueueListener(queue_handler.queue, handler)
        listener.start()
        root_logger.addHandler(queue_handler)

        # events go through their own worker for the same reason the
        # log records do: Logger.event() must never block on a Mongo
        # round trip (or the driver's multi-second timeout)
        event_queue = queue_mod.SimpleQueue()

        def sink(attrs):
            event_queue.put(dict(attrs, session=handler.docid))

        def drain():
            warned = False
            while True:
                item = event_queue.get()
                if item is None:
                    return
                try:
                    events.insert_one(item)
                except Exception:
                    # the span is dropped (the JSONL recorder still has
                    # it) — but say so ONCE: in this mode sink() only
                    # enqueues, so record()'s warn-once can never fire
                    if not warned:
                        warned = True
                        logging.getLogger("MongoLogHandler").exception(
                            "event insert failed (further failures "
                            "silent; spans remain in the JSONL log)")

        event_worker = threading.Thread(target=drain,
                                        name="mongo-events", daemon=True)
        event_worker.start()
    else:
        root_logger.addHandler(handler)

        def sink(attrs):
            events.insert_one(dict(attrs, session=handler.docid))

    get_event_recorder().add_sink(sink)

    def detach():
        get_event_recorder().remove_sink(sink)
        if listener is not None:
            root_logger.removeHandler(queue_handler)
            listener.stop()
            event_queue.put(None)  # drains queued spans first (FIFO)
            event_worker.join(timeout=10)
            if event_worker.is_alive():
                # a stuck driver timeout can outlive the join budget —
                # the flush promise must fail loudly, not silently
                logging.getLogger("MongoLogHandler").warning(
                    "mongo event queue not fully flushed within 10s; "
                    "remaining spans may be lost (daemon worker still "
                    "inserting)")
        else:
            root_logger.removeHandler(handler)

    handler.on_close = detach
    return handler


class EventRecorder:
    """Append-only JSONL event-span log, the TPU-era stand-in for the
    reference's MongoDB event store (``logger.py:210-289``). Spans carry a
    session id and wall-clock time; the web-status timeline reads this file.
    """

    #: pre-open buffer cap: a recorder CONFIGURED with a path whose
    #: open() never comes (misordered startup, crashed initializer)
    #: must not grow its buffer forever — beyond this the OLDEST spans
    #: drop (the recent ones are the ones worth flushing) with one
    #: warning
    MAX_BUFFER = 10000

    def __init__(self, path=None, session=None):
        self.path = path
        self.session = session or "%d" % os.getpid()
        self._lock = threading.Lock()
        self._fd = None
        self._buffer = []
        self._buffer_dropped = 0
        self._sinks = []
        self._sink_warned = set()
        self.enabled = path is not None

    def add_sink(self, sink):
        """Register an extra span consumer (e.g. the Mongo duplicator);
        ``sink(attrs_dict)`` is called for every recorded span. Sink
        exceptions are swallowed (logged once per sink) and the sink
        KEPT — a transient outage must neither kill the run nor
        permanently disable duplication."""
        with self._lock:
            self._sinks.append(sink)
            self._sink_warned.discard(id(sink))

    def remove_sink(self, sink):
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)
            self._sink_warned.discard(id(sink))

    def open(self, path):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self._fd = open(path, "a", buffering=1)
        self.enabled = True
        with self._lock:
            for line in self._buffer:
                self._fd.write(line)
            self._buffer.clear()

    def record(self, **attrs):
        attrs.setdefault("time", time.time())
        # monotonic stamp: what the Chrome trace exporter orders and
        # measures by (wall time can step; span durations must not)
        attrs.setdefault("mono", time.monotonic())
        attrs.setdefault("session", self.session)
        line = json.dumps(attrs, default=str) + "\n"
        warn_drop = False
        with self._lock:
            if self._fd is not None:
                self._fd.write(line)
            elif self.enabled:
                if len(self._buffer) >= self.MAX_BUFFER:
                    # drop-oldest: the spans worth flushing at open()
                    # are the recent ones
                    del self._buffer[0]
                    warn_drop = self._buffer_dropped == 0
                    self._buffer_dropped += 1
                self._buffer.append(line)
        if warn_drop:  # once — this can be a high-frequency path
            logging.getLogger("EventRecorder").warning(
                "pre-open event buffer full (%d spans); dropping the "
                "oldest from here on — call open()/"
                "enable_event_recording to flush (reported once)",
                self.MAX_BUFFER)
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink(attrs)
            except Exception:
                with self._lock:
                    warn = id(sink) not in self._sink_warned
                    self._sink_warned.add(id(sink))
                if warn:  # once per sink — spans can be high-frequency
                    logging.getLogger("EventRecorder").exception(
                        "event sink failed (kept; reported once)")

    def close(self):
        with self._lock:
            if self._fd is not None:
                self._fd.close()
                self._fd = None


_event_recorder = EventRecorder()


def get_event_recorder():
    return _event_recorder


def enable_event_recording(path):
    _event_recorder.open(path)
    return _event_recorder
