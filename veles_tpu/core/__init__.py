"""Core layer: foundation + unit/graph machinery."""
