"""Shared stdlib HTTP-server plumbing for the service units.

One implementation of the ThreadingHTTPServer-on-daemon-thread lifecycle
and JSON reply bookkeeping, used by the REST inference API
(``serving.py``) and the web-status dashboard (``web_status.py``).
Binds loopback by default — the same posture as the fleet server
(``fleet/server.py``); pass an explicit host to expose wider.
"""

import json
import threading


class QuietHandlerMixin:
    """Suppress the per-request stderr log lines."""

    def log_message(self, *args):
        pass


def reply(handler, body, code=200, content_type="application/json"):
    """Write one complete HTTP response."""
    if isinstance(body, (dict, list)):
        body = json.dumps(body).encode()
    elif isinstance(body, str):
        body = body.encode()
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def read_body(handler):
    length = int(handler.headers.get("Content-Length", 0))
    return handler.rfile.read(length)


def start_server(handler_cls, host="127.0.0.1", port=0, name="httpd"):
    """Start a ThreadingHTTPServer on a daemon thread.

    Returns (httpd, resolved_port). Stop with ``httpd.shutdown()``."""
    from http.server import ThreadingHTTPServer

    httpd = ThreadingHTTPServer((host, port), handler_cls)
    thread = threading.Thread(target=httpd.serve_forever, name=name,
                              daemon=True)
    thread.start()
    return httpd, httpd.server_address[1]
