"""Shared stdlib HTTP-server plumbing for the service units.

One implementation of the ThreadingHTTPServer-on-daemon-thread lifecycle
and JSON reply bookkeeping, used by the REST inference API
(``serving.py``) and the web-status dashboard (``web_status.py``).
Binds loopback by default — the same posture as the fleet server
(``fleet/server.py``); pass an explicit host to expose wider.

Survival-layer additions shared by every HTTP surface
(docs/serving_robustness.md):

- :func:`read_body` enforces a request-body byte cap and answers 413
  *before* buffering anything, so no client can balloon server memory
  with a huge ``Content-Length``;
- :func:`serve_health` mounts the ``/healthz`` + ``/readyz`` probe pair
  off any object with a ``snapshot()``/``ready`` surface (the serving
  units' ``ServingHealth``), the same contract k8s-style orchestrators
  expect;
- :func:`serve_metrics` mounts ``GET /metrics`` (Prometheus text
  exposition off the process-global MetricsRegistry,
  ``observe/metrics.py``) — the one telemetry plane every HTTP surface
  shares (docs/observability.md). Mounting it ENABLES the registry:
  processes that never start an HTTP server keep the no-op fast path.
"""

import json
import threading

#: default request-body cap (bytes); generous for base64 tensors, far
#: below anything that could pressure host memory
MAX_BODY = 32 * 1024 * 1024


class QuietHandlerMixin:
    """Suppress the per-request stderr log lines."""

    def log_message(self, *args):
        pass


class BodyTooLarge(ValueError):
    """Raised by :func:`read_body` after the 413 has been sent."""


def reply(handler, body, code=200, content_type="application/json",
          headers=None):
    """Write one complete HTTP response. Client disconnects are
    swallowed: the peer walking away mid-reply must never take down the
    handler thread loop (or spam tracebacks) on a serving box."""
    if isinstance(body, (dict, list)):
        body = json.dumps(body).encode()
    elif isinstance(body, str):
        body = body.encode()
    try:
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(body)))
        for key, value in (headers or {}).items():
            handler.send_header(key, value)
        handler.end_headers()
        handler.wfile.write(body)
    except OSError:  # covers BrokenPipe/ConnectionReset and the rest:
        # a peer (or socket) failing mid-reply must never take down the
        # handler thread; mark the connection dead so the handler does
        # not try to keep-alive a half-closed socket
        handler.close_connection = True


def read_body(handler, limit=MAX_BODY):
    """Read the request body, bounded.

    An absent/garbage ``Content-Length`` reads as empty; a length above
    ``limit`` answers 413 immediately (nothing is buffered) and raises
    :class:`BodyTooLarge` so the caller just returns."""
    try:
        length = int(handler.headers.get("Content-Length", 0))
    except (TypeError, ValueError):
        length = 0
    if length < 0:
        length = 0
    if length > limit:
        reply(handler, {"error": "request body %d bytes exceeds the "
                                 "%d byte cap" % (length, limit)},
              code=413)
        handler.close_connection = True
        raise BodyTooLarge("body %d > cap %d" % (length, limit))
    return handler.rfile.read(length)


def retry_after_headers(source=None, need=1, fallback=1.0):
    """THE priced ``Retry-After`` header — one helper for every
    429/503 the serving surfaces emit (historically five independent
    hardcoded ``"1"``s). ``source`` is anything with a
    ``retry_after_s(need)`` (``ServingHealth`` consults its attached
    governor, then its pool's observed page-release rate); without one
    the fallback applies. Clamped to [1, 60] seconds like the
    pool-gate pricing (``kv_pool.PagePool.retry_after``); a broken
    source must degrade to the fallback, never break the reply."""
    seconds = None
    price = getattr(source, "retry_after_s", None)
    if price is not None:
        try:
            seconds = price(need)
        except Exception:
            seconds = None
    if seconds is None:
        seconds = fallback
    return {"Retry-After": "%d" % int(min(60, max(1, round(seconds))))}


def serve_health(handler, health):
    """Route ``GET /healthz`` and ``GET /readyz`` against ``health``
    (any object with ``snapshot()`` -> dict and a ``ready`` bool).

    ``/healthz`` always answers 200 with the counter snapshot — the
    process is alive and can say so; ``/readyz`` answers 200 only while
    the unit can actually serve (breaker closed, decoder built) and 503
    otherwise, so load balancers drain a rebuilding replica instead of
    feeding it traffic. Returns True when the path was handled."""
    path = handler.path.split("?")[0]
    if path == "/healthz":
        reply(handler, health.snapshot())
        return True
    if path == "/readyz":
        if health.ready:
            reply(handler, {"ready": True})
        else:
            reply(handler, {"ready": False, "state": health.snapshot()},
                  code=503, headers=retry_after_headers(health))
        return True
    return False


def serve_metrics(handler, registry=None):
    """Route ``GET /metrics``: the Prometheus exposition of
    ``registry`` (default: the process-global one). Returns True when
    the path was handled. The first mount enables the registry — until
    some surface can actually be scraped, every ``incr``/``observe``
    in the hot paths stays a structural no-op.

    Content negotiation: a scraper advertising
    ``application/openmetrics-text`` in ``Accept`` gets the OpenMetrics
    rendering — histogram bucket EXEMPLARS (trace-id links on the
    request-latency families, docs/observability.md) and the ``# EOF``
    terminator; everyone else gets the plain 0.0.4 text exposition, so
    exemplars can never break a legacy scraper."""
    path = handler.path.split("?")[0]
    if path != "/metrics":
        return False
    if registry is None:
        from veles_tpu.observe.metrics import get_metrics_registry
        registry = get_metrics_registry()
    registry.enable()  # scrapeable == enabled, as documented
    # device truth rides every mounted surface: the compile tracker
    # turns on and the XLA/memory/MFU collector attaches (idempotent)
    from veles_tpu.observe.xla_stats import ensure_registered
    ensure_registered(registry)
    # the metric flight recorder rides too (observe/history.py):
    # history is default-on wherever /metrics is mounted, so trends
    # and incident autopsies exist for anything scrapeable (idempotent)
    from veles_tpu.observe.history import start_history_sampler
    start_history_sampler()
    # the serving goodput families (observe/servescope.py) ride every
    # mount as well — gated inside the collector on actual traffic
    from veles_tpu.observe.servescope import ensure_serve_registered
    ensure_serve_registered(registry)
    accept = str(getattr(handler, "headers", {}).get("Accept") or "")
    if "application/openmetrics-text" in accept:
        reply(handler, registry.expose(openmetrics=True),
              content_type="application/openmetrics-text; "
                           "version=1.0.0; charset=utf-8")
    else:
        reply(handler, registry.expose(),
              content_type="text/plain; version=0.0.4; charset=utf-8")
    return True


#: the debug surfaces the serving HTTP mounts share, path -> one-line
#: description — what the ``GET /debug/`` index answers so operators
#: stop guessing paths (the fleet metrics sidecar passes its own map
#: with ``/debug/fleet``)
DEBUG_SURFACES = {
    "/debug/requests": "request-truth ledger: in-flight + slowest "
                       "resolved request rows (observe/reqledger.py)",
    "/debug/history": "metric flight recorder: windowed series tails "
                      "+ anomaly-rule states (observe/history.py)",
    "/debug/serve": "serving goodput observatory: per-slot occupancy "
                    "timeline + token-waste decomposition "
                    "(observe/servescope.py; assemble with `veles_tpu "
                    "observe serve-trace`)",
    "/debug/memory": "per-owner HBM attribution: reconciled owner "
                     "bytes + untagged residue, lifecycle-edge leak "
                     "verdicts and the pool headroom forecast "
                     "(observe/memscope.py)",
}


def serve_debug_index(handler, surfaces=None):
    """Route ``GET /debug`` / ``GET /debug/``: list the debug surfaces
    mounted on this server (path -> description) so operators discover
    ``/debug/requests``, ``/debug/history``, ``/debug/serve`` and the
    fleet sidecar's ``/debug/fleet`` instead of guessing. Returns True
    when the path was handled."""
    path = handler.path.split("?")[0]
    if path not in ("/debug", "/debug/"):
        return False
    reply(handler, {"surfaces": dict(DEBUG_SURFACES
                                     if surfaces is None
                                     else surfaces)})
    return True


def serve_debug_serve(handler, scope=None, ledger=None):
    """Route ``GET /debug/serve``: the serving goodput observatory's
    payload (``observe/servescope.py``) — goodput/waste decomposition,
    the per-slot occupancy timeline and the request-ledger rows it
    merges with, assembled into a Perfetto trace by ``veles_tpu
    observe serve-trace [ARTIFACT | --live URL]``. Mounted on the
    serving surfaces beside ``/debug/requests``; returns True when
    handled."""
    path = handler.path.split("?")[0]
    if path != "/debug/serve":
        return False
    if scope is None:
        from veles_tpu.observe.servescope import get_serve_scope
        scope = get_serve_scope()
    if ledger is None:
        from veles_tpu.observe.reqledger import get_request_ledger
        ledger = get_request_ledger()
    reply(handler, scope.debug_snapshot(ledger=ledger))
    return True


def serve_debug_requests(handler, ledger=None):
    """Route ``GET /debug/requests``: the request-truth ledger's live
    view — in-flight rows plus the N slowest resolved (``?n=``, default
    8, capped 64) as JSON (``observe/reqledger.py``). Mounted on every
    serving surface beside ``/healthz``; returns True when handled."""
    path, _, query = handler.path.partition("?")
    if path != "/debug/requests":
        return False
    if ledger is None:
        from veles_tpu.observe.reqledger import get_request_ledger
        ledger = get_request_ledger()
    n = 8
    for part in query.split("&"):
        if part.startswith("n="):
            try:
                n = max(1, min(64, int(part[2:])))
            except ValueError:
                pass
    reply(handler, ledger.debug_snapshot(slowest=n))
    return True


def serve_debug_history(handler, history=None):
    """Route ``GET /debug/history``: the metric flight recorder's
    windowed series tails + anomaly-rule states as JSON
    (``observe/history.py``). Query params: ``series=`` (name
    substring filter) and ``window=`` (trailing seconds). Mounted on
    the serving surfaces beside ``/debug/requests``; returns True when
    handled (404 when history is disabled)."""
    path, _, query = handler.path.partition("?")
    if path != "/debug/history":
        return False
    if history is None:
        from veles_tpu.observe.history import get_metric_history
        history = get_metric_history()
    if history is None:
        reply(handler, {"error": "metric history disabled "
                                 "(root.common.observe.history)"},
              code=404)
        return True
    series, window = None, None
    for part in query.split("&"):
        if part.startswith("series="):
            series = part[len("series="):] or None
        elif part.startswith("window="):
            try:
                window = max(0.0, float(part[len("window="):]))
            except ValueError:
                pass
    reply(handler, history.debug_snapshot(series=series, window=window))
    return True


def serve_debug_memory(handler, scope=None):
    """Route ``GET /debug/memory``: memscope's reconciled per-owner
    HBM attribution — owner bytes + the ``untagged`` residue against
    the device total, the trailing lifecycle-edge leak verdicts (with
    incident artifact paths) and the pool headroom forecast as JSON
    (``observe/memscope.py``). Query param: ``edges=`` (trailing edge
    verdicts to include, default 16, capped 64). Mounted on the
    serving surfaces beside ``/debug/serve``; returns True when
    handled."""
    path, _, query = handler.path.partition("?")
    if path != "/debug/memory":
        return False
    if scope is None:
        from veles_tpu.observe.memscope import get_memscope
        scope = get_memscope()
    edges = 16
    for part in query.split("&"):
        if part.startswith("edges="):
            try:
                edges = max(1, min(64, int(part[len("edges="):])))
            except ValueError:
                pass
    reply(handler, scope.debug_snapshot(edges=edges))
    return True


def enable_metrics():
    """Turn the process-global registry on (idempotent); every HTTP
    surface calls this at start so its counters accumulate from the
    first request, not the first scrape. Also enables the device-truth
    plane (compile tracking, memory/MFU gauges — observe/xla_stats.py)
    and starts the metric-history sampler (observe/history.py) so a
    scrape of any surface sees what the chip is doing AND how it has
    been trending."""
    from veles_tpu.observe.history import start_history_sampler
    from veles_tpu.observe.metrics import get_metrics_registry
    from veles_tpu.observe.servescope import ensure_serve_registered
    from veles_tpu.observe.xla_stats import ensure_registered
    registry = ensure_registered(get_metrics_registry().enable())
    ensure_serve_registered(registry)
    start_history_sampler()
    return registry


def start_server(handler_cls, host="127.0.0.1", port=0, name="httpd"):
    """Start a ThreadingHTTPServer on a daemon thread.

    Returns (httpd, resolved_port). Stop with ``httpd.shutdown()``."""
    from http.server import ThreadingHTTPServer

    httpd = ThreadingHTTPServer((host, port), handler_cls)
    thread = threading.Thread(target=httpd.serve_forever, name=name,
                              daemon=True)
    thread.start()
    return httpd, httpd.server_address[1]
