"""veles_tpu — a TPU-native dataflow machine-learning framework.

A from-scratch re-design of the capabilities of Samsung VELES
(``gujunli/veles``) for TPUs: models are Workflows — directed graphs of Units
linked by control and data edges — whose accelerated segments compile into
fused XLA computations via JAX (jit/pjit), with Pallas kernels for hot ops,
data/tensor/sequence parallelism over a ``jax.sharding.Mesh`` (ICI
collectives), an elastic host-orchestrated fleet mode over TCP (DCN),
whole-workflow snapshot/resume, plotting/web-status/REST services, genetic
hyperparameter optimization, ensembles, a model hub, and a C++ inference
runtime for exported workflow packages.

Importable API (reference ``veles/__init__.py:126-189``): the package is
callable — ``import veles_tpu; veles_tpu("wf.py", config...)`` runs a
workflow with kwargs mirroring the CLI flags.
"""

import sys

__version__ = "0.1.0"
__license__ = "Apache 2.0"

from veles_tpu.core.config import root, Config  # noqa: F401
from veles_tpu.core.mutable import Bool, LinkableAttribute  # noqa: F401
from veles_tpu.core import prng  # noqa: F401


def __run__(workflow_file, config_file=None, **kwargs):
    from veles_tpu.cli import run_workflow_file
    return run_workflow_file(workflow_file, config_file, **kwargs)


#: discovered plugin modules (reference ``veles.__plugins__`` — the
#: package scanned installed ``veles.*`` namespace packages,
#: ``__init__.py:191-215``); populated lazily by :func:`scan_plugins`
__plugins__ = None


def scan_plugins():
    """Discover and import installed plugins, returning the module list.

    Two conventions (both additive — a plugin only needs to be
    installed, no registration call):

    - top-level modules named ``veles_tpu_<name>`` (the TPU-era
      namespace-package equivalent of the reference's ``veles.*`` scan);
    - ``veles_tpu.plugins`` entry points (the modern packaging idiom).

    Importing a plugin registers its units/loaders through the same
    registry metaclasses every in-tree unit uses, so discovered units
    are immediately constructible by name (StandardWorkflow layer specs,
    mapped loaders, CLI flags). Scanning is lazy — the CLI calls this
    once at startup; library users call it when they want plugins.
    """
    global __plugins__
    if __plugins__ is not None:
        return __plugins__
    import importlib
    import pkgutil

    plugins = []
    for info in pkgutil.iter_modules():
        if info.name.startswith("veles_tpu_"):
            try:
                plugins.append(importlib.import_module(info.name))
            except Exception as e:  # a broken plugin must not kill the CLI
                sys.stderr.write("veles_tpu: plugin %s failed to import: "
                                 "%s\n" % (info.name, e))
    try:
        from importlib.metadata import entry_points
        eps = entry_points()
        group = (eps.select(group="veles_tpu.plugins")
                 if hasattr(eps, "select")
                 else eps.get("veles_tpu.plugins", ()))
        for ep in group:
            try:
                plugins.append(ep.load())
            except Exception as e:
                sys.stderr.write("veles_tpu: plugin entry point %s failed:"
                                 " %s\n" % (ep.name, e))
    except Exception as e:
        # one unrelated distribution with broken metadata can make
        # entry_points() itself raise — say so instead of silently
        # skipping the whole entry-point convention
        sys.stderr.write("veles_tpu: plugin entry-point scan failed: %s\n"
                         % (e,))
    __plugins__ = plugins
    return plugins


class _VelesTPUModule(sys.modules[__name__].__class__):
    """Callable module (reference ``VelesModule``, ``__init__.py:126``)."""

    def __call__(self, workflow_file, config_file=None, **kwargs):
        return __run__(workflow_file, config_file, **kwargs)


sys.modules[__name__].__class__ = _VelesTPUModule
