"""veles_tpu — a TPU-native dataflow machine-learning framework.

A from-scratch re-design of the capabilities of Samsung VELES
(``gujunli/veles``) for TPUs: models are Workflows — directed graphs of Units
linked by control and data edges — whose accelerated segments compile into
fused XLA computations via JAX (jit/pjit), with Pallas kernels for hot ops,
data/tensor/sequence parallelism over a ``jax.sharding.Mesh`` (ICI
collectives), an elastic host-orchestrated fleet mode over TCP (DCN),
whole-workflow snapshot/resume, plotting/web-status/REST services, genetic
hyperparameter optimization, ensembles, a model hub, and a C++ inference
runtime for exported workflow packages.

Importable API (reference ``veles/__init__.py:126-189``): the package is
callable — ``import veles_tpu; veles_tpu("wf.py", config...)`` runs a
workflow with kwargs mirroring the CLI flags.
"""

import sys

__version__ = "0.1.0"
__license__ = "Apache 2.0"

from veles_tpu.core.config import root, Config  # noqa: F401
from veles_tpu.core.mutable import Bool, LinkableAttribute  # noqa: F401
from veles_tpu.core import prng  # noqa: F401


def __run__(workflow_file, config_file=None, **kwargs):
    from veles_tpu.cli import run_workflow_file
    return run_workflow_file(workflow_file, config_file, **kwargs)


class _VelesTPUModule(sys.modules[__name__].__class__):
    """Callable module (reference ``VelesModule``, ``__init__.py:126``)."""

    def __call__(self, workflow_file, config_file=None, **kwargs):
        return __run__(workflow_file, config_file, **kwargs)


sys.modules[__name__].__class__ = _VelesTPUModule
