"""Kohonen self-organizing map units.

The reference shipped SOM units in the Znicz plugin (absent submodule;
SURVEY §7 build-plan item 10 lists Kohonen as a parity model — it
exercises the reduce + argmin + random op families). TPU design: the
entire SOM step — pairwise distances, best-matching-unit argmin, grid
neighborhood kernel, weight delta — is ONE jitted computation over the
whole minibatch; the classic sample-at-a-time SOM loop would be scalar
poison on the MXU, so the batch variant averages the neighborhood-weighted
deltas of all samples (batch SOM, equivalent in the small-learning-rate
limit).

Units:

- :class:`KohonenForward` — winner (BMU) index per sample;
- :class:`KohonenTrainer` — one batch update with exponentially decayed
  learning rate + neighborhood radius.
"""

import numpy

import jax
import jax.numpy as jnp

from veles_tpu.core.units import Unit
from veles_tpu.core import prng
from veles_tpu.memory import Array
from veles_tpu.nn.jit_unit import JitUnit


def _grid_coords(shape):
    gy, gx = shape
    ys, xs = jnp.meshgrid(jnp.arange(gy), jnp.arange(gx), indexing="ij")
    return jnp.stack([ys.ravel(), xs.ravel()], axis=1).astype(jnp.float32)


@jax.jit
def _bmu(batch, weights):
    """Best-matching unit per sample: argmin over squared distances."""
    # ||x - w||^2 = ||x||^2 - 2 x.w + ||w||^2 ; the x term is constant
    # per-row and cannot change the argmin
    scores = batch @ weights.T - 0.5 * jnp.sum(weights * weights, axis=1)
    return jnp.argmax(scores, axis=1)


class KohonenForward(JitUnit):
    """Winner lookup: output[i] = BMU index of sample i."""

    INPUTS = ("input", "weights")
    OUTPUTS = ("output",)

    def compute(self, batch, weights):
        n = batch.shape[0]
        return _bmu(batch.reshape(n, -1), weights)


class KohonenTrainer(Unit):
    """One batch-SOM update per run (the whole step is one XLA
    computation).

    Attributes: ``shape`` (gy, gx) neuron grid; ``weights`` (gy*gx, D);
    decayed ``sigma`` / ``learning_rate``; ``quantization_error`` — the
    mean distance of samples to their BMU, the SOM convergence metric.
    """

    VIEW_GROUP = "TRAINER"

    def __init__(self, workflow, **kwargs):
        self.shape = tuple(kwargs.pop("shape", (8, 8)))
        self.learning_rate = kwargs.pop("learning_rate", 0.5)
        self.sigma = kwargs.pop("sigma", max(self.shape) / 2.0)
        self.decay = kwargs.pop("decay", 0.05)
        self.prng_key = kwargs.pop("prng_key", "kohonen")
        super().__init__(workflow, **kwargs)
        self.weights = Array()
        self.winners = Array()
        self.quantization_error = None
        self.steps = 0
        self.demand("input")

    def init_unpickled(self):
        super().init_unpickled()
        self._step_jit_ = None

    @property
    def n_neurons(self):
        return self.shape[0] * self.shape[1]

    def initialize(self, **kwargs):
        raw = getattr(self.input, "mem", self.input)
        if raw is None:
            # a clear error here beats an opaque broadcast failure from
            # (n_neurons, 1) weights deep inside the jitted step
            raise ValueError(
                "%s: linked input has no data at initialize time — "
                "initialize the loader first" % self.name)
        batch = numpy.asarray(raw)
        dim = int(numpy.prod(batch.shape[1:]))
        if self.weights.mem is None:
            init = prng.get(self.prng_key).normal(
                0.0, 0.1, size=(self.n_neurons, dim))
            self.weights.reset(init.astype(numpy.float32))
            self.weights.to_device()

    @property
    def _step_jit(self):
        if self._step_jit_ is None:
            coords = _grid_coords(self.shape)

            @jax.jit
            def step(weights, batch, lr, sigma):
                n = batch.shape[0]
                x = batch.reshape(n, -1)
                # MXU expansion of ||x - w||^2 — the broadcasted (B,N,D)
                # difference would be VPU elementwise work and O(B*N*D)
                # intermediate memory
                d2 = (jnp.sum(x * x, axis=1)[:, None]
                      - 2.0 * (x @ weights.T)
                      + jnp.sum(weights * weights, axis=1)[None, :])
                d2 = jnp.maximum(d2, 0.0)
                winners = jnp.argmin(d2, axis=1)
                qerr = jnp.mean(jnp.sqrt(jnp.min(d2, axis=1)))
                # grid-space neighborhood of each sample's winner
                win_xy = coords[winners]  # (B, 2)
                grid_d2 = jnp.sum(
                    (win_xy[:, None, :] - coords[None, :, :]) ** 2, axis=2)
                h = jnp.exp(-grid_d2 / (2.0 * sigma * sigma))  # (B, N)
                # batch update: neighborhood-weighted mean pull
                num = h.T @ x                       # (N, D)
                den = jnp.sum(h, axis=0)[:, None]   # (N, 1)
                target = num / jnp.maximum(den, 1e-8)
                moved = weights + lr * (target - weights)
                active = (den > 1e-8).astype(jnp.float32)
                return weights * (1 - active) + moved * active, \
                    winners, qerr

            self._step_jit_ = step
        return self._step_jit_

    def run(self):
        if isinstance(self.input, Array):
            batch = self.input.data
        else:  # plain ndarray (.data would be its memoryview!)
            batch = jnp.asarray(numpy.asarray(self.input))
        decay = jnp.float32(numpy.exp(-self.decay * self.steps))
        lr = jnp.float32(self.learning_rate) * decay
        sigma = jnp.maximum(jnp.float32(self.sigma) * decay,
                            jnp.float32(0.5))
        new_w, winners, qerr = self._step_jit(
            self.weights.data, batch, lr, sigma)
        self.weights.data = new_w
        self.winners.data = winners
        self.quantization_error = qerr  # lazy device scalar
        self.steps += 1

    # -- results --------------------------------------------------------------
    def get_metric_names(self):
        return ["quantization_error"]

    def get_metric_values(self):
        return [float(self.quantization_error)
                if self.quantization_error is not None else None]
