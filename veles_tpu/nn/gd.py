"""Gradient-descent units: the backward chain.

The Znicz GradientDescent* family (named in ``BASELINE.json``): each GD unit
mirrors one forward unit, consuming ``err_output`` (dL/d output) and the
forward unit's saved ``input``/``output``, producing ``err_input`` for the
next unit down and updating the **shared** weights/bias Array slots in
place. The whole backward step for a layer — activation derivative, weight
gradient GEMM, error back-GEMM, momentum + weight-decay update — is one
jitted computation (the reference launched four separate kernels:
err_y_update, weights_update, bias_update, err_h_update).

Update rule (Znicz GD semantics, ``solver="momentum"``, the default):

    v    ← μ·v − λ·(∇W + Λ₂·W + Λ₁·sign(W))
    W    ← W + v

with learning_rate λ, gradient_moment μ, l2 Λ₂ (``weights_decay``), l1 Λ₁.
Hyperparameters are passed into the jitted function as arrays so they can be
annealed per epoch without retracing.

``solver="adam"`` (additive — the reference had only momentum SGD) keeps
the same regularized gradient and applies the bias-corrected Adam update:

    m ← β₁·m + (1−β₁)·g        s ← β₂·s + (1−β₂)·g²
    W ← W − λ·(m/(1−β₁ᵗ)) / (√(s/(1−β₂ᵗ)) + ε)

The first moment lives in the same ``_velocity_*`` slots (so fleet and
snapshot plumbing is identical); second moments and the shared step
counter are extra Array slots created only when the solver needs them.
The fused engine (``parallel/fused.py``) implements the SAME per-leaf
math, so graph and fused modes stay bit-identical for both solvers.
"""

import jax.numpy as jnp

from veles_tpu.memory import Array
from veles_tpu.nn.jit_unit import JitUnit
from veles_tpu.ops import activations
from veles_tpu.ops.gemm import matmul

SOLVERS = ("momentum", "adam", "adagrad")


def make_updater(solver, hyper, step):
    """The per-leaf update shared by every GD unit (and mirrored by the
    fused engine): ``upd(w, grad, vel, second, rate) -> (new_w, new_vel,
    new_second)``. ``grad`` arrives already regularized (l2/l1 added by
    the caller where the leaf's policy says so). For momentum the second
    moment passes through untouched; ``step`` is the ALREADY incremented
    step count (1-based) for Adam's bias correction (unused by
    adagrad, whose accumulator needs no correction)."""
    if solver == "momentum":
        moment = hyper[4]

        def upd(w, grad, vel, second, rate):
            v2 = moment * vel - rate * grad
            return w + v2, v2, second
        return upd
    if solver == "adagrad":
        eps = hyper[7]

        def upd(w, grad, vel, second, rate):
            s = second + grad * grad
            return w - rate * grad / (jnp.sqrt(s) + eps), vel, s
        return upd
    beta1, beta2, eps = hyper[5], hyper[6], hyper[7]

    def upd(w, grad, vel, second, rate):
        m = beta1 * vel + (1.0 - beta1) * grad
        s = beta2 * second + (1.0 - beta2) * grad * grad
        m_hat = m / (1.0 - beta1 ** step)
        s_hat = s / (1.0 - beta2 ** step)
        return w - rate * m_hat / (jnp.sqrt(s_hat) + eps), m, s
    return upd


def fleet_merge_mode():
    """Validated ``root.common.fleet.merge``. The Launcher checks it at
    startup too — a typo must fail fast, not put every slave into a
    silent drop/reconnect loop when the first update arrives."""
    from veles_tpu.core.config import root
    mode = root.common.fleet.get("merge", "overwrite")
    if mode not in ("overwrite", "average"):
        raise ValueError("unknown fleet merge mode %r (use 'overwrite' "
                         "or 'average')" % mode)
    return mode


class GradientDescent(JitUnit):
    """Backward unit for All2All (linear activation)."""

    ACTIVATION = "linear"
    VIEW_GROUP = "TRAINER"

    INPUTS = ("err_output", "input", "output", "weights", "bias",
              "_velocity_w", "_velocity_b", "_hyper")
    OUTPUTS = ("err_input", "weights", "bias", "_velocity_w", "_velocity_b")

    def __init__(self, workflow, **kwargs):
        self.learning_rate = kwargs.pop("learning_rate", 0.01)
        self.learning_rate_bias = kwargs.pop("learning_rate_bias", None)
        self.weights_decay = kwargs.pop("weights_decay", 0.0)
        self.l1_vs_l2 = kwargs.pop("l1_vs_l2", 0.0)
        self.gradient_moment = kwargs.pop("gradient_moment", 0.0)
        self.include_bias = kwargs.pop("include_bias", True)
        self.solver = kwargs.pop("solver", "momentum")
        if self.solver not in SOLVERS:
            raise ValueError("unknown solver %r (use %s)"
                             % (self.solver, "/".join(SOLVERS)))
        self.adam_beta1 = kwargs.pop("adam_beta1", 0.9)
        self.adam_beta2 = kwargs.pop("adam_beta2", 0.999)
        self.adam_epsilon = kwargs.pop("adam_epsilon", 1e-8)
        super().__init__(workflow, **kwargs)
        if self.solver != "momentum":
            # second moments + shared step count, as extra traced slots:
            # instance INPUTS/OUTPUTS extend the class tuples (jit_unit
            # and the partial-fusion planner read self.INPUTS)
            self._second_slots_ = tuple(
                vel.replace("_velocity", "_second")
                for vel in type(self).INPUTS if "_velocity" in vel)
            for name in self._second_slots_:
                setattr(self, name, Array())
            self._step = Array()
            extra = self._second_slots_ + ("_step",)
            base_in = type(self).INPUTS
            self.INPUTS = base_in[:-1] + extra + base_in[-1:]
            self.OUTPUTS = type(self).OUTPUTS + extra
        # linked from the paired forward unit:
        self.input = None
        self.output = None
        self.weights = None
        self.bias = None
        # linked from the next unit up (evaluator or deeper GD):
        self.err_output = None
        self.demand("err_output", "input", "output", "weights", "bias")
        self._velocity_w = Array()
        self._velocity_b = Array()
        self._hyper = Array()

    def link_forward(self, forward_unit, err_source):
        """Wire this GD unit to its forward twin + the error source
        (convenience mirroring how Znicz models assemble the chain)."""
        self.link_attrs(forward_unit, "input", "output", "weights", "bias")
        link_err_output(self, err_source)
        return self

    def initialize(self, **kwargs):
        if self.weights is None or self.weights.data is None:
            return True
        if self._velocity_w.data is None:
            self._velocity_w.data = jnp.zeros_like(self.weights.data)
            self._velocity_b.data = jnp.zeros_like(self.bias.data)
        self._init_solver_state()
        self._refresh_hyper()

    def _init_solver_state(self):
        """Zero the adam/adagrad second moments (shaped like their
        velocities) and the step counter; no-op for momentum."""
        if self.solver == "momentum":
            return
        for name in self._second_slots_:
            slot = getattr(self, name)
            if slot.data is None:
                vel = getattr(self, name.replace("_second", "_velocity"))
                slot.data = jnp.zeros_like(vel.data)
        if self._step.data is None:
            self._step.data = jnp.zeros((), jnp.float32)

    def _unpack_solver(self, rest, n_leaves=2):
        """Split a compute()'s trailing args into (updater, hyper,
        seconds, extra_outputs_fn) — the ONE place that knows the
        positional layout. Momentum: rest == (hyper,), seconds are
        Nones. Adam/adagrad: rest == (*seconds, step, hyper) with the
        step pre-incremented here."""
        if self.solver != "momentum":
            *seconds, step, hyper = rest
            step = step + 1.0
            return (make_updater(self.solver, hyper, step), hyper,
                    tuple(seconds),
                    lambda new_seconds: tuple(new_seconds) + (step,))
        (hyper,) = rest
        return (make_updater("momentum", hyper, None), hyper,
                (None,) * n_leaves, lambda new_seconds: ())

    def _refresh_hyper(self):
        lr_bias = (self.learning_rate_bias
                   if self.learning_rate_bias is not None
                   else self.learning_rate)
        self._hyper.data = jnp.asarray(
            [self.learning_rate, lr_bias, self.weights_decay,
             self.l1_vs_l2, self.gradient_moment, self.adam_beta1,
             self.adam_beta2, self.adam_epsilon], jnp.float32)

    def set_learning_rate(self, value):
        """Anneal without retracing (hyper is a traced input)."""
        self.learning_rate = value
        self._refresh_hyper()

    def scale_learning_rate(self, factor):
        """Multiply BOTH rates (weights and bias) — the plateau-decay
        entry point; one hyper refresh, no retrace."""
        self.learning_rate *= factor
        if self.learning_rate_bias is not None:
            self.learning_rate_bias *= factor
        self._refresh_hyper()

    def compute(self, err_output, x, y, weights, bias, vel_w, vel_b,
                *rest):
        upd, hyper, (sec_w, sec_b), extras = self._unpack_solver(rest)
        lr, lr_b, l2, l1 = hyper[0], hyper[1], hyper[2], hyper[3]
        _, deriv = activations.ACTIVATIONS[self.ACTIVATION]
        err_pre = (err_output.reshape(err_output.shape[0], -1)
                   * deriv(y.reshape(y.shape[0], -1)))
        x2 = x.reshape(x.shape[0], -1)
        grad_w = matmul(x2.T, err_pre, out_dtype=jnp.float32)
        grad_w = grad_w + l2 * weights + l1 * jnp.sign(weights)
        err_input = matmul(err_pre, weights.T,
                           out_dtype=jnp.float32).reshape(x.shape)
        grad_b = jnp.sum(err_pre, axis=0)
        new_w, new_vel_w, new_sec_w = upd(weights, grad_w, vel_w, sec_w,
                                          lr)
        new_b, new_vel_b, new_sec_b = upd(bias, grad_b, vel_b, sec_b,
                                          lr_b)
        return (err_input, new_w, new_b, new_vel_w, new_vel_b) \
            + extras((new_sec_w, new_sec_b))

    # fleet-mode DP: slaves ship their weight deltas; the master merges.
    # (Pod-mode DP instead all-reduces gradients inside the tick — see
    # veles_tpu/parallel/.)
    def _param_attrs(self):
        """Trainable parameter slots, derived from the unit's I/O
        contract (attrs in both INPUTS and OUTPUTS that are not solver
        state or the error lanes) — so subclasses with extra leaves
        (GDSelfAttention's out projection) ship them in fleet payloads
        automatically instead of silently desynchronizing."""
        return [name for name in self.OUTPUTS
                if name in self.INPUTS and not name.startswith("_")
                and name != "err_input"]

    def _solver_state_attrs(self):
        """Fleet-payload policy for optimizer state: momentum
        velocities stay slave-local (reference Znicz parity — its wire
        never carried them); the ADDITIVE stateful solvers
        (adam/adagrad) ship first+second moments and the step count so
        (a) the master's canonical state is resumable — a snapshot of a
        fleet Adam run restarts with real moments — and (b) a respawned
        slave continues instead of restarting from zeroed moments. See
        docs/distributed.md."""
        if self.solver == "momentum":
            return []
        return [n for n in self.OUTPUTS if n.startswith("_velocity")] \
            + list(self._second_slots_) + ["_step"]

    @staticmethod
    def _control_plane():
        from veles_tpu.fleet import fleet_control_plane
        return fleet_control_plane()

    @property
    def negotiates_on_connect(self):
        """Control-plane fleet (docs/compiler_fleet.md): initial
        weights travel ONCE in the handshake instead of riding every
        job, so the per-job wire can stay weight-free. Data plane keeps
        the reference behavior (no handshake exchange — weights ride
        the first job payload)."""
        return self._control_plane()

    def _state_payload(self):
        """Full distributable state: params + (stateful-solver) moments
        — the body shared by the data-plane update payload, the
        control-plane handshake and the epoch-fence sync."""
        data = {attr: getattr(self, attr).mem
                for attr in self._param_attrs()}
        for attr in self._solver_state_attrs():
            if getattr(self, attr).data is not None:
                data[attr] = getattr(self, attr).mem
        return data

    def generate_data_for_master(self):
        if self._control_plane():
            # control plane: per-job updates carry NO weight payload —
            # the gradient merge happened in-program on the slave's
            # mesh; the scalar metrics ride the Decision's payload
            return None
        return self._state_payload()

    def apply_data_from_slave(self, data, slave=None):
        """Merge a slave's trained weights into master state.

        Modes (``root.common.fleet.merge``):

        - ``overwrite`` (default) — reference Znicz parity: master state
          replaced by the slave's result (asynchronous DP,
          last-writer-wins, stale updates accepted);
        - ``average`` — master keeps the mean of its current state and
          the slave's: N slaves pushing divergent updates blend instead
          of thrashing, an EASGD-flavored option the reference lacked.

        Solver moments (stateful solvers only) are always OVERWRITTEN —
        they are running estimates, and averaging a second moment
        against a stale one has no useful semantics.
        """
        mode = fleet_merge_mode()
        for attr in self._param_attrs():
            if attr not in data:
                continue
            slot = getattr(self, attr)
            value = jnp.asarray(data[attr])
            if mode == "average" and slot.data is not None:
                # device-resident math: .mem here would serialize two
                # PCIe round-trips per layer per update under the
                # server's lock
                value = (slot.data + value) * 0.5
            slot.data = value
        for attr in self._solver_state_attrs():
            if attr in data:
                getattr(self, attr).data = jnp.asarray(data[attr])

    def generate_data_for_slave(self, slave=None):
        # the rates ride every job so master-side annealing (plateau
        # lr_decay, set_learning_rate) reaches the slaves that execute
        # the actual GD ticks
        if self._control_plane():
            # control plane: jobs are batch assignments + hypers only;
            # weights traveled once in the handshake and live on the
            # slave's devices between epoch fences
            return {"lr": self.learning_rate,
                    "lr_bias": self.learning_rate_bias}
        data = self._state_payload()
        data["lr"] = self.learning_rate
        data["lr_bias"] = self.learning_rate_bias
        return data

    def generate_handshake_data(self, slave=None):
        """Control-plane handshake: the FULL state (weights + solver
        moments + rates), shipped once at connect so a joining slave
        adopts the master's canonical params without per-job weight
        frames. (Only reached in control-plane mode — see
        ``negotiates_on_connect``.)"""
        data = self._state_payload()
        data["lr"] = self.learning_rate
        data["lr_bias"] = self.learning_rate_bias
        return data

    def generate_sync_for_master(self):
        """The epoch-fence bulk sync payload (control plane): current
        weights + solver moments, read from the unit Arrays the fused
        tick wrote at the fence."""
        return self._state_payload()

    def apply_sync_from_slave(self, data, slave=None):
        """Fence sync application: OVERWRITE — between fences the
        slave's in-program replica is the canonical state, so there is
        nothing meaningful to merge (the data-plane merge modes apply
        to per-job host aggregation only)."""
        for attr in self._param_attrs() + self._solver_state_attrs():
            if attr in data:
                getattr(self, attr).data = jnp.asarray(data[attr])

    def apply_data_from_master(self, data):
        for attr in self._param_attrs() + self._solver_state_attrs():
            if attr in data:
                getattr(self, attr).data = jnp.asarray(data[attr])
        if "lr" in data and (data["lr"] != self.learning_rate
                             or data["lr_bias"]
                             != self.learning_rate_bias):
            self.learning_rate = data["lr"]
            self.learning_rate_bias = data["lr_bias"]
            self._refresh_hyper()


def link_err_output(gd_unit, err_source):
    """Wire ``gd_unit.err_output`` to the upstream error: a backward unit
    exposes ``err_input``, an evaluator exposes ``err_output``."""
    if hasattr(err_source, "err_input"):
        gd_unit.link_attrs(err_source, ("err_output", "err_input"))
    else:
        gd_unit.link_attrs(err_source, "err_output")
    return gd_unit


class GDTanh(GradientDescent):
    ACTIVATION = "tanh"


class GDRELU(GradientDescent):
    ACTIVATION = "relu"


class GDStrictRELU(GradientDescent):
    ACTIVATION = "strict_relu"


class GDSigmoid(GradientDescent):
    ACTIVATION = "sigmoid"


class GDSoftmax(GradientDescent):
    """Backward for All2AllSoftmax: the evaluator's err_output is already
    d(loss)/d(logits) (softmax folded into the cross-entropy gradient), so
    the activation derivative is identity."""
    ACTIVATION = "linear"
