"""Pooling units (Znicz MaxPooling/AvgPooling + their GD twins).

``lax.reduce_window`` forward; ``jax.vjp`` backward (max-pooling's adjoint
is the winner-scatter the reference implemented as a dedicated kernel with
an offset buffer — vjp recovers exactly that, fused).
"""

import jax
import jax.numpy as jnp
from jax import lax

from veles_tpu.memory import Array
from veles_tpu.nn.jit_unit import ForwardUnit
from veles_tpu.core.units import Unit


class Pooling(ForwardUnit):
    """Base pooling over NHWC, window (ky, kx), stride = sliding."""

    INPUTS = ("input",)
    OUTPUTS = ("output",)

    def __init__(self, workflow, kx=2, ky=2, sliding=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.kx, self.ky = kx, ky
        self.sliding = tuple(sliding) if sliding else (ky, kx)
        self.input = None

    def initialize(self, **kwargs):
        if self.input is None or (isinstance(self.input, Array)
                                  and self.input.data is None):
            return True
        if self.output.data is None:
            shape = jax.eval_shape(
                self._pool, jax.ShapeDtypeStruct(self.input.shape,
                                                 jnp.float32)).shape
            self.output.data = jnp.zeros(shape, jnp.float32)

    def _window(self):
        return ((1, self.ky, self.kx, 1), (1,) + self.sliding + (1,))

    def _pool(self, x):
        raise NotImplementedError

    def compute(self, x):
        return self._pool(x)


class MaxPooling(Pooling):
    def _pool(self, x):
        window, strides = self._window()
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                 "VALID")


class AvgPooling(Pooling):
    def _pool(self, x):
        window, strides = self._window()
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides,
                                   "VALID")
        return summed / (self.kx * self.ky)


class MaxAbsPooling(Pooling):
    """Znicz's max-by-absolute-value pooling variant.

    Built from the two DIFFERENTIABLE reduce_windows (max and min) — a
    custom absmax reducer has no reverse-mode rule, and :class:`GDPooling`
    backprops through ``jax.vjp(self._pool)``. Tie-break (+x vs -x in one
    window) deterministically prefers the positive value; the fused engine
    (``parallel/fused.py``) uses the identical expression, so fused and
    graph modes match bit-for-bit."""

    def _pool(self, x):
        window, strides = self._window()
        mx = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                               "VALID")
        mn = lax.reduce_window(x, jnp.inf, lax.min, window, strides,
                               "VALID")
        return jnp.where(jnp.abs(mx) >= jnp.abs(mn), mx, mn)


class GDPooling(Unit):
    """Backward for any Pooling: routes err_output back through the
    pooling's vjp. No parameters — just error propagation."""

    VIEW_GROUP = "TRAINER"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.forward_unit = None
        self.err_output = None
        self.input = None
        self.err_input = Array()
        self.demand("err_output", "input")

    def link_pooling(self, pooling_unit, err_source):
        from veles_tpu.nn.gd import link_err_output
        self.forward_unit = pooling_unit
        self.link_attrs(pooling_unit, "input")
        link_err_output(self, err_source)
        return self

    def init_unpickled(self):
        super().init_unpickled()
        self._jitted_ = None

    def run(self):
        if self._jitted_ is None:
            def backward(x, err_out):
                _, vjp = jax.vjp(self.forward_unit._pool, x)
                return vjp(err_out)[0]
            self._jitted_ = jax.jit(backward)
        self.err_input.data = self._jitted_(self.input.data,
                                            self.err_output.data)
