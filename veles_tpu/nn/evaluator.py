"""Evaluator units: loss, error signal, and metrics.

The Znicz EvaluatorSoftmax/EvaluatorMSE contract: consume the last forward
unit's ``output`` plus the loader's ``minibatch_labels``/``targets``, emit
``err_output`` for the gradient chain and metric accumulators the Decision
unit reads at epoch boundaries.

TPU design notes:

- the softmax + cross-entropy + gradient are one fused jitted computation
  over logits (All2AllSoftmax emits logits — see its docstring);
- a 0/1 ``sample_mask`` handles short final minibatches under jit's static
  shapes (the reference instead re-filled the tail with previous samples);
- metric values stay on device; ``n_err`` etc. are read to host only when
  the Decision unit asks at epoch end.
"""

import jax.numpy as jnp

from veles_tpu.memory import Array
from veles_tpu.nn.jit_unit import JitUnit
from veles_tpu.ops import losses


class EvaluatorBase(JitUnit):

    hide_from_registry = True
    VIEW_GROUP = "EVALUATOR"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.input = None          # forward output (linked)
        self.sample_mask = None    # loader-provided validity mask (linked)
        self.demand("input")


class EvaluatorSoftmax(EvaluatorBase):
    """Softmax cross-entropy evaluator (Znicz EvaluatorSoftmax)."""

    INPUTS = ("input", "labels", "sample_mask")
    OUTPUTS = ("err_output", "loss", "n_err", "max_err_output_sum",
               "confusion_matrix")

    def __init__(self, workflow, **kwargs):
        self.compute_confusion = kwargs.pop("compute_confusion", True)
        super().__init__(workflow, **kwargs)
        self.labels = None  # linked from loader.minibatch_labels
        self.demand("labels")

    def compute(self, logits, labels, mask):
        n_classes = logits.shape[-1]
        valid = jnp.maximum(jnp.sum(mask), 1.0)
        err, loss_sum, n_err, _ = losses.masked_softmax_xent(
            logits, labels, mask, valid)
        max_err = jnp.max(jnp.abs(err))
        if self.compute_confusion:
            cm = losses.confusion_matrix(logits, labels, n_classes, mask)
        else:
            cm = jnp.zeros((n_classes, n_classes), jnp.int32)
        return err, loss_sum / valid, n_err, max_err, cm


class EvaluatorMSE(EvaluatorBase):
    """Mean-squared-error evaluator (Znicz EvaluatorMSE)."""

    INPUTS = ("input", "target", "sample_mask")
    OUTPUTS = ("err_output", "loss", "max_err")

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.target = None  # linked from loader.minibatch_targets
        self.demand("target")

    def compute(self, output, target, mask):
        valid = jnp.maximum(jnp.sum(mask), 1.0)
        err, loss_sum, max_err = losses.masked_mse(output, target, mask,
                                                   valid)
        return err, loss_sum / valid, max_err
