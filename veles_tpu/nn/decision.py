"""DecisionGD: epoch accounting and stop decisions.

The Znicz Decision unit watches the loader's epoch flags and the evaluator's
metrics, accumulates per-class error counts, decides whether the validation
error improved, remembers the best snapshot point, and raises
``complete`` when training should stop (max epochs reached or no
improvement for ``fail_iterations`` epochs).

Host-side by design: it runs once per minibatch but does only flag checks;
device metric reads happen at epoch boundaries (one small transfer per
epoch). Its ``improved``/``snapshot_suffix``/``complete`` outputs gate the
Snapshotter and the Repeater loop exactly as in the reference workflows.
"""

from veles_tpu.core.mutable import Bool
from veles_tpu.core.units import Unit
from veles_tpu.loader.base import CLASS_NAMES, TEST, TRAIN, VALID


class DecisionGD(Unit):
    """Training-loop decision unit (the Znicz Decision contract)."""

    VIEW_GROUP = "TRAINER"

    def __init__(self, workflow, **kwargs):
        self.max_epochs = kwargs.pop("max_epochs", None)
        self.fail_iterations = kwargs.pop("fail_iterations", 100)
        # plateau annealing: factor in (0, 1), applied to every GD unit
        # after each `lr_decay_patience` epochs without improvement
        self.lr_decay = kwargs.pop("lr_decay", None)
        self.lr_decay_patience = kwargs.pop("lr_decay_patience", 5)
        if self.lr_decay is not None \
                and not 0.0 < self.lr_decay < 1.0:
            raise ValueError("lr_decay must be in (0, 1), got %r"
                             % (self.lr_decay,))
        if self.lr_decay is not None and self.lr_decay_patience < 1:
            raise ValueError("lr_decay_patience must be >= 1, got %r"
                             % (self.lr_decay_patience,))
        super().__init__(workflow, **kwargs)
        # linked from the loader:
        self.loader = None
        # linked from the evaluator (device scalars, read at epoch end):
        self.evaluator = None
        self.demand("loader", "evaluator")
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.train_ended = Bool(False)
        self.epoch_ended = Bool(False)
        # gate for the GD chain: True on non-train minibatches so the
        # backward units gate_skip (run nothing, still propagate the tick)
        self.gd_skipped = Bool(False)
        # accumulated per-class stats, indexed TEST/VALID/TRAIN:
        self.epoch_n_err = [0, 0, 0]
        self.epoch_samples = [0, 0, 0]
        self.epoch_loss = [0.0, 0.0, 0.0]
        self.best_n_err = [None, None, None]
        self.best_epoch = 0
        self.snapshot_suffix = ""
        # frozen copies of the LAST finished epoch (plotter/publisher feed)
        self.last_epoch_n_err = [0, 0, 0]
        self.last_epoch_samples = [0, 0, 0]
        self.last_epoch_loss = [0.0, 0.0, 0.0]
        self.last_epoch_confusion = None
        self._epoch_confusion = None
        self._epochs_without_improvement = 0
        self._epochs_done = 0
        # sweep serving: classes whose sweep finished but whose
        # accumulators are still lazy device values (materialized in one
        # batched transfer at the epoch boundary)
        self._pending_classes = []
        # (the volatile per-tick accumulators — _acc_jit_, _dev_acc_,
        # _dev_confusion_ — are created in init_unpickled, which
        # Pickleable.__init__ already ran)
        # pipelined fused mode: materialize each epoch's metrics this
        # many epochs LATE — by then the device has finished computing
        # them, so the batched read never stalls the dispatch pipeline.
        # 0 = read at the epoch's own boundary (the default)
        self.pipeline_depth = 0
        self._lagged_epochs_ = []

    def link_from_workflow(self, loader, evaluator):
        self.loader = loader
        self.evaluator = evaluator
        return self

    def initialize(self, **kwargs):
        if self.loader is None or self.evaluator is None:
            return True

    def run(self):
        self.improved.unset()
        self.epoch_ended.unset()
        klass = self.loader.minibatch_class
        self.gd_skipped.set(klass != TRAIN)
        if self.is_slave:
            # epoch accounting lives on the master (fed by update payloads
            # via apply_data_from_slave); the slave just executes its job
            return
        # accumulate metrics as LAZY device scalars — a host read here would
        # block the async XLA dispatch pipeline every minibatch; conversion
        # to Python numbers happens only at class/epoch boundaries
        size = int(self.loader.minibatch_valid_size)
        # MSE evaluators publish no n_err — the error count stays 0 and
        # improvement tracks the loss metric (DecisionMSE._metric)
        n_err_slot = getattr(self.evaluator, "n_err", None)
        sweep = getattr(self.loader, "sweep_serving", False)
        self.epoch_samples[klass] += size
        cm_data = None
        if klass == VALID:
            cm = getattr(self.evaluator, "confusion_matrix", None)
            cm_data = getattr(cm, "data", None)
        if sweep:
            # one tick per class sweep: device-side accumulate is one
            # cheap lazy op and the values ride the epoch pipeline
            if n_err_slot is not None:
                self.epoch_n_err[klass] = (self.epoch_n_err[klass]
                                           + n_err_slot.data)
            self.epoch_loss[klass] = (self.epoch_loss[klass]
                                      + self.evaluator.loss.data * size)
            if cm_data is not None:
                self._epoch_confusion = (cm_data
                                         if self._epoch_confusion is None
                                         else self._epoch_confusion
                                         + cm_data)
        else:
            # per-minibatch serving (graph / partial fusion): exactly ONE
            # jitted dispatch on the tick path — the 3-6 separate eager
            # accumulate ops this used to run cost ~30 ms/tick through a
            # tunneled runtime (each eager op is its own dispatch), the
            # dominant graph-mode cost. The fused accumulator keeps the
            # running sums on device; ONE device_get settles them at the
            # class boundary.
            if self._acc_jit_ is None:
                import jax

                @jax.jit
                def acc_fn(n_err_acc, loss_acc, n_err, loss, size):
                    return n_err_acc + n_err, loss_acc + loss * size

                @jax.jit
                def acc_cm_fn(n_err_acc, loss_acc, cm_acc,
                              n_err, loss, size, cm):
                    return (n_err_acc + n_err, loss_acc + loss * size,
                            cm_acc + cm)
                self._acc_jit_ = (acc_fn, acc_cm_fn)
            import jax.numpy as jnp
            if self._dev_acc_[klass] is None:
                self._dev_acc_[klass] = (jnp.zeros((), jnp.int32),
                                         jnp.zeros((), jnp.float32))
            n_err_acc, loss_acc = self._dev_acc_[klass]
            n_err_val = (n_err_slot.data if n_err_slot is not None
                         else 0)
            if cm_data is not None:
                if self._dev_confusion_ is None:
                    self._dev_confusion_ = jnp.zeros_like(cm_data)
                n_err_acc, loss_acc, self._dev_confusion_ = \
                    self._acc_jit_[1](
                        n_err_acc, loss_acc, self._dev_confusion_,
                        n_err_val, self.evaluator.loss.data, size,
                        cm_data)
            else:
                n_err_acc, loss_acc = self._acc_jit_[0](
                    n_err_acc, loss_acc, n_err_val,
                    self.evaluator.loss.data, size)
            self._dev_acc_[klass] = (n_err_acc, loss_acc)
        if not self.loader.epoch_ended_for_class:
            return
        if sweep:
            # sweep mode: a host read here would block on the in-flight
            # sweep once per class — a full device round trip each (the
            # dominant per-epoch cost on a tunneled TPU). Defer ALL
            # materialization to the epoch boundary and fetch every
            # accumulator in ONE batched transfer instead (and, in
            # pipelined mode, a further ``pipeline_depth`` epochs late).
            self._pending_classes.append(klass)
            if self.loader.epoch_ended:
                self._queue_epoch()
                self._drain_epochs()
            return
        # one sample class finished: settle the device accumulators in
        # ONE batched transfer
        import jax
        if self._dev_acc_[klass] is not None:
            n_err, loss = jax.device_get(self._dev_acc_[klass])
            self._dev_acc_[klass] = None
            self.epoch_n_err[klass] += int(n_err)
            self.epoch_loss[klass] += float(loss)
        if klass == VALID and self._dev_confusion_ is not None:
            total = jax.device_get(self._dev_confusion_)
            self._dev_confusion_ = None
            self._epoch_confusion = (
                total if self._epoch_confusion is None
                else self._epoch_confusion + total)
        self._on_class_ended(klass)
        if self.loader.epoch_ended:
            self._on_epoch_ended()

    def _queue_epoch(self):
        """Park the finished epoch's (still-lazy) accumulators and reset
        the live ones for the next epoch."""
        entry = {
            "n_err": self.epoch_n_err, "loss": self.epoch_loss,
            "samples": self.epoch_samples,
            "confusion": self._epoch_confusion,
            "classes": self._pending_classes,
        }
        if self.pipeline_depth:
            # start the device->host copies NOW: they complete during
            # the next epoch's compute, so the lagged materialization
            # pays neither the compute wait nor the transfer round trip
            for value in (*entry["n_err"], *entry["loss"],
                          entry["confusion"]):
                if hasattr(value, "copy_to_host_async"):
                    value.copy_to_host_async()
        self._lagged_epochs_.append(entry)
        self.epoch_n_err = [0, 0, 0]
        self.epoch_loss = [0.0, 0.0, 0.0]
        self.epoch_samples = [0, 0, 0]
        self._epoch_confusion = None
        self._pending_classes = []

    def _drain_epochs(self):
        """Materialize queued epochs down to ``pipeline_depth`` — or ALL
        of them when the serving side has reached ``max_epochs`` (an
        exact stop: nothing speculative is in flight then). A lagged
        no-improvement stop drops the younger, speculatively-trained
        epochs and rolls the fused params back, making the run's outputs
        identical to the unpipelined ones."""
        served = self._epochs_done + len(self._lagged_epochs_)
        drain_all = (self.max_epochs is not None
                     and served >= self.max_epochs)
        # whichever engine owns the pipelined params history (the fused
        # tick or the sweep tier) gets the advance/rollback hooks
        tick = (getattr(self.workflow, "fused_tick", None)
                or getattr(self.workflow, "sweep_unit", None))
        first = True
        while self._lagged_epochs_ and (
                drain_all
                or len(self._lagged_epochs_) > self.pipeline_depth):
            entry = self._lagged_epochs_.pop(0)
            if not first and tick is not None:
                # two epochs materialize on this tick but the tick's
                # one-slot params history rotated only once: if the
                # SECOND epoch is about to take 'improved' (peek its
                # prefetched valid error), advance the unit Arrays to
                # the params it evaluated so a snapshot-on-improved
                # stays exact; if not, leave them on the older epoch's
                # evaluated state — the improvement that stands
                if self._is_improvement(VALID, self._peek_metric(entry)):
                    tick.advance_eval_params()
            first = False
            self._materialize_entry(entry)
            if self.complete and self._lagged_epochs_:
                dropped = len(self._lagged_epochs_)
                self._lagged_epochs_ = []
                if tick is not None:
                    tick.rollback_speculative()
                self.info("dropped %d speculative epoch(s) after the "
                          "lagged stop decision", dropped)
                break

    def _materialize_entry(self, entry):
        """One batched device->host transfer for one epoch's
        accumulators (error counts, loss sums, confusion), then the
        class summaries in serving order and the epoch summary."""
        import jax
        n_errs, losses, cm = jax.device_get(
            (entry["n_err"], entry["loss"], entry["confusion"]))
        self.epoch_n_err = [int(v) for v in n_errs]
        self.epoch_loss = [float(v) for v in losses]
        self.epoch_samples = list(entry["samples"])
        self._epoch_confusion = cm
        for klass in entry["classes"]:
            self._on_class_ended(klass)
        self._on_epoch_ended()

    # -- epoch boundary logic -------------------------------------------------
    def _metric(self, n_err, samples, loss_sum):
        """The tracked improvement metric for one class sweep: the error
        COUNT here, the average loss in DecisionMSE. Smaller is better
        in both."""
        return n_err

    def _peek_metric(self, entry):
        """The VALID metric of a still-lazy epoch entry (the pipelined
        drain's advance-peek)."""
        import jax
        return int(jax.device_get(entry["n_err"][VALID]))

    def _improvement_suffix(self, metric, n_err, samples):
        return "validation_%.2fpt" % (100.0 * n_err / max(samples, 1))

    def _class_summary(self, klass, n_err, samples, loss_sum, epoch):
        """One sample-class sweep of one epoch finished."""
        samples = max(samples, 1)
        error_pct = 100.0 * n_err / samples
        self.info(
            "epoch %d %s: errors %d/%d (%.2f%%) avg loss %.6f",
            epoch, CLASS_NAMES[klass], n_err, samples, error_pct,
            loss_sum / samples)
        if klass == VALID:
            metric = self._metric(n_err, samples, loss_sum)
            self._track_improvement(
                VALID, metric, epoch,
                self._improvement_suffix(metric, n_err, samples))

    def _is_improvement(self, klass, metric):
        """THE improvement predicate — _track_improvement and the
        pipelined drain's advance-peek must never diverge."""
        best = self.best_n_err[klass]
        return best is None or metric < best

    def _track_improvement(self, klass, metric, epoch, suffix):
        if self._is_improvement(klass, metric):
            self.best_n_err[klass] = metric
            self.best_epoch = epoch
            self.improved.set()
            self._epochs_without_improvement = 0
            self.snapshot_suffix = suffix
        else:
            self._epochs_without_improvement += 1
            self._maybe_decay_lr()

    def _maybe_decay_lr(self):
        """Plateau annealing (the Znicz lr-adjuster role, additive knob):
        with ``lr_decay`` set, every ``lr_decay_patience`` epochs without
        improvement multiply each GD unit's learning rate by the factor.
        Works in every execution mode — ``scale_learning_rate`` refreshes
        the traced hyper vector (no retrace, gd.py contract), and in
        fleet mode the decayed rates ride the next job payloads to the
        slaves (``GradientDescent.generate_data_for_slave``)."""
        if not self.lr_decay:
            return
        if self._epochs_without_improvement % self.lr_decay_patience:
            return
        workflow = self.workflow
        gds = [gd for gd in getattr(workflow, "gds", [])
               if gd is not None and hasattr(gd, "scale_learning_rate")]
        for gd in gds:
            gd.scale_learning_rate(self.lr_decay)
        lrs = sorted({round(gd.learning_rate, 10) for gd in gds})
        self.info("no improvement for %d epochs: learning rate decayed "
                  "x%g (now %s)", self._epochs_without_improvement,
                  self.lr_decay, lrs)

    @property
    def epochs_done(self):
        """Completed-epoch count (the published 'epochs' metric)."""
        return self._epochs_done

    def _epoch_summary(self, stats, epoch):
        """All classes of ``epoch`` accounted: decide whether to stop.
        ``stats[klass]`` is (n_err, samples, loss_sum)."""
        # STABLE per-epoch snapshots for side-band consumers (plotters,
        # publishers): the live accumulators are zeroed right after this
        # — and this method is reached by BOTH the standalone and the
        # fleet epoch-bucket paths
        self.last_epoch_n_err = [s[0] for s in stats]
        self.last_epoch_samples = [s[1] for s in stats]
        self.last_epoch_loss = [s[2] for s in stats]
        self.epoch_ended.set()
        self._epochs_done += 1
        # when there is no validation set, improvement tracks train error
        if stats[VALID][1] == 0 and stats[TRAIN][1] > 0:
            n_err, samples, loss_sum = stats[TRAIN]
            self._track_improvement(
                TRAIN, self._metric(n_err, samples, loss_sum), epoch,
                "train_%.2fpt" % (100.0 * n_err / max(samples, 1)))
        stop = False
        if self.max_epochs is not None \
                and self._epochs_done >= self.max_epochs:
            self.info("stopping: reached max_epochs=%d", self.max_epochs)
            stop = True
        if self._epochs_without_improvement >= self.fail_iterations:
            self.info("stopping: no improvement for %d epochs",
                      self.fail_iterations)
            stop = True
        if stop:
            self.complete.set()
            self.train_ended.set()

    def _on_class_ended(self, klass):
        self._class_summary(klass, self.epoch_n_err[klass],
                            self.epoch_samples[klass],
                            self.epoch_loss[klass], self._epochs_done)

    def _on_epoch_ended(self):
        stats = [(self.epoch_n_err[k], self.epoch_samples[k],
                  self.epoch_loss[k]) for k in (TEST, VALID, TRAIN)]
        self._epoch_summary(stats, self._epochs_done)
        if self._epoch_confusion is not None:
            import numpy
            self.last_epoch_confusion = numpy.asarray(
                self._epoch_confusion)
            self._epoch_confusion = None
        for klass in (TEST, VALID, TRAIN):
            self.epoch_n_err[klass] = 0
            self.epoch_samples[klass] = 0
            self.epoch_loss[klass] = 0.0

    # -- fleet-mode distribution ---------------------------------------------
    # The slave reports its job's metrics tagged with the serving epoch; the
    # master buckets them PER EPOCH, because with >=2 slaves (or async
    # pipelining) next-epoch updates arrive before the current epoch's last
    # ones — flat accumulators would re-fire class boundaries and drop
    # samples at the reset (the Znicz Decision's distributed contract).
    def generate_data_for_master(self):
        if not self.is_slave:
            return None
        return {
            "klass": self.loader.minibatch_class,
            "epoch": self.loader.minibatch_epoch,
            "valid": int(self.loader.minibatch_valid_size),
            "n_err": (int(self.evaluator.n_err.data)
                      if getattr(self.evaluator, "n_err", None)
                      is not None else 0),
            "loss": float(self.evaluator.loss.data),
        }

    def init_unpickled(self):
        super().init_unpickled()
        if not hasattr(self, "_epoch_buckets"):
            self._epoch_buckets = {}
        if not hasattr(self, "_pending_classes"):
            self._pending_classes = []
        if not hasattr(self, "pipeline_depth"):
            self.pipeline_depth = 0
        if not hasattr(self, "lr_decay"):  # pre-knob snapshots
            self.lr_decay = None
            self.lr_decay_patience = 5
        self._lagged_epochs_ = []
        self._acc_jit_ = None
        self._dev_acc_ = [None, None, None]
        self._dev_confusion_ = None

    def apply_data_from_slave(self, data, slave=None):
        klass = data["klass"]
        epoch = data.get("epoch", 0)
        bucket = self._epoch_buckets.setdefault(
            epoch, {"stats": [[0, 0, 0.0] for _ in range(3)],
                    "fired": set()})
        entry = bucket["stats"][klass]
        entry[0] += data["n_err"]
        entry[1] += data["valid"]
        entry[2] += data["loss"] * data["valid"]
        lengths = self.loader.effective_class_lengths
        if klass not in bucket["fired"] \
                and 0 < lengths[klass] <= entry[1]:
            bucket["fired"].add(klass)
            self._class_summary(klass, entry[0], entry[1], entry[2], epoch)
            if all(bucket["stats"][k][1] >= lengths[k]
                   for k in (TEST, VALID, TRAIN) if lengths[k]):
                stats = [tuple(s) for s in bucket["stats"]]
                del self._epoch_buckets[epoch]
                self._epoch_summary(stats, epoch)

    # -- results (IResultProvider) -------------------------------------------
    def get_metric_names(self):
        return ["best_validation_errors", "best_epoch", "epochs"]

    def get_metric_values(self):
        return [self.best_n_err[VALID] if self.best_n_err[VALID] is not None
                else self.best_n_err[TRAIN],
                self.best_epoch, self._epochs_done]


class DecisionMSE(DecisionGD):
    """Decision for regression workflows: improvement tracks the minimum
    validation MSE instead of the error count (the Znicz DecisionMSE
    role — its ``minimum_mse``/``min_validation_mse`` contract). Works
    with :class:`~veles_tpu.nn.evaluator.EvaluatorMSE`, which publishes
    ``loss``/``max_err`` but no ``n_err``."""

    def _metric(self, n_err, samples, loss_sum):
        return loss_sum / max(samples, 1)

    def _peek_metric(self, entry):
        import jax
        loss_sum = float(jax.device_get(entry["loss"][VALID]))
        return loss_sum / max(entry["samples"][VALID], 1)

    def _improvement_suffix(self, metric, n_err, samples):
        return "validation_mse_%.6f" % metric

    def _class_summary(self, klass, n_err, samples, loss_sum, epoch):
        samples = max(samples, 1)
        self.info("epoch %d %s: avg mse %.6f", epoch,
                  CLASS_NAMES[klass], loss_sum / samples)
        if klass == VALID:
            metric = self._metric(n_err, samples, loss_sum)
            self._track_improvement(
                VALID, metric, epoch,
                self._improvement_suffix(metric, n_err, samples))

    @property
    def best_mse(self):
        """Alias: ``best_n_err`` stores the tracked metric, which for
        this decision is the average MSE."""
        return self.best_n_err

    def get_metric_names(self):
        return ["best_validation_mse", "best_epoch", "epochs"]
