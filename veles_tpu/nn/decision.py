"""DecisionGD: epoch accounting and stop decisions.

The Znicz Decision unit watches the loader's epoch flags and the evaluator's
metrics, accumulates per-class error counts, decides whether the validation
error improved, remembers the best snapshot point, and raises
``complete`` when training should stop (max epochs reached or no
improvement for ``fail_iterations`` epochs).

Host-side by design: it runs once per minibatch but does only flag checks;
device metric reads happen at epoch boundaries (one small transfer per
epoch). Its ``improved``/``snapshot_suffix``/``complete`` outputs gate the
Snapshotter and the Repeater loop exactly as in the reference workflows.
"""

from veles_tpu.core.mutable import Bool
from veles_tpu.core.units import Unit
from veles_tpu.loader.base import CLASS_NAMES, TEST, TRAIN, VALID


class DecisionGD(Unit):
    """Training-loop decision unit (the Znicz Decision contract)."""

    VIEW_GROUP = "TRAINER"

    def __init__(self, workflow, **kwargs):
        self.max_epochs = kwargs.pop("max_epochs", None)
        self.fail_iterations = kwargs.pop("fail_iterations", 100)
        super().__init__(workflow, **kwargs)
        # linked from the loader:
        self.loader = None
        # linked from the evaluator (device scalars, read at epoch end):
        self.evaluator = None
        self.demand("loader", "evaluator")
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.train_ended = Bool(False)
        self.epoch_ended = Bool(False)
        # gate for the GD chain: True on non-train minibatches so the
        # backward units gate_skip (run nothing, still propagate the tick)
        self.gd_skipped = Bool(False)
        # accumulated per-class stats, indexed TEST/VALID/TRAIN:
        self.epoch_n_err = [0, 0, 0]
        self.epoch_samples = [0, 0, 0]
        self.epoch_loss = [0.0, 0.0, 0.0]
        self.best_n_err = [None, None, None]
        self.best_epoch = 0
        self.snapshot_suffix = ""
        self._epochs_without_improvement = 0

    def link_from_workflow(self, loader, evaluator):
        self.loader = loader
        self.evaluator = evaluator
        return self

    def initialize(self, **kwargs):
        if self.loader is None or self.evaluator is None:
            return True

    def run(self):
        self.improved.unset()
        self.epoch_ended.unset()
        klass = self.loader.minibatch_class
        self.gd_skipped.set(klass != TRAIN)
        # accumulate metrics as LAZY device scalars — a host read here would
        # block the async XLA dispatch pipeline every minibatch; conversion
        # to Python numbers happens only at class/epoch boundaries
        size = int(self.loader.minibatch_valid_size)
        self.epoch_n_err[klass] = (self.epoch_n_err[klass]
                                   + self.evaluator.n_err.data)
        self.epoch_samples[klass] += size
        self.epoch_loss[klass] = (self.epoch_loss[klass]
                                  + self.evaluator.loss.data * size)
        if not self.loader.epoch_ended_for_class:
            return
        # one sample-class sweep finished: sync its accumulators to host
        self.epoch_n_err[klass] = int(self.epoch_n_err[klass])
        self.epoch_loss[klass] = float(self.epoch_loss[klass])
        self._on_class_ended(klass)
        if self.loader.epoch_ended:
            self._on_epoch_ended()

    # -- epoch boundary logic -------------------------------------------------
    def _on_class_ended(self, klass):
        samples = max(self.epoch_samples[klass], 1)
        error_pct = 100.0 * self.epoch_n_err[klass] / samples
        self.info(
            "epoch %d %s: errors %d/%d (%.2f%%) avg loss %.6f",
            self.loader.epoch_number, CLASS_NAMES[klass],
            self.epoch_n_err[klass], samples, error_pct,
            self.epoch_loss[klass] / samples)
        if klass == VALID:
            best = self.best_n_err[VALID]
            if best is None or self.epoch_n_err[VALID] < best:
                self.best_n_err[VALID] = self.epoch_n_err[VALID]
                self.best_epoch = self.loader.epoch_number
                self.improved.set()
                self._epochs_without_improvement = 0
                self.snapshot_suffix = "validation_%.2fpt" % error_pct
            else:
                self._epochs_without_improvement += 1

    def _on_epoch_ended(self):
        self.epoch_ended.set()
        # when there is no validation set, improvement tracks train error
        if self.epoch_samples[VALID] == 0 and self.epoch_samples[TRAIN] > 0:
            best = self.best_n_err[TRAIN]
            if best is None or self.epoch_n_err[TRAIN] < best:
                self.best_n_err[TRAIN] = self.epoch_n_err[TRAIN]
                self.best_epoch = self.loader.epoch_number
                self.improved.set()
                self._epochs_without_improvement = 0
                samples = max(self.epoch_samples[TRAIN], 1)
                self.snapshot_suffix = "train_%.2fpt" % (
                    100.0 * self.epoch_n_err[TRAIN] / samples)
            else:
                self._epochs_without_improvement += 1
        stop = False
        # epoch_number is 0-based and only increments when the NEXT epoch
        # starts serving, so at the end of epoch N it still reads N
        if self.max_epochs is not None \
                and self.loader.epoch_number + 1 >= self.max_epochs:
            self.info("stopping: reached max_epochs=%d", self.max_epochs)
            stop = True
        if self._epochs_without_improvement >= self.fail_iterations:
            self.info("stopping: no improvement for %d epochs",
                      self.fail_iterations)
            stop = True
        if stop:
            self.complete.set()
            self.train_ended.set()
        for klass in (TEST, VALID, TRAIN):
            self.epoch_n_err[klass] = 0
            self.epoch_samples[klass] = 0
            self.epoch_loss[klass] = 0.0

    # -- results (IResultProvider) -------------------------------------------
    def get_metric_names(self):
        return ["best_validation_errors", "best_epoch", "epochs"]

    def get_metric_values(self):
        return [self.best_n_err[VALID] if self.best_n_err[VALID] is not None
                else self.best_n_err[TRAIN],
                self.best_epoch, self.loader.epoch_number]
