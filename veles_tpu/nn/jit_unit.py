"""JitUnit: the TPU-era AcceleratedUnit.

The reference AcceleratedUnit (``accelerated_units.py:130-673``) dispatches
per backend (numpy_run/ocl_run/cuda_run) and hand-builds kernels through a
jinja2 + compile + binary-cache pipeline. Under XLA that entire machinery is
``jax.jit``: a JitUnit subclass writes one pure ``compute(*arrays)`` and the
framework traces/compiles/caches it per shape signature. The reference's
``--force-numpy`` escape hatch survives as ``root.common.engine.force_cpu``
(jit on the CPU backend); its kernel binary cache is XLA's own compilation
cache.

Contract:

- ``INPUTS``/``OUTPUTS`` name Array-slot attributes on the unit;
- ``compute(*tensors)`` is pure (no self-state reads that change between
  calls — changing hyperparameters must be passed as tensors, e.g. via
  ``PARAMS`` slots);
- ``run()`` gathers INPUT slots' device values, invokes the jitted compute,
  and stores results back into OUTPUT slots (mutable Array containers shared
  with consumers by ``link_attrs``), so downstream units — and the fused
  tick, later — see new values without host round-trips.
"""

import jax

from veles_tpu.core.units import Unit
from veles_tpu.memory import Array
from veles_tpu.observe.xla_stats import instrument


class JitUnit(Unit):
    """Base for units whose run() is one jitted computation."""

    hide_from_registry = True

    INPUTS = ()
    OUTPUTS = ()

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        for name in self.OUTPUTS:
            if getattr(self, name, None) is None:
                setattr(self, name, Array())

    def init_unpickled(self):
        super().init_unpickled()
        self._jitted_ = None

    # -- the pure computation -------------------------------------------------
    def compute(self, *tensors):
        """Pure function of the INPUT tensors; returns one tensor per OUTPUT
        (or a single tensor when there is one OUTPUT)."""
        raise NotImplementedError

    def install_program(self, fn):
        """Adopt a caller-provided program as this unit's compute —
        the AOT artifact loader's seam (``veles_tpu/aot/loader.py``):
        a deserialized compiled program (wrapped with a live-jit
        fallback dispatcher) slots in here and ``run()`` uses it
        unchanged, so a unit's cold start skips tracing entirely."""
        self._jitted_ = fn
        return self

    @property
    def jitted(self):
        if self._jitted_ is None:
            backend = None
            from veles_tpu.core.config import root
            if root.common.engine.get("force_cpu", False):
                backend = "cpu"
            # per-unit compile/hit telemetry (observe/xla_stats.py): a
            # unit whose input shape churns every tick is the classic
            # recompilation storm; the tracker names it so /metrics
            # and the black box can point at the culprit
            self._jitted_ = instrument(
                "unit.%s" % type(self).__name__,
                jax.jit(self.compute, backend=backend))
        return self._jitted_

    # -- slot plumbing --------------------------------------------------------
    def gather_inputs(self):
        values = []
        for name in self.INPUTS:
            slot = getattr(self, name)
            if isinstance(slot, Array):
                if slot.data is None:
                    raise ValueError(
                        "%s: input slot %r is empty" % (self.name, name))
                values.append(slot.data)
            else:
                values.append(slot)
        return values

    def scatter_outputs(self, results):
        if len(self.OUTPUTS) == 1:
            results = (results,)
        for name, value in zip(self.OUTPUTS, results):
            slot = getattr(self, name)
            if isinstance(slot, Array):
                slot.data = value
            else:
                setattr(self, name, value)

    def run(self):
        self.scatter_outputs(self.jitted(*self.gather_inputs()))


class ForwardUnit(JitUnit):
    """Marker base for forward-propagation units (the Znicz ``Forward``
    contract: ``input``/``output`` + ``weights``/``bias`` slots). The tick
    compiler and the model exporter recognize these."""

    hide_from_registry = True

    VIEW_GROUP = "WORKER"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.demand("input")
