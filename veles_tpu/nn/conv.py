"""Convolutional units (Znicz Conv/GradientDescentConv equivalents).

Forward: NHWC activations × HWIO weights through ``ops.gemm.conv2d`` —
the layout XLA maps straight onto the MXU (the reference hand-tiled
OpenCL/CUDA conv kernels in libZnicz; on TPU the compiler's conv emitter
is the fast path, under the shared engine precision policy).

Backward: ``jax.vjp`` of the pre-activation forward *inside the jitted
compute* — exact gradients with zero hand-derived transpose-conv code, fully
fused by XLA. This is the pattern for every structured op whose manual
adjoint the reference maintained by hand.
"""

import math

import jax
import jax.numpy as jnp

from veles_tpu.core.prng import get as get_rng
from veles_tpu.memory import Array
from veles_tpu.nn.jit_unit import ForwardUnit
from veles_tpu.nn.gd import GradientDescent
from veles_tpu.ops import activations


class Conv(ForwardUnit):
    """2-D convolution + activation."""

    ACTIVATION = "linear"

    INPUTS = ("input", "weights", "bias")
    OUTPUTS = ("output",)

    def __init__(self, workflow, n_kernels=None, kx=3, ky=3,
                 sliding=(1, 1), padding="SAME", **kwargs):
        self.weights_stddev = kwargs.pop("weights_stddev", None)
        self.prng_key = kwargs.pop("prng_key", "default")
        super().__init__(workflow, **kwargs)
        if n_kernels is None:
            raise ValueError("%s needs n_kernels" % self.name)
        self.n_kernels = n_kernels
        self.kx, self.ky = kx, ky
        self.sliding = tuple(sliding)
        self.padding = padding
        self.weights = Array()
        self.bias = Array()
        self.input = None

    def initialize(self, **kwargs):
        if self.input is None or (isinstance(self.input, Array)
                                  and self.input.data is None):
            return True
        in_shape = self.input.shape  # (N, H, W, C)
        if len(in_shape) != 4:
            raise ValueError(
                "%s expects NHWC input, got %s" % (self.name, (in_shape,)))
        channels = in_shape[3]
        if self.weights.data is None:
            fan_in = self.kx * self.ky * channels
            stddev = self.weights_stddev or 1.0 / math.sqrt(fan_in)
            rng = get_rng(self.prng_key)
            self.weights.data = jnp.asarray(rng.fill_uniform(
                (self.ky, self.kx, channels, self.n_kernels), stddev),
                jnp.float32)
            self.bias.data = jnp.zeros((self.n_kernels,), jnp.float32)
        if self.output.data is None:
            shape = jax.eval_shape(
                lambda x, w, b: self._pre_activation(x, w, b),
                jax.ShapeDtypeStruct(in_shape, jnp.float32),
                jax.ShapeDtypeStruct(self.weights.shape, jnp.float32),
                jax.ShapeDtypeStruct(self.bias.shape, jnp.float32)).shape
            self.output.data = jnp.zeros(shape, jnp.float32)

    def _pre_activation(self, x, weights, bias):
        from veles_tpu.ops.gemm import conv2d
        out = conv2d(x, weights, self.sliding, self.padding)
        return out + bias

    def compute(self, x, weights, bias):
        act, _ = activations.ACTIVATIONS[self.ACTIVATION]
        return act(self._pre_activation(x, weights, bias))


class ConvTanh(Conv):
    ACTIVATION = "tanh"


class ConvRELU(Conv):
    ACTIVATION = "relu"


class ConvStrictRELU(Conv):
    ACTIVATION = "strict_relu"


class GDConv(GradientDescent):
    """Backward unit for Conv: exact adjoint via jax.vjp of the paired
    forward's pre-activation, fused into one jitted computation."""

    ACTIVATION = "linear"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.forward_unit = None  # set by link_conv

    def link_conv(self, conv_unit, err_source):
        from veles_tpu.nn.gd import link_err_output
        self.forward_unit = conv_unit
        self.link_attrs(conv_unit, "input", "output", "weights", "bias")
        link_err_output(self, err_source)
        return self

    def compute(self, err_output, x, y, weights, bias, vel_w, vel_b,
                *rest):
        upd, hyper, (sec_w, sec_b), extras = self._unpack_solver(rest)
        lr, lr_b, l2, l1 = hyper[0], hyper[1], hyper[2], hyper[3]
        _, deriv = activations.ACTIVATIONS[self.ACTIVATION]
        err_pre = err_output * deriv(y)
        _, vjp = jax.vjp(self.forward_unit._pre_activation, x, weights, bias)
        err_input, grad_w, grad_b = vjp(err_pre)
        grad_w = grad_w + l2 * weights + l1 * jnp.sign(weights)
        new_w, new_vel_w, new_sec_w = upd(weights, grad_w, vel_w, sec_w,
                                          lr)
        new_b, new_vel_b, new_sec_b = upd(bias, grad_b, vel_b, sec_b,
                                          lr_b)
        return (err_input, new_w, new_b, new_vel_w, new_vel_b) \
            + extras((new_sec_w, new_sec_b))


class GDConvTanh(GDConv):
    ACTIVATION = "tanh"


class GDConvRELU(GDConv):
    ACTIVATION = "relu"


class GDConvStrictRELU(GDConv):
    ACTIVATION = "strict_relu"
