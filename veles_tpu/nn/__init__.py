"""veles_tpu.nn: neural-network units (the Znicz plugin equivalent).

The reference keeps its NN op units in the Znicz submodule (absent from the
snapshot; unit families named in ``BASELINE.json`` and the docs:
All2All*/Conv/Pooling/GradientDescent*/Evaluator*/Decision). Here they are
first-class: each unit is a :class:`veles_tpu.nn.jit_unit.JitUnit` whose
``compute`` is a pure jax function compiled once per shape, with parameters
held in shared :class:`veles_tpu.memory.Array` slots so forward and
gradient units see the same weights without copies.
"""

from veles_tpu.nn.jit_unit import JitUnit, ForwardUnit  # noqa: F401
from veles_tpu.nn.all2all import (  # noqa: F401
    All2All, All2AllTanh, All2AllRELU, All2AllStrictRELU, All2AllSigmoid,
    All2AllSoftmax)
from veles_tpu.nn.evaluator import EvaluatorSoftmax, EvaluatorMSE  # noqa: F401
from veles_tpu.nn.gd import (  # noqa: F401
    GradientDescent, GDTanh, GDRELU, GDStrictRELU, GDSigmoid, GDSoftmax)
from veles_tpu.nn.decision import DecisionGD, DecisionMSE  # noqa: F401
