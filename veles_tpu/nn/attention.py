"""Attention / layer-norm units for sequence models.

No Znicz counterpart (the reference predates attention); these extend the
unit-graph API to transformers with the same contracts as All2All/Conv:
shared weight Array slots, ``err_output`` in / ``err_input`` out, exact
backward via ``jax.vjp`` of the forward inside one jitted compute.

``SelfAttention`` computes fused multi-head self-attention
(``ops.attention``); over a ``seq``-sharded mesh the same unit math runs
inside the fused step via ``ops.attention.ring_attention``.
"""

import math

import jax
import jax.numpy as jnp

from veles_tpu.core.prng import get as get_rng
from veles_tpu.memory import Array
from veles_tpu.nn.jit_unit import ForwardUnit
from veles_tpu.nn.gd import GradientDescent
from veles_tpu.ops.attention import attention_block, ffn_block


class SelfAttention(ForwardUnit):
    """Multi-head self-attention block: x → attn(norm-free) → out proj.

    Input/output: (B, T, E). Weights: qkv (E, 3·E) fused projection and
    out (E, E), biases each. One jitted compute; the attention core is the
    flash kernel on TPU. ``residual=True`` adds the block input to the
    output (the standard pre-LN transformer wiring: pair with a LayerNorm
    in front and a residual :class:`TokenFFN` behind).
    """

    INPUTS = ("input", "weights", "bias", "out_weights", "out_bias")
    OUTPUTS = ("output",)

    def __init__(self, workflow, heads=8, causal=False, residual=False,
                 **kwargs):
        self.prng_key = kwargs.pop("prng_key", "default")
        super().__init__(workflow, **kwargs)
        self.heads = heads
        self.causal = causal
        self.residual = residual
        self.weights = Array()
        self.bias = Array()
        self.out_weights = Array()
        self.out_bias = Array()
        self.input = None

    def initialize(self, **kwargs):
        if self.input is None or (isinstance(self.input, Array)
                                  and self.input.data is None):
            return True
        batch, t, embed = self.input.shape
        if embed % self.heads:
            raise ValueError("%s: embed %d not divisible by %d heads"
                             % (self.name, embed, self.heads))
        if self.weights.data is None:
            rng = get_rng(self.prng_key)
            stddev = 1.0 / math.sqrt(embed)
            self.weights.data = jnp.asarray(
                rng.fill_uniform((embed, 3 * embed), stddev), jnp.float32)
            self.bias.data = jnp.zeros((3 * embed,), jnp.float32)
            self.out_weights.data = jnp.asarray(
                rng.fill_uniform((embed, embed), stddev), jnp.float32)
            self.out_bias.data = jnp.zeros((embed,), jnp.float32)
        if self.output.data is None:
            self.output.data = jnp.zeros(self.input.shape, jnp.float32)

    def _forward(self, x, w_qkv, b_qkv, w_out, b_out):
        # shared implementation with the fused engine: the whole block
        # (residual included) under the engine precision policy
        return attention_block(x, w_qkv, b_qkv, w_out, b_out,
                               self.heads, self.causal, self.residual)

    def compute(self, x, w_qkv, b_qkv, w_out, b_out):
        return self._forward(x, w_qkv, b_qkv, w_out, b_out)


class GDSelfAttention(GradientDescent):
    """Backward for SelfAttention via jax.vjp — updates both projections."""

    INPUTS = ("err_output", "input", "weights", "bias", "out_weights",
              "out_bias", "_velocity_w", "_velocity_b", "_velocity_ow",
              "_velocity_ob", "_hyper")
    OUTPUTS = ("err_input", "weights", "bias", "out_weights", "out_bias",
               "_velocity_w", "_velocity_b", "_velocity_ow", "_velocity_ob")

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.forward_unit = None
        self.out_weights = None
        self.out_bias = None
        self._velocity_ow = Array()
        self._velocity_ob = Array()

    def link_attention(self, attn_unit, err_source):
        from veles_tpu.nn.gd import link_err_output
        self.forward_unit = attn_unit
        self.link_attrs(attn_unit, "input", "output", "weights", "bias",
                        "out_weights", "out_bias")
        link_err_output(self, err_source)
        return self

    def initialize(self, **kwargs):
        if self.weights is None or self.weights.data is None:
            return True
        if self._velocity_w.data is None:
            self._velocity_w.data = jnp.zeros_like(self.weights.data)
            self._velocity_b.data = jnp.zeros_like(self.bias.data)
            self._velocity_ow.data = jnp.zeros_like(self.out_weights.data)
            self._velocity_ob.data = jnp.zeros_like(self.out_bias.data)
        self._init_solver_state()
        self._refresh_hyper()

    def compute(self, err_output, x, w_qkv, b_qkv, w_out, b_out,
                vel_w, vel_b, vel_ow, vel_ob, *rest):
        solver_upd, hyper, secs, extras = self._unpack_solver(
            rest, n_leaves=4)
        lr, lr_b, l2, l1 = hyper[0], hyper[1], hyper[2], hyper[3]
        _, vjp = jax.vjp(self.forward_unit._forward, x, w_qkv, b_qkv,
                         w_out, b_out)
        err_input, g_qkv, g_bqkv, g_out, g_bout = vjp(err_output)

        def upd(w, g, v, sec, rate):
            g = g + l2 * w + l1 * jnp.sign(w)
            return solver_upd(w, g, v, sec, rate)

        w_qkv, vel_w, sec_w = upd(w_qkv, g_qkv, vel_w, secs[0], lr)
        b_qkv, vel_b, sec_b = upd(b_qkv, g_bqkv, vel_b, secs[1], lr_b)
        w_out, vel_ow, sec_ow = upd(w_out, g_out, vel_ow, secs[2], lr)
        b_out, vel_ob, sec_ob = upd(b_out, g_bout, vel_ob, secs[3], lr_b)
        return (err_input, w_qkv, b_qkv, w_out, b_out,
                vel_w, vel_b, vel_ow, vel_ob) \
            + extras((sec_w, sec_b, sec_ow, sec_ob))


class TokenFFN(ForwardUnit):
    """Position-wise transformer feed-forward block:
    ``act(x @ w1 + b1) @ w2 + b2`` (+ residual, default on) applied to
    every token independently.

    Input/output: (B, T, E). Weights: expansion (E, ratio·E) and
    contraction (ratio·E, E) projections — stored in the same slot names
    as SelfAttention (``weights``/``out_weights``) so the GD/fleet/fused
    leaf contracts are shared. With LayerNorm and a residual
    SelfAttention this completes the standard transformer block as a
    unit-graph topology.
    """

    INPUTS = ("input", "weights", "bias", "out_weights", "out_bias")
    OUTPUTS = ("output",)

    def __init__(self, workflow, ratio=4, activation="gelu",
                 residual=True, **kwargs):
        from veles_tpu.ops.attention import _FFN_ACTIVATIONS
        self.prng_key = kwargs.pop("prng_key", "default")
        super().__init__(workflow, **kwargs)
        if activation not in _FFN_ACTIVATIONS:
            # fail at construction with the valid names, not with a bare
            # KeyError inside jit tracing on the first tick
            raise ValueError(
                "%s: unknown ffn activation %r (one of %s)"
                % (self.name, activation,
                   "/".join(sorted(_FFN_ACTIVATIONS))))
        self.ratio = ratio
        self.activation = activation
        self.residual = residual
        self.weights = Array()
        self.bias = Array()
        self.out_weights = Array()
        self.out_bias = Array()
        self.input = None

    def initialize(self, **kwargs):
        if self.input is None or (isinstance(self.input, Array)
                                  and self.input.data is None):
            return True
        embed = self.input.shape[-1]
        hidden = int(self.ratio * embed)
        if self.weights.data is None:
            rng = get_rng(self.prng_key)
            self.weights.data = jnp.asarray(
                rng.fill_uniform((embed, hidden), 1.0 / math.sqrt(embed)),
                jnp.float32)
            self.bias.data = jnp.zeros((hidden,), jnp.float32)
            self.out_weights.data = jnp.asarray(
                rng.fill_uniform((hidden, embed),
                                 1.0 / math.sqrt(hidden)), jnp.float32)
            self.out_bias.data = jnp.zeros((embed,), jnp.float32)
        if self.output.data is None:
            self.output.data = jnp.zeros(self.input.shape, jnp.float32)

    def _forward(self, x, w1, b1, w2, b2):
        # shared implementation with the fused engine (ops/attention.py)
        return ffn_block(x, w1, b1, w2, b2, self.activation,
                         self.residual)

    def compute(self, x, w1, b1, w2, b2):
        return self._forward(x, w1, b1, w2, b2)


class GDTokenFFN(GDSelfAttention):
    """Backward for TokenFFN — the four-leaf vjp update of
    GDSelfAttention verbatim (the slot contract is identical:
    ``weights``/``bias`` are the expansion projection,
    ``out_weights``/``out_bias`` the contraction)."""

    link_ffn = GDSelfAttention.link_attention


class GDLayerNorm(GradientDescent):
    """Backward for LayerNorm via jax.vjp — trains scale/shift and routes
    the input error."""

    def link_forward(self, ln_unit, err_source):
        from veles_tpu.nn.gd import link_err_output
        self.forward_unit = ln_unit
        self.link_attrs(ln_unit, "input", "output", "weights", "bias")
        link_err_output(self, err_source)
        return self

    def compute(self, err_output, x, y, scale, shift, vel_w, vel_b,
                *rest):
        upd, hyper, (sec_w, sec_b), extras = self._unpack_solver(rest)
        lr, lr_b, l2, l1 = hyper[0], hyper[1], hyper[2], hyper[3]
        _, vjp = jax.vjp(self.forward_unit._forward, x, scale, shift)
        err_input, g_scale, g_shift = vjp(err_output)
        g_scale = g_scale + l2 * scale + l1 * jnp.sign(scale)
        new_w, new_vel_w, new_sec_w = upd(scale, g_scale, vel_w, sec_w,
                                          lr)
        new_b, new_vel_b, new_sec_b = upd(shift, g_shift, vel_b, sec_b,
                                          lr_b)
        return (err_input, new_w, new_b, new_vel_w, new_vel_b) \
            + extras((new_sec_w, new_sec_b))


class LayerNorm(ForwardUnit):
    """Layer normalization over the last axis with learned scale/shift."""

    INPUTS = ("input", "weights", "bias")
    OUTPUTS = ("output",)

    def __init__(self, workflow, eps=1e-5, **kwargs):
        super().__init__(workflow, **kwargs)
        self.eps = eps
        self.weights = Array()
        self.bias = Array()
        self.input = None

    def initialize(self, **kwargs):
        if self.input is None or (isinstance(self.input, Array)
                                  and self.input.data is None):
            return True
        dim = self.input.shape[-1]
        if self.weights.data is None:
            self.weights.data = jnp.ones((dim,), jnp.float32)
            self.bias.data = jnp.zeros((dim,), jnp.float32)
        if self.output.data is None:
            self.output.data = jnp.zeros(self.input.shape, jnp.float32)

    def _forward(self, x, scale, shift):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + self.eps) * scale + shift

    def compute(self, x, scale, shift):
        return self._forward(x, scale, shift)
