"""InputJoiner: concatenate several minibatch tensors into one.

TPU-native re-design of reference ``veles/input_joiner.py:49-212``: the
reference generated a jinja-templated ``join`` kernel per input count
(``ocl/join.jcl``); here the join is one jitted ``jnp.concatenate`` over
the flattened trailing dims — XLA emits the same single fused copy, cached
per input-shape signature.

``offset_N``/``length_N`` attributes (element offsets into the joined
sample) are published after initialize() exactly like the reference, so
downstream units can slice their segment back out.
"""

import jax
import jax.numpy as jnp

from veles_tpu.core.units import Unit
from veles_tpu.memory import Array


class InputJoiner(Unit):
    """Joins N input Arrays along the sample axis (reference
    ``InputJoiner``, ``input_joiner.py:49``)."""

    def __init__(self, workflow, **kwargs):
        inputs = kwargs.pop("inputs", None)
        super().__init__(workflow, **kwargs)
        self.output = Array()
        self.inputs = list(inputs) if inputs else []

    def init_unpickled(self):
        super().init_unpickled()
        self._join_jit_ = None

    @property
    def num_inputs(self):
        return len(self.inputs)

    def initialize(self, **kwargs):
        if not self.inputs:
            raise ValueError("%s: no inputs to join" % self.name)
        offset = 0
        for i, inp in enumerate(self.inputs):
            shape = inp.shape
            length = 1
            for dim in shape[1:]:
                length *= dim
            setattr(self, "offset_%d" % i, offset)
            setattr(self, "length_%d" % i, length)
            offset += length

    @property
    def _join_jit(self):
        if self._join_jit_ is None:
            @jax.jit
            def join(*tensors):
                n = tensors[0].shape[0]
                return jnp.concatenate(
                    [t.reshape(n, -1) for t in tensors], axis=1)

            self._join_jit_ = join
        return self._join_jit_

    def run(self):
        tensors = []
        for inp in self.inputs:
            value = inp.data if isinstance(inp, Array) else jnp.asarray(inp)
            if value is None:
                raise ValueError("%s: empty input" % self.name)
            tensors.append(value)
        n = min(int(t.shape[0]) for t in tensors)
        self.output.data = self._join_jit(*[t[:n] for t in tensors])
