"""All2All: fully-connected forward units.

The Znicz All2All family (named in ``BASELINE.json``; reference GPU path was
the tiled GEMM kernels of ``ocl/matrix_multiplication*.cl`` driven by
``accelerated_units.py``). TPU design: one jitted ``act(x @ W + b)`` over
``ops.gemm.matmul`` (MXU, bf16 passes + f32 accumulation by default).

Weight layout is (in_features, out_features) — natural for row-major
activations on the MXU; the reference stored (out, in) and transposed inside
its GEMM kernel.

Weights are initialized U(-stddev, stddev) from the unit's named
reproducible PRNG stream (reference Znicz used the same symmetric fill via
``prng``), with the Znicz default magnitude ``1/sqrt(fan_in)``-scaled unless
``weights_stddev`` is given.
"""

import math

import jax.numpy as jnp

from veles_tpu.core.prng import get as get_rng
from veles_tpu.memory import Array
from veles_tpu.nn.jit_unit import ForwardUnit
from veles_tpu.ops import activations
from veles_tpu.ops.gemm import matmul


class All2All(ForwardUnit):
    """Linear layer: output = act(input @ weights + bias)."""

    ACTIVATION = "linear"

    INPUTS = ("input", "weights", "bias")
    OUTPUTS = ("output",)

    def __init__(self, workflow, output_sample_shape=None, **kwargs):
        self.weights_stddev = kwargs.pop("weights_stddev", None)
        self.bias_stddev = kwargs.pop("bias_stddev", None)
        self.include_bias = kwargs.pop("include_bias", True)
        self.prng_key = kwargs.pop("prng_key", "default")
        super().__init__(workflow, **kwargs)
        if output_sample_shape is None:
            raise ValueError("%s needs output_sample_shape" % self.name)
        if isinstance(output_sample_shape, int):
            output_sample_shape = (output_sample_shape,)
        self.output_sample_shape = tuple(output_sample_shape)
        self.weights = Array()
        self.bias = Array()
        self.input = None

    @property
    def neurons_number(self):
        return int(math.prod(self.output_sample_shape))

    def initialize(self, **kwargs):
        if self.input is None or (isinstance(self.input, Array)
                                  and self.input.data is None):
            return True  # retry after the provider initializes
        in_features = int(math.prod(self.input.shape[1:]))
        out_features = self.neurons_number
        if self.weights.data is None:
            stddev = self.weights_stddev or 1.0 / math.sqrt(in_features)
            rng = get_rng(self.prng_key)
            self.weights.data = jnp.asarray(rng.fill_uniform(
                (in_features, out_features), stddev), jnp.float32)
            bias_std = self.bias_stddev or stddev
            self.bias.data = jnp.asarray(rng.fill_uniform(
                (out_features,), bias_std), jnp.float32) \
                if self.include_bias else jnp.zeros(
                    (out_features,), jnp.float32)
        if self.output.data is None:
            # allocate the output slot so downstream units can initialize
            # against its shape before the first tick (reference
            # AcceleratedUnit allocated output buffers at init)
            batch = self.input.shape[0]
            self.output.data = jnp.zeros(
                (batch,) + self.output_sample_shape, jnp.float32)

    def compute(self, x, weights, bias):
        x = x.reshape(x.shape[0], -1)
        pre = matmul(x, weights, out_dtype=jnp.float32) + bias
        act, _ = activations.ACTIVATIONS[self.ACTIVATION]
        out = act(pre)
        if len(self.output_sample_shape) > 1:
            out = out.reshape((x.shape[0],) + self.output_sample_shape)
        return out


class All2AllTanh(All2All):
    """Scaled-tanh dense layer (Znicz All2AllTanh, 1.7159·tanh(0.6666x))."""
    ACTIVATION = "tanh"


class All2AllRELU(All2All):
    """Softplus dense layer (Znicz All2AllRELU is log(1+e^x))."""
    ACTIVATION = "relu"


class All2AllStrictRELU(All2All):
    """max(0, x) dense layer."""
    ACTIVATION = "strict_relu"


class All2AllSigmoid(All2All):
    ACTIVATION = "sigmoid"


class All2AllSoftmax(All2All):
    """Classifier head. Emits **logits** in ``output`` plus the argmax in
    ``max_idx``; the softmax itself lives fused inside EvaluatorSoftmax's
    cross-entropy (numerically stabler and one less HBM round trip than the
    reference's explicit softmax kernel). Consumers needing probabilities
    use ``jax.nn.softmax(output.data)``."""

    ACTIVATION = "linear"
    OUTPUTS = ("output", "max_idx")

    def compute(self, x, weights, bias):
        logits = super().compute(x, weights, bias)
        return logits, jnp.argmax(logits, axis=-1)
