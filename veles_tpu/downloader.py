"""Downloader: fetch + unpack dataset archives at initialize time.

Reference ``veles/downloader.py:56``: a unit that, before anything else
runs, ensures the dataset archive named by ``url`` is present in
``directory`` and unpacked. Kept semantics: no-op when the expected files
already exist; fetch supports plain files, ``.gz`` single members,
``.tar[.gz|.bz2|.xz]`` and ``.zip`` archives; works for ``http(s)://``,
``file://`` URLs and local paths (the offline-test path). Adds an
optional sha256 integrity check (the reference trusted the transport).
"""

import gzip
import hashlib
import os
import shutil
import tarfile
import urllib.parse
import urllib.request
import zipfile

from veles_tpu.core.config import root
from veles_tpu.core.units import Unit


def fetch(url, directory, checksum=None, logger=None):
    """Download ``url`` into ``directory`` and unpack it. Returns the list
    of extracted paths (or the downloaded file itself).

    A ``<name>.ok`` marker is written after a successful
    fetch+verify+unpack; later calls short-circuit on it, so workflow
    restarts never re-hash or re-extract a complete dataset."""
    os.makedirs(directory, exist_ok=True)
    name = os.path.basename(urllib.parse.urlparse(url).path) \
        or "download.bin"
    target = os.path.join(directory, name)
    marker = target + ".ok"
    if os.path.exists(marker) and os.path.exists(target):
        return [target]
    if not os.path.exists(target):
        if logger is not None:
            logger.info("fetching %s", url)
        if "://" not in url:
            shutil.copy(url, target)
        else:
            tmp = target + ".part"
            with urllib.request.urlopen(url) as response, \
                    open(tmp, "wb") as out:
                shutil.copyfileobj(response, out)
            os.replace(tmp, target)
    if checksum is not None:
        sha = hashlib.sha256()
        with open(target, "rb") as fin:
            for chunk in iter(lambda: fin.read(1 << 20), b""):
                sha.update(chunk)
        if sha.hexdigest() != checksum:
            os.remove(target)
            raise ValueError("%s: sha256 mismatch (got %s, want %s)"
                             % (url, sha.hexdigest(), checksum))
    members = unpack(target, directory)
    with open(marker, "w") as out:
        out.write("ok\n")
    return members


def unpack(path, directory):
    """Unpack an archive in place; returns extracted member paths."""
    if tarfile.is_tarfile(path):
        with tarfile.open(path) as tar:
            tar.extractall(directory, filter="data")
            return [os.path.join(directory, m) for m in tar.getnames()]
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            zf.extractall(directory)
            return [os.path.join(directory, m) for m in zf.namelist()]
    if path.endswith(".gz"):
        member = path[:-3]
        if not os.path.exists(member):
            # extract via a temp name + atomic rename: an interrupted
            # extraction must not leave a truncated member that later
            # runs mistake for the real file
            tmp = member + ".part"
            with gzip.open(path, "rb") as fin, open(tmp, "wb") as out:
                shutil.copyfileobj(fin, out)
            os.replace(tmp, member)
        return [member]
    return [path]


class Downloader(Unit):
    """Dataset-fetching unit (reference ``downloader.py:56``).

    kwargs: ``url`` (or ``urls`` list), ``directory`` (defaults to the
    configured datasets dir), ``files`` — names that must exist afterwards
    (also the short-circuit check), ``checksums`` — optional url→sha256.
    """

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, **kwargs):
        self.urls = list(kwargs.pop("urls", ()))
        url = kwargs.pop("url", None)
        if url:
            self.urls.append(url)
        self.directory = kwargs.pop(
            "directory", root.common.dirs.get("datasets"))
        self.files = list(kwargs.pop("files", ()))
        self.checksums = dict(kwargs.pop("checksums", {}))
        super().__init__(workflow, **kwargs)

    def _missing(self):
        return [f for f in self.files
                if not os.path.exists(os.path.join(self.directory, f))]

    def initialize(self, **kwargs):
        if self.files and not self._missing():
            self.debug("all %d files already present in %s",
                       len(self.files), self.directory)
            return
        for url in self.urls:
            fetch(url, self.directory, self.checksums.get(url), self)
        missing = self._missing()
        if missing:
            raise FileNotFoundError(
                "%s: still missing after download: %s"
                % (self.name, ", ".join(missing)))

    def run(self):
        pass
