"""Publisher: render an experiment report when the workflow finishes.

TPU-native re-design of reference ``veles/publishing/`` (1.1k LoC:
Publisher unit + Markdown/HTML/Confluence/PDF/jinja2 backends). Kept: the
Publisher unit contract — it fires at workflow end, gathers every
IResultProvider metric, the config snapshot, the DOT workflow graph, the
rendered plot images and run metadata, and hands the bundle to one or
more registered backends. Backends here: ``markdown`` (the canonical
report), ``html`` (self-contained page with inlined plot images), and
``json`` (machine-readable; the CI artifact). Confluence/PDF publishing
were service integrations around the same bundle — the backend registry
is the extension point for them.
"""

import base64
import html as html_lib
import json
import os
import pprint
import time

from veles_tpu.core.config import root
from veles_tpu.core.units import Unit

#: name -> backend class (reference publishing/registry.py)
backend_registry = {}


def register_backend(cls):
    backend_registry[cls.MAPPING] = cls
    return cls


class Backend:
    """One output format; ``render(bundle) -> text``."""

    MAPPING = None
    EXTENSION = "txt"

    def __init__(self, **kwargs):
        self.options = kwargs

    def render(self, bundle):
        raise NotImplementedError


@register_backend
class MarkdownBackend(Backend):
    """Reference ``markdown_backend.py:49``."""

    MAPPING = "markdown"
    EXTENSION = "md"

    def render(self, bundle):
        lines = ["# %s" % bundle["name"], "",
                 "*generated %s; run time %.1fs*" % (
                     bundle["timestamp"], bundle["run_time"]), "",
                 "## Results", ""]
        for key, value in sorted(bundle["results"].items()):
            lines.append("- **%s**: %s" % (key, value))
        lines += ["", "## Configuration", "", "```"]
        lines += bundle["config"].splitlines()
        lines += ["```", ""]
        if bundle["plots"]:
            lines += ["## Plots", ""]
            for name, path in sorted(bundle["plots"].items()):
                lines.append("![%s](%s)" % (name, path))
            lines.append("")
        if bundle.get("graph"):
            lines += ["## Workflow graph", "", "```dot"]
            lines += bundle["graph"].splitlines()
            lines += ["```", ""]
        return "\n".join(lines)


@register_backend
class HTMLBackend(Backend):
    """Self-contained HTML (plot images inlined as data URIs) —
    the role of the reference's markdown→HTML template."""

    MAPPING = "html"
    EXTENSION = "html"

    def render(self, bundle):
        esc = html_lib.escape
        rows = "".join(
            "<tr><td>%s</td><td>%s</td></tr>"
            % (esc(str(k)), esc(str(v)))
            for k, v in sorted(bundle["results"].items()))
        plots = []
        for name, path in sorted(bundle["plots"].items()):
            try:
                with open(path, "rb") as fin:
                    data = base64.b64encode(fin.read()).decode()
                plots.append('<figure><img src="data:image/png;base64,%s"'
                             '/><figcaption>%s</figcaption></figure>'
                             % (data, esc(name)))
            except OSError:
                continue
        return ("<!DOCTYPE html><html><head><title>%(name)s</title>"
                "<style>body{font-family:sans-serif;margin:2em} "
                "td{border:1px solid #999;padding:4px 10px} "
                "img{max-width:480px}</style></head><body>"
                "<h1>%(name)s</h1><p><em>%(ts)s — %(rt).1fs</em></p>"
                "<h2>Results</h2><table>%(rows)s</table>"
                "<h2>Plots</h2>%(plots)s"
                "<h2>Configuration</h2><pre>%(config)s</pre>"
                "</body></html>") % {
            "name": esc(bundle["name"]), "ts": esc(bundle["timestamp"]),
            "rt": bundle["run_time"], "rows": rows,
            "plots": "".join(plots) or "<p>none</p>",
            "config": esc(bundle["config"])}


@register_backend
class JSONBackend(Backend):
    MAPPING = "json"
    EXTENSION = "json"

    def render(self, bundle):
        payload = dict(bundle)
        payload.pop("graph", None)
        return json.dumps(payload, indent=1, default=str)


class Publisher(Unit):
    """Report-rendering unit (reference ``publishing/publisher.py:57``).

    Link it from the Decision (or EndPoint predecessor) with
    ``gate_skip = ~decision.complete`` so it fires once at the end; or
    call :meth:`publish` directly."""

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, **kwargs):
        backends = kwargs.pop("backends", ("markdown",))
        self.directory = kwargs.pop(
            "directory",
            os.path.join(root.common.dirs.get("cache", "."), "reports"))
        self.include_plots = kwargs.pop("plots", True)
        super().__init__(workflow, **kwargs)
        self._remembers_gates = False
        self.backends = {}
        for spec in backends:
            name, options = (spec, {}) if isinstance(spec, str) else spec
            cls = backend_registry.get(name)
            if cls is None:
                raise ValueError("unknown publishing backend %r (have %s)"
                                 % (name, sorted(backend_registry)))
            self.backends[name] = cls(**options)
        self.published = {}

    def gather_bundle(self):
        wf = self.workflow
        plots = {}
        if self.include_plots:
            launcher = getattr(wf, "workflow", None)
            server = getattr(launcher, "graphics_server", None)
            if server is not None:
                server.flush()
                plots = server.rendered
        return {
            "name": getattr(wf, "name", "workflow"),
            "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
            "run_time": float(getattr(wf, "run_time", 0.0) or 0.0),
            "results": wf.gather_results(),
            "config": pprint.pformat(root.__content__()),
            "plots": plots,
            "graph": wf.generate_graph(),
        }

    def publish(self):
        if root.common.disable.get("publishing", False):
            return {}
        bundle = self.gather_bundle()
        os.makedirs(self.directory, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in bundle["name"])
        for name, backend in self.backends.items():
            path = os.path.join(self.directory, "%s_report.%s"
                                % (safe, backend.EXTENSION))
            with open(path, "w") as fout:
                fout.write(backend.render(bundle))
            self.published[name] = path
            self.info("published %s report: %s", name, path)
        return dict(self.published)

    def run(self):
        self.publish()
