"""Publisher: render an experiment report when the workflow finishes.

TPU-native re-design of reference ``veles/publishing/`` (1.1k LoC:
Publisher unit + Markdown/HTML/Confluence/PDF/jinja2 backends). Kept: the
Publisher unit contract — it fires at workflow end, gathers every
IResultProvider metric, the config snapshot, the DOT workflow graph, the
rendered plot images and run metadata, and hands the bundle to one or
more registered backends. Backends here: ``markdown`` (the canonical
report), ``html`` (self-contained page with inlined plot images), and
``json`` (machine-readable; the CI artifact). Confluence/PDF publishing
were service integrations around the same bundle — the backend registry
is the extension point for them.
"""

import base64
import html as html_lib
import json
import os
import pprint
import time

from veles_tpu.core.config import root
from veles_tpu.core.units import Unit

#: name -> backend class (reference publishing/registry.py)
backend_registry = {}


def register_backend(cls):
    backend_registry[cls.MAPPING] = cls
    return cls


class Backend:
    """One output format; ``render(bundle) -> text`` (or bytes when
    ``BINARY`` — the Publisher then writes the file in binary mode)."""

    MAPPING = None
    EXTENSION = "txt"
    BINARY = False

    def __init__(self, **kwargs):
        self.options = kwargs

    def render(self, bundle):
        raise NotImplementedError


@register_backend
class MarkdownBackend(Backend):
    """Reference ``markdown_backend.py:49``."""

    MAPPING = "markdown"
    EXTENSION = "md"

    def render(self, bundle):
        lines = ["# %s" % bundle["name"], "",
                 "*generated %s; run time %.1fs*" % (
                     bundle["timestamp"], bundle["run_time"]), "",
                 "## Results", ""]
        for key, value in sorted(bundle["results"].items()):
            lines.append("- **%s**: %s" % (key, value))
        lines += ["", "## Configuration", "", "```"]
        lines += bundle["config"].splitlines()
        lines += ["```", ""]
        if bundle["plots"]:
            lines += ["## Plots", ""]
            for name, path in sorted(bundle["plots"].items()):
                lines.append("![%s](%s)" % (name, path))
            lines.append("")
        if bundle.get("graph"):
            lines += ["## Workflow graph", "", "```dot"]
            lines += bundle["graph"].splitlines()
            lines += ["```", ""]
        return "\n".join(lines)


@register_backend
class HTMLBackend(Backend):
    """Self-contained HTML (plot images inlined as data URIs) —
    the role of the reference's markdown→HTML template."""

    MAPPING = "html"
    EXTENSION = "html"

    def render(self, bundle):
        esc = html_lib.escape
        rows = "".join(
            "<tr><td>%s</td><td>%s</td></tr>"
            % (esc(str(k)), esc(str(v)))
            for k, v in sorted(bundle["results"].items()))
        plots = []
        for name, path in sorted(bundle["plots"].items()):
            try:
                with open(path, "rb") as fin:
                    data = base64.b64encode(fin.read()).decode()
                plots.append('<figure><img src="data:image/png;base64,%s"'
                             '/><figcaption>%s</figcaption></figure>'
                             % (data, esc(name)))
            except OSError:
                continue
        return ("<!DOCTYPE html><html><head><title>%(name)s</title>"
                "<style>body{font-family:sans-serif;margin:2em} "
                "td{border:1px solid #999;padding:4px 10px} "
                "img{max-width:480px}</style></head><body>"
                "<h1>%(name)s</h1><p><em>%(ts)s — %(rt).1fs</em></p>"
                "<h2>Results</h2><table>%(rows)s</table>"
                "<h2>Plots</h2>%(plots)s"
                "<h2>Configuration</h2><pre>%(config)s</pre>"
                "</body></html>") % {
            "name": esc(bundle["name"]), "ts": esc(bundle["timestamp"]),
            "rt": bundle["run_time"], "rows": rows,
            "plots": "".join(plots) or "<p>none</p>",
            "config": esc(bundle["config"])}


@register_backend
class JSONBackend(Backend):
    MAPPING = "json"
    EXTENSION = "json"

    def render(self, bundle):
        payload = dict(bundle)
        payload.pop("graph", None)
        return json.dumps(payload, indent=1, default=str)


@register_backend
class ConfluenceBackend(HTMLBackend):
    """Publish the report to a Confluence wiki over XML-RPC (reference
    ``publishing/confluence_backend.py:42`` + ``confluence.py:45`` —
    stdlib ``xmlrpc.client`` here, no requests/jinja2 needed).

    Options: ``server`` (base URL), ``username``, ``password``,
    ``space``; optional ``page`` (defaults to the workflow name, made
    unique with " (N)" suffixes like the reference) and ``parent``.
    ``render`` returns the page body, so the Publisher's local file is
    the artifact copy of what was uploaded."""

    MAPPING = "confluence"
    EXTENSION = "xml"

    def render(self, bundle):
        import xmlrpc.client
        content = self._page_body(bundle)
        opts = self.options
        proxy = xmlrpc.client.ServerProxy(
            opts["server"].rstrip("/") + "/rpc/xmlrpc")
        token = proxy.confluence2.login(opts["username"],
                                        opts["password"])
        try:
            space = opts["space"]
            title = opts.get("page") or bundle["name"]
            existing = self._get_page(proxy, token, space, title)
            if not opts.get("page"):
                index = 1
                while existing is not None:  # make the title unique
                    title = "%s (%d)" % (bundle["name"], index)
                    index += 1
                    existing = self._get_page(proxy, token, space, title)
            page = {"space": space, "title": title, "content": content}
            if existing is not None:
                page["id"] = existing["id"]
                page["version"] = existing["version"]
            parent = opts.get("parent")
            if parent:
                parent_page = self._get_page(proxy, token, space, parent)
                if parent_page is not None:
                    page["parentId"] = parent_page["id"]
            stored = proxy.confluence2.storePage(token, page)
            self.url = stored.get("url")
        finally:
            try:
                proxy.confluence2.logout(token)
            except Exception:
                pass
        return content

    def _page_body(self, bundle):
        # Confluence storage format is XHTML: the HTML backend's body is
        # valid content; strip the full-document envelope
        html = super().render(bundle)
        start = html.index("<body>") + len("<body>")
        end = html.index("</body>")
        return html[start:end]

    def _get_page(self, proxy, token, space, title):
        import xmlrpc.client
        try:
            return proxy.confluence2.getPage(token, space, title)
        except xmlrpc.client.Fault:
            return None


@register_backend
class IpynbBackend(Backend):
    """Jupyter-notebook report (the reference's jinja2 ipynb template
    role, ``publishing/ipynb_template.ipynb``): one markdown cell per
    report section — a notebook is plain JSON, no jinja2 needed."""

    MAPPING = "ipynb"
    EXTENSION = "ipynb"

    def render(self, bundle):
        md = MarkdownBackend().render(bundle)
        cells = []
        for i, section in enumerate(md.split("\n## ")):
            text = section if section.startswith("#") \
                else "## " + section
            cells.append({
                "cell_type": "markdown", "metadata": {},
                "id": "cell-%d" % i,  # mandatory since nbformat 4.5
                "source": text.splitlines(keepends=True)})
        return json.dumps({
            "cells": cells,
            "metadata": {"language_info": {"name": "python"}},
            "nbformat": 4, "nbformat_minor": 5}, indent=1)


@register_backend
class PDFBackend(Backend):
    """Text PDF report (reference ``publishing/pdf_backend.py:48`` went
    through pandoc/latex; this is a dependency-free PDF 1.4 writer —
    monospace text pages, enough for the metric/config report)."""

    MAPPING = "pdf"
    EXTENSION = "pdf"
    BINARY = True  # byte-exact write: xref offsets are byte positions

    LINES_PER_PAGE = 60
    CHARS_PER_LINE = 95

    def render(self, bundle):
        md = MarkdownBackend().render(bundle)
        lines = []
        for raw in md.splitlines():
            while len(raw) > self.CHARS_PER_LINE:
                lines.append(raw[:self.CHARS_PER_LINE])
                raw = raw[self.CHARS_PER_LINE:]
            lines.append(raw)
        pages = [lines[i:i + self.LINES_PER_PAGE]
                 for i in range(0, len(lines), self.LINES_PER_PAGE)] or [[]]
        return self._assemble(pages)

    @staticmethod
    def _escape(text):
        return (text.replace("\\", r"\\").replace("(", r"\(")
                .replace(")", r"\)").encode("ascii", "replace")
                .decode("ascii"))

    def _assemble(self, pages):
        # objects: 1 catalog, 2 page tree, 3 font, then per page:
        # page object + content stream
        objects = {}
        kids = []
        next_id = 4
        for page in pages:
            page_id, content_id = next_id, next_id + 1
            next_id += 2
            kids.append("%d 0 R" % page_id)
            text = ["BT", "/F1 10 Tf", "1 0 0 1 40 800 Tm", "12 TL"]
            for line in page:
                text.append("(%s) '" % self._escape(line))
            text.append("ET")
            stream = "\n".join(text)
            objects[content_id] = ("<< /Length %d >>\nstream\n%s\n"
                                   "endstream" % (len(stream), stream))
            objects[page_id] = (
                "<< /Type /Page /Parent 2 0 R /MediaBox [0 0 595 842] "
                "/Contents %d 0 R /Resources << /Font << /F1 3 0 R >> >> "
                ">>" % content_id)
        objects[1] = "<< /Type /Catalog /Pages 2 0 R >>"
        objects[2] = ("<< /Type /Pages /Kids [%s] /Count %d >>"
                      % (" ".join(kids), len(pages)))
        objects[3] = ("<< /Type /Font /Subtype /Type1 "
                      "/BaseFont /Courier >>")
        out = bytearray(b"%PDF-1.4\n")
        offsets = {}
        for oid in sorted(objects):
            offsets[oid] = len(out)
            out += ("%d 0 obj\n%s\nendobj\n"
                    % (oid, objects[oid])).encode("latin-1")
        xref_at = len(out)
        count = max(objects) + 1
        out += ("xref\n0 %d\n0000000000 65535 f \n" % count).encode()
        for oid in range(1, count):
            out += ("%010d 00000 n \n" % offsets[oid]).encode()
        out += ("trailer\n<< /Size %d /Root 1 0 R >>\nstartxref\n%d\n"
                "%%%%EOF\n" % (count, xref_at)).encode()
        return bytes(out)


class Publisher(Unit):
    """Report-rendering unit (reference ``publishing/publisher.py:57``).

    Link it from the Decision (or EndPoint predecessor) with
    ``gate_skip = ~decision.complete`` so it fires once at the end; or
    call :meth:`publish` directly."""

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, **kwargs):
        backends = kwargs.pop("backends", ("markdown",))
        self.directory = kwargs.pop(
            "directory",
            os.path.join(root.common.dirs.get("cache", "."), "reports"))
        self.include_plots = kwargs.pop("plots", True)
        super().__init__(workflow, **kwargs)
        self._remembers_gates = False
        self.backends = {}
        for spec in backends:
            name, options = (spec, {}) if isinstance(spec, str) else spec
            cls = backend_registry.get(name)
            if cls is None:
                raise ValueError("unknown publishing backend %r (have %s)"
                                 % (name, sorted(backend_registry)))
            self.backends[name] = cls(**options)
        self.published = {}

    def gather_bundle(self):
        wf = self.workflow
        plots = {}
        if self.include_plots:
            launcher = getattr(wf, "workflow", None)
            server = getattr(launcher, "graphics_server", None)
            if server is not None:
                server.flush()
                plots = server.rendered
        return {
            "name": getattr(wf, "name", "workflow"),
            "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
            "run_time": float(getattr(wf, "run_time", 0.0) or 0.0),
            "results": wf.gather_results(),
            "config": pprint.pformat(root.__content__()),
            "plots": plots,
            "graph": wf.generate_graph(),
        }

    def publish(self):
        if root.common.disable.get("publishing", False):
            return {}
        bundle = self.gather_bundle()
        os.makedirs(self.directory, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in bundle["name"])
        for name, backend in self.backends.items():
            path = os.path.join(self.directory, "%s_report.%s"
                                % (safe, backend.EXTENSION))
            try:
                rendered = backend.render(bundle)
                with open(path, "wb" if backend.BINARY else "w") as fout:
                    fout.write(rendered)
            except Exception:
                # a failed backend (e.g. the wiki is down) must not kill
                # the remaining reports — or fail the finished training
                self.exception("%s backend failed", name)
                continue
            self.published[name] = path
            self.info("published %s report: %s", name, path)
        return dict(self.published)

    def run(self):
        self.publish()
