"""The shipped rule families (docs/static_analysis.md is the catalog).

Every rule encodes an invariant a past PR paid for:

- ``lock.record-path`` / ``lock.ordering`` — the flight-recorder
  discipline (PRs 10/12) and lock-order safety across a class;
- ``retrace.*`` — the PR 6 retrace-storm class of bugs (unpinned
  ``out_shardings`` on mesh jits, unhashable statics, per-iteration
  re-jitting, non-canonical shape-cache keys);
- ``donation.read-after-dispatch`` — the PR 9 donated-buffer doctrine
  (a donated operand is DEAD after the call; XLA may have reused its
  buffer);
- ``shared.rmw`` — the thread-shared-state census: non-GIL-atomic
  read-modify-write on declared handler+driver classes must hold the
  class lock;
- ``deploy.swap-seam`` — the zero-downtime deploy doctrine (ISSUE 16):
  live weights are only rebound inside the drain seam
  (``__init__``/``swap_params``), never reached into from outside;
- ``metric.naming`` / ``metric.help`` — PR 5's Prometheus grammar
  (promoted from ``tests/test_observe.py::TestMetricNamingLint``) plus
  HELP-string presence per family.

All rules are intraprocedural by design: they check what a function's
own statements do, never what its callees do. That keeps every finding
explainable from the flagged line alone (and keeps the analyzer fast
enough to gate CI).
"""

import ast
import re

from veles_tpu.analyze.engine import Finding, Rule
from veles_tpu.analyze.registry import LOCK_ATTR_PATTERN
# the exposition regexes come from the runtime registry (the lockstep
# the deleted TestMetricNamingLint walk enforced): the gate must check
# exactly the grammar observe/metrics.py validates at booking time —
# metrics.py is stdlib-only, so the no-third-party constraint holds
from veles_tpu.observe.metrics import LABEL_NAME_RE, METRIC_NAME_RE

LOCK_ATTR_RE = re.compile(LOCK_ATTR_PATTERN, re.IGNORECASE)

#: calls forbidden on the record path: blocking, I/O, device sync
_RECORD_PATH_BANNED_NAMES = {"open", "print", "input"}
_RECORD_PATH_BANNED_ATTRS = {
    ("time", "sleep"): "blocks the record path",
    ("os", "replace"): "filesystem I/O",
    ("os", "rename"): "filesystem I/O",
    ("os", "remove"): "filesystem I/O",
    ("os", "unlink"): "filesystem I/O",
    ("os", "makedirs"): "filesystem I/O",
    ("os", "fsync"): "filesystem I/O",
    ("jax", "device_get"): "forces a device sync",
    ("jax", "block_until_ready"): "forces a device sync",
    ("jax", "effects_barrier"): "forces a device sync",
}
_DEVICE_SYNC_METHODS = {"block_until_ready"}
#: logging methods — handlers flush to streams/files, i.e. I/O
_LOGGING_METHODS = {"debug", "info", "warning", "error", "exception",
                    "critical"}


def _qualify(tree):
    """Map every function/class node to its dotted qualname (one level
    of class nesting is enough for this codebase)."""
    names = {}

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = prefix + child.name if prefix else child.name
                names[child] = qual
                visit(child, qual + ".")
            else:
                visit(child, prefix)

    visit(tree, "")
    return names


def _dotted(node):
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lockish(expr):
    """True for expressions that read like lock acquisition targets:
    ``self._lock``, ``some_mutex``, ``threading.Lock()`` results."""
    if isinstance(expr, ast.Call):
        dotted = _dotted(expr.func)
        if dotted and dotted.split(".")[-1] in (
                "Lock", "RLock", "Condition", "Semaphore",
                "BoundedSemaphore"):
            return True
        return False
    if isinstance(expr, ast.Attribute):
        return bool(LOCK_ATTR_RE.search(expr.attr))
    if isinstance(expr, ast.Name):
        return bool(LOCK_ATTR_RE.search(expr.id))
    return False


def _is_jit_call(node):
    """True for ``jax.jit(...)`` / bare ``jit(...)`` calls."""
    if not isinstance(node, ast.Call):
        return False
    dotted = _dotted(node.func)
    return dotted in ("jax.jit", "jit")


def _keyword(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


class RecordPathRule(Rule):
    """``lock.record-path``: declared record-path functions may not
    acquire locks, block, do I/O, or force a device sync — the
    flight-recorder discipline (PR 10's overhead contract: a stage
    mark is one enabled-flag check + one GIL-atomic container op)."""

    id = "lock.record-path"
    family = "lock"
    doc = ("record-path functions must stay lock-free, I/O-free and "
           "device-sync-free")

    def check_file(self, path, tree, lines):
        declared = self.registry.record_path_functions(path)
        if declared == ():
            return
        quals = _qualify(tree)
        for node, qual in quals.items():
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if declared is not None and qual not in declared:
                continue
            # whole-module declarations visit every def under its OWN
            # qualname, so each checks only its own scope (a nested
            # violation must not be reported twice); an explicitly
            # declared function also owns its nested closures — they
            # are not separately declared
            yield from self._check_function(
                path, node, qual, include_nested=declared is not None)

    def _check_function(self, path, func, qual, include_nested=False):
        nodes = list(_walk_scope(func))
        if include_nested:
            for child in ast.walk(func):
                if child is not func \
                        and isinstance(child, (ast.FunctionDef,
                                               ast.AsyncFunctionDef)):
                    nodes.extend(_walk_scope(child))
        for node in nodes:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if _is_lockish(item.context_expr):
                        yield Finding(
                            self.id, path, item.context_expr.lineno,
                            "record-path function %s acquires a lock "
                            "(%s) — the flight-recorder discipline "
                            "allows GIL-atomic container ops only"
                            % (qual,
                               _dotted(item.context_expr) or "with"))
            elif isinstance(node, ast.Call):
                yield from self._check_call(path, node, qual)

    def _check_call(self, path, call, qual):
        func = call.func
        if isinstance(func, ast.Name) \
                and func.id in _RECORD_PATH_BANNED_NAMES:
            yield Finding(
                self.id, path, call.lineno,
                "record-path function %s calls %s() — I/O is forbidden "
                "on the record path" % (qual, func.id))
            return
        if isinstance(func, ast.Attribute):
            if func.attr == "acquire":
                yield Finding(
                    self.id, path, call.lineno,
                    "record-path function %s calls .acquire() — the "
                    "record path must stay lock-free" % qual)
                return
            if func.attr in _DEVICE_SYNC_METHODS:
                yield Finding(
                    self.id, path, call.lineno,
                    "record-path function %s calls .%s() — a device "
                    "sync stalls every thread behind the dispatch"
                    % (qual, func.attr))
                return
            if func.attr in _LOGGING_METHODS \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id in ("self", "logger", "log",
                                          "logging"):
                yield Finding(
                    self.id, path, call.lineno,
                    "record-path function %s logs via .%s() — logging "
                    "handlers flush to streams/files; record, don't "
                    "narrate" % (qual, func.attr))
                return
            dotted = _dotted(func)
            if dotted:
                key = tuple(dotted.split(".")[-2:])
                why = _RECORD_PATH_BANNED_ATTRS.get(key)
                if why:
                    yield Finding(
                        self.id, path, call.lineno,
                        "record-path function %s calls %s — %s"
                        % (qual, dotted, why))


class LockOrderingRule(Rule):
    """``lock.ordering``: within one class, two methods must not nest
    the same pair of lock attributes in opposite orders — the classic
    deadlock-by-inversion (each inverted edge is reported where the
    second ordering appears)."""

    id = "lock.ordering"
    family = "lock"
    doc = "lock-acquisition nesting across a class must be acyclic"

    def check_file(self, path, tree, lines):
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(path, node)

    def _check_class(self, path, cls):
        edges = {}  # (outer, inner) -> (method, line)

        def walk(node, held, method):
            stack = list(held)
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    name = self._lock_name(item.context_expr)
                    if name:
                        for outer in stack:
                            edge = (outer, name)
                            edges.setdefault(
                                edge, (method, item.context_expr.lineno))
                        stack.append(name)
            for child in ast.iter_child_nodes(node):
                walk(child, stack, method)

        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(item, [], item.name)
        for (outer, inner), (method, line) in sorted(
                edges.items(), key=lambda kv: kv[1][1]):
            if (inner, outer) in edges and outer < inner:
                other_method, other_line = edges[(inner, outer)]
                report = max((method, line), (other_method, other_line),
                             key=lambda pair: pair[1])
                yield Finding(
                    self.id, path, report[1],
                    "class %s acquires %s->%s in %s (line %d) but "
                    "%s->%s in %s (line %d) — lock-order inversion"
                    % (cls.name, outer, inner, method, line,
                       inner, outer, other_method, other_line))

    @staticmethod
    def _lock_name(expr):
        if isinstance(expr, ast.Attribute) \
                and LOCK_ATTR_RE.search(expr.attr):
            return _dotted(expr) or expr.attr
        if isinstance(expr, ast.Name) and LOCK_ATTR_RE.search(expr.id):
            return expr.id
        return None


class UnpinnedOutShardingsRule(Rule):
    """``retrace.unpinned-out-shardings``: a ``jax.jit`` call that pins
    ``in_shardings`` (a mesh-layout program) must pin ``out_shardings``
    too — otherwise a donated state adopts whatever layout the last
    program preferred and every admit retraces (the PR 6 storm)."""

    id = "retrace.unpinned-out-shardings"
    family = "retrace"
    doc = "mesh-jitted programs must pin out_shardings"

    def check_file(self, path, tree, lines):
        for node in ast.walk(tree):
            if not _is_jit_call(node):
                continue
            if _keyword(node, "in_shardings") is not None \
                    and _keyword(node, "out_shardings") is None:
                yield Finding(
                    self.id, path, node.lineno,
                    "jax.jit call pins in_shardings but not "
                    "out_shardings — the output layout floats and "
                    "donated state drifts into retrace storms "
                    "(pin it like decode.sharded_slot_fns)")


def _walk_scope(node):
    """Walk a function's OWN statements — never descending into nested
    function/class defs (those run in a different dynamic scope)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield child
        yield from _walk_scope(child)


class LocalJitDispatchRule(Rule):
    """``retrace.local-jit-dispatch``: building a jit around a
    PER-CALL callable (a local def of this very function, a lambda, or
    a fresh ``shard_map(...)`` wrapper) and dispatching it in the same
    scope — the jit cache keys on the callable's identity, and a fresh
    object is born per enclosing call, so EVERY call re-traces (the
    compile counters read it as a permanent storm). Builders that jit
    once and RETURN the result (the caller holds one object) are
    exempt, as is jitting a module-level function (stable identity)."""

    id = "retrace.local-jit-dispatch"
    family = "retrace"
    doc = "jit of a per-call callable dispatched in the same scope"

    def check_file(self, path, tree, lines):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(path, node)

    def _check_function(self, path, func):
        local_defs = {child.name for child in func.body
                      if isinstance(child, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))}
        jitted = {}  # bound name -> (jit line, wrapped description)
        for stmt in _walk_scope(func):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and _is_jit_call(stmt.value):
                wrapped = self._per_call_identity(stmt.value,
                                                  local_defs)
                if wrapped:
                    jitted[stmt.targets[0].id] = (stmt.value.lineno,
                                                  wrapped)
        if not jitted:
            return
        # two sanctioned memo shapes survive across calls and carry no
        # per-call identity: a jit stored into a keyed cache
        # (`fn = jax.jit(...)` guarded by `_FN_CACHE.get(key)` then
        # `_FN_CACHE[key] = fn`), and a jit assigned to a nonlocal/
        # global closure slot BEHIND a guard that mentions the slot
        # (`nonlocal tp_fn; if tp_fn is None: tp_fn = ...`) — an
        # UNGUARDED nonlocal rebuild still re-traces every call
        guarded = self._guard_tested_names(func)
        memo_names = set()
        for stmt in _walk_scope(func):
            if isinstance(stmt, (ast.Nonlocal, ast.Global)):
                memo_names.update(stmt.names)
        for stmt in _walk_scope(func):
            if isinstance(stmt, ast.Assign) \
                    and any(isinstance(t, ast.Subscript)
                            for t in stmt.targets) \
                    and isinstance(stmt.value, ast.Name):
                jitted.pop(stmt.value.id, None)
        for name in memo_names & guarded:
            jitted.pop(name, None)
        for node in _walk_scope(func):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in jitted:
                line, wrapped = jitted[node.func.id]
                yield Finding(
                    self.id, path, node.lineno,
                    "dispatching %r, a jit (line %d) of %s — a fresh "
                    "callable identity per %s() call means EVERY call "
                    "re-traces; hoist the jit to module scope or "
                    "cache it keyed on its statics"
                    % (node.func.id, line, wrapped, func.name))

    @staticmethod
    def _guard_tested_names(func):
        """Names assigned inside an ``if`` whose test mentions them —
        the `if slot is None: slot = ...` memo-guard shape."""
        guarded = set()

        def visit(node, tests):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                child_tests = tests
                if isinstance(node, ast.If) and child in node.body:
                    child_tests = tests | {
                        n.id for n in ast.walk(node.test)
                        if isinstance(n, ast.Name)}
                if isinstance(child, ast.Assign):
                    for target in child.targets:
                        if isinstance(target, ast.Name) \
                                and target.id in child_tests:
                            guarded.add(target.id)
                visit(child, child_tests)

        visit(func, frozenset())
        return guarded

    @staticmethod
    def _per_call_identity(jit_call, local_defs):
        """A description of the per-call-identity callable this jit
        wraps, or None when the wrapped object is identity-stable."""
        if not jit_call.args:
            return None
        target = jit_call.args[0]
        if isinstance(target, ast.Lambda):
            return "a lambda"
        if isinstance(target, ast.Call):
            dotted = _dotted(target.func)
            if dotted and dotted.split(".")[-1] == "shard_map":
                return "a fresh shard_map wrapper"
            return None
        if isinstance(target, ast.Name) and target.id in local_defs:
            return "local def %r" % target.id
        return None


class UnhashableStaticRule(Rule):
    """``retrace.unhashable-static``: passing a list/dict/set literal
    for a declared ``static_argnames`` parameter of a module-local jit
    wrapper — statics key the jit cache, an unhashable one raises and a
    call-varying one retraces per call."""

    id = "retrace.unhashable-static"
    family = "retrace"
    doc = "jit statics must be hashable, canonical values"

    _MUTABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                ast.SetComp)

    def check_file(self, path, tree, lines):
        statics = {}  # local name -> set of static argnames
        for node in ast.walk(tree):
            target = None
            call = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _is_jit_call(node.value):
                target, call = node.targets[0].id, node.value
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) \
                            and _dotted(dec.func) == "functools.partial" \
                            and dec.args and _dotted(dec.args[0]) in (
                                "jax.jit", "jit"):
                        target, call = node.name, dec
            if call is None:
                continue
            kw = _keyword(call, "static_argnames")
            names = self._literal_strings(kw.value) if kw else set()
            if names:
                statics[target] = names
        if not statics:
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name):
                continue
            names = statics.get(node.func.id)
            if not names:
                continue
            for kw in node.keywords:
                if kw.arg in names \
                        and isinstance(kw.value, self._MUTABLE):
                    yield Finding(
                        self.id, path, kw.value.lineno,
                        "call passes a mutable %s for static arg %r of "
                        "jitted %s — statics must be hashable (use a "
                        "tuple) or the dispatch raises/retraces"
                        % (type(kw.value).__name__.lower(), kw.arg,
                           node.func.id))

    @staticmethod
    def _literal_strings(node):
        out = set()
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for element in node.elts:
                if isinstance(element, ast.Constant) \
                        and isinstance(element.value, str):
                    out.add(element.value)
        elif isinstance(node, ast.Constant) \
                and isinstance(node.value, str):
            out.add(node.value)
        return out


class JitInLoopRule(Rule):
    """``retrace.jit-in-loop``: constructing ``jax.jit(...)`` inside a
    loop body builds a FRESH traced callable per iteration — nothing is
    cached across iterations, so every pass pays a retrace. Filling a
    keyed cache (``cache[key] = jax.jit(...)`` / ``setdefault``) is the
    sanctioned shape and is exempt."""

    id = "retrace.jit-in-loop"
    family = "retrace"
    doc = "jit construction inside a loop retraces per iteration"

    def check_file(self, path, tree, lines):
        findings = []
        scopes = [tree] + [n for n in ast.walk(tree)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        for scope in scopes:
            findings.extend(self._check_scope(path, scope))
        return findings

    def _check_scope(self, path, scope):
        # names that flow into a keyed cache IN THIS SCOPE
        # (`cache[k] = fn`, `cache.setdefault(k, fn)`): the miss-branch
        # shape builds the jit in the loop but caches it — no
        # per-iteration retrace. Scope-local so an unrelated
        # function's `cache[k] = fn` cannot silence a same-named
        # uncached jit elsewhere in the file.
        cached_names = set()
        for node in _walk_scope(scope):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Subscript)
                            for t in node.targets) \
                    and isinstance(node.value, ast.Name):
                cached_names.add(node.value.id)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "setdefault":
                cached_names.update(a.id for a in node.args
                                    if isinstance(a, ast.Name))
        findings = []

        def visit(node, in_loop, stmt):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue  # a separate scope (checked on its own)
                child_in_loop = in_loop
                if isinstance(node, (ast.For, ast.While)) \
                        and child in getattr(node, "body", ()):
                    child_in_loop = True
                child_stmt = child if isinstance(child, ast.stmt) \
                    else stmt
                if child_in_loop and _is_jit_call(child) \
                        and not self._fills_cache(child_stmt,
                                                  cached_names):
                    findings.append(Finding(
                        self.id, path, child.lineno,
                        "jax.jit constructed inside a loop — a fresh "
                        "traced callable per iteration, nothing cached; "
                        "hoist it or store it in a keyed cache"))
                visit(child, child_in_loop, child_stmt)

        visit(scope, False, None)
        return findings

    @staticmethod
    def _fills_cache(stmt, cached_names):
        if stmt is None:
            return False
        if isinstance(stmt, ast.Assign):
            if any(isinstance(t, ast.Subscript) for t in stmt.targets):
                return True
            # the miss-branch shape: `fn = jax.jit(...)` whose name is
            # stored into a keyed cache elsewhere in the file
            return any(isinstance(t, ast.Name) and t.id in cached_names
                       for t in stmt.targets)
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Call) \
                and isinstance(stmt.value.func, ast.Attribute):
            return stmt.value.func.attr == "setdefault"
        return False


class ShapeKeyRule(Rule):
    """``retrace.shape-key``: program/shape caches must key on
    canonical hashable tuples — a list/set/dict (or ``list(...)`` /
    ``set(...)`` call) in the key raises at runtime or, worse, keys on
    identity and silently re-traces per call."""

    id = "retrace.shape-key"
    family = "retrace"
    doc = "shape caches must key on canonical tuples"

    _CACHEY = re.compile(r"cache|_fns|programs|jit", re.IGNORECASE)
    _BAD = (ast.List, ast.Set, ast.Dict, ast.ListComp, ast.SetComp)

    def check_file(self, path, tree, lines):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if not isinstance(target, ast.Subscript):
                    continue
                container = _dotted(target.value) or ""
                if not self._CACHEY.search(container):
                    continue
                bad = self._bad_key(target.slice)
                if bad is not None:
                    yield Finding(
                        self.id, path, node.lineno,
                        "%s is keyed on a non-canonical %s — shape "
                        "keys must be hashable tuples (one compiled "
                        "program per canonical key is the "
                        "dispatch-economy invariant)"
                        % (container, bad))

    def _bad_key(self, key):
        for node in ast.walk(key):
            if isinstance(node, self._BAD):
                return type(node).__name__.lower()
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in ("list", "set", "dict"):
                return "%s(...) call" % node.func.id
        return None


class DonationReadAfterDispatchRule(Rule):
    """``donation.read-after-dispatch``: an argument at a donated
    position is DEAD once the jitted call returns — XLA may already
    have reused its buffer (PR 9's doctrine). Reading the same name
    later in the same straight-line scope (before rebinding) is flagged."""

    id = "donation.read-after-dispatch"
    family = "donation"
    doc = "donated buffers must not be read after the jitted call"

    def check_file(self, path, tree, lines):
        donated = self._collect_donated(tree)
        scopes = [tree] + [n for n in ast.walk(tree)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from self._check_body(path, scope.body, donated)

    @staticmethod
    def _collect_donated(tree):
        """Local names bound to jit wrappers with donate_argnums →
        donated positional indices."""
        donated = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) \
                    or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name) \
                    or not _is_jit_call(node.value):
                continue
            kw = _keyword(node.value, "donate_argnums")
            if kw is None:
                continue
            indices = []
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, int):
                indices = [kw.value.value]
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                indices = [e.value for e in kw.value.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, int)]
            if indices:
                donated[node.targets[0].id] = tuple(indices)
        return donated

    def _check_body(self, path, body, donated):
        """Straight-line scan of one statement list: after a call that
        donates name N, a Load of N before a rebinding is a finding."""
        dead = {}  # name -> (call line, callee)
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            # reads first: the canonical `state = step(state)` rebind
            # reads the pre-call value, which is fine
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in dead:
                    line, callee = dead[node.id]
                    yield Finding(
                        self.id, path, node.lineno,
                        "%r is read after being donated to %s (line "
                        "%d) — the buffer may already be reused; "
                        "copy before the call or use the returned "
                        "value" % (node.id, callee, line))
                    dead.pop(node.id, None)
            # then rebindings revive names
            stored = set()
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, (ast.Store, ast.Del)):
                    dead.pop(node.id, None)
                    stored.add(node.id)
            # then this statement's donations take effect — but a name
            # REBOUND by the same statement (`state = step(state, b)`)
            # now holds the returned value, not the donated buffer
            donated_uses = {}  # name -> donated-position use count
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id in donated:
                    for index in donated[node.func.id]:
                        if index < len(node.args):
                            arg = node.args[index]
                            if isinstance(arg, ast.Name) \
                                    and arg.id not in stored:
                                dead[arg.id] = (node.lineno,
                                                node.func.id)
                                donated_uses[arg.id] = \
                                    donated_uses.get(arg.id, 0) + 1
            # a SAME-statement read beyond the donated-arg position
            # (`return step(state, b) + state`) already reads the
            # possibly-reused buffer
            for name, uses in donated_uses.items():
                loads = sum(1 for n in ast.walk(stmt)
                            if isinstance(n, ast.Name)
                            and isinstance(n.ctx, ast.Load)
                            and n.id == name)
                if loads > uses:
                    line, callee = dead[name]
                    yield Finding(
                        self.id, path, stmt.lineno,
                        "%r is read in the same statement that "
                        "donates it to %s — the buffer may already "
                        "be reused; copy before the call or use the "
                        "returned value" % (name, callee))

class SharedRmwRule(Rule):
    """``shared.rmw``: on declared handler+driver shared classes, an
    attribute read-modify-write (``self.x += 1``,
    ``self.d[k] = self.d.get(k, 0) + 1``) is NOT GIL-atomic — two
    threads interleave load/op/store and drop updates. Such mutations
    must run under the class's lock (``with self._lock:``)."""

    id = "shared.rmw"
    family = "shared-state"
    doc = ("read-modify-write on shared classes must hold the class "
           "lock")

    def check_file(self, path, tree, lines):
        declared = self.registry.shared_classes_for(path)
        if not declared:
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name in declared:
                exempt = set(declared[node.name]) | {"__init__"}
                yield from self._check_class(path, node, exempt)

    def _check_class(self, path, cls, exempt):
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                    or item.name in exempt:
                continue
            yield from self._check_method(path, cls.name, item)

    def _check_method(self, path, cls_name, method):
        findings = []

        def visit(node, locked):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if any(_is_lockish(i.context_expr) for i in node.items):
                    locked = True
            if not locked:
                rmw = self._rmw(node)
                if rmw:
                    findings.append(Finding(
                        self.id, path, node.lineno,
                        "%s.%s mutates %s outside the class lock — "
                        "load/op/store interleaves across threads and "
                        "drops updates (wrap in `with self.<lock>:`)"
                        % (cls_name, method.name, rmw)))
            for child in ast.iter_child_nodes(node):
                visit(child, locked)

        visit(method, False)
        return findings

    @staticmethod
    def _self_attr(node):
        """``self.x`` or ``self.x[...]`` → dotted description."""
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return "self." + node.attr
        return None

    def _rmw(self, node):
        if isinstance(node, ast.AugAssign):
            return self._self_attr(node.target)
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = self._self_attr(node.targets[0])
            if target is None:
                return None
            # self.d[k] = ... self.d.get(...) / self.d[...] ... is a
            # two-step read-modify-write on the same attribute
            for sub in ast.walk(node.value):
                if self._self_attr(sub) == target \
                        and isinstance(sub, (ast.Subscript,
                                             ast.Attribute)) \
                        and sub is not node.targets[0]:
                    return target
        return None


# -- zero-downtime deploys (ISSUE 16's drain-seam doctrine) ----------------

#: the live-weight attributes a serving engine exposes
_WEIGHT_ATTRS = {"params", "embed_table"}
#: the only methods sanctioned to write them on ``self``: the
#: constructor (no concurrency before publication) and the drain-seam
#: swap itself
_SEAM_METHODS = {"__init__", "swap_params"}


class SwapSeamRule(Rule):
    """``deploy.swap-seam``: live weights (``.params`` /
    ``.embed_table``) may only be written inside the drain seam. The
    serving drive loop reads them on every dispatch; a handler thread
    (or governor callback) assigning ``decoder.params = new`` races
    requests mid-decode onto half-swapped weights. The sanctioned
    writers are ``__init__`` (no concurrency before publication) and
    the object's own ``swap_params`` — which the drive loop invokes
    via ``request_swap`` only once both engines are drained. Reaching
    through another object (``self.decoder.params = ...``) is never
    sanctioned: route it through ``request_swap()``."""

    id = "deploy.swap-seam"
    family = "deploy"
    doc = ("live weights may only be written at the drain seam "
           "(__init__/swap_params on self; request_swap otherwise)")

    def check_file(self, path, tree, lines):
        findings = []

        def visit(node, fn_name):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                fn_name = node.name
            for target in self._write_targets(node):
                findings.append(self._judge(path, target, fn_name))
            for child in ast.iter_child_nodes(node):
                visit(child, fn_name)

        visit(tree, None)
        return [f for f in findings if f is not None]

    @staticmethod
    def _write_targets(node):
        """Attribute targets of assignments to a weight attribute."""
        if isinstance(node, ast.AugAssign):
            targets = [node.target]
        elif isinstance(node, ast.Assign):
            targets = []
            for t in node.targets:
                targets.extend(t.elts if isinstance(t, ast.Tuple)
                               else [t])
        else:
            return ()
        return [t for t in targets
                if isinstance(t, ast.Attribute)
                and t.attr in _WEIGHT_ATTRS]

    def _judge(self, path, target, fn_name):
        owner = target.value
        on_self = isinstance(owner, ast.Name) and owner.id == "self"
        if on_self and fn_name in _SEAM_METHODS:
            return None
        dotted = _dotted(target) or target.attr
        if on_self:
            detail = ("an engine may only rebind its own weights in "
                      "__init__ or swap_params")
        else:
            detail = ("reaching into another object's live weights "
                      "races the drive loop mid-dispatch — call "
                      "request_swap() so the swap lands at the "
                      "drained seam")
        return Finding(
            self.id, path, target.lineno,
            "write to %s outside the drain seam — %s"
            % (dotted, detail))


# -- metric hygiene (PR 5's grammar, promoted from the test suite) ---------

#: stricter than METRIC_NAME_RE: the repo convention is lowercase
#: veles_-prefixed tokens (the runtime grammar also allows colons and
#: uppercase, which scrapers accept but this codebase bans)
_METRIC_TOKEN_RE = re.compile(r"^veles_[a-z][a-z0-9_]*$")
_COUNTER_METHODS = {"incr", "counter_set"}
_HISTOGRAM_METHODS = {"observe"}
_GAUGE_METHODS = {"set", "set_gauge_family"}
_METRIC_METHODS = (_COUNTER_METHODS | _HISTOGRAM_METHODS
                   | _GAUGE_METHODS)


def iter_metric_calls(tree):
    """Every registry-method call with a literal ``veles_*`` name:
    ``(node, method, name, label_keys, has_help)`` rows — shared by
    both metric rules and by the test-suite wrapper."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        method = node.func.attr
        if method not in _METRIC_METHODS:
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        name = node.args[0].value
        if not name.startswith("veles_"):
            continue
        labels = []
        has_help = False
        for kw in node.keywords:
            if kw.arg == "labels" and isinstance(kw.value, ast.Dict):
                for key in kw.value.keys:
                    if isinstance(key, ast.Constant):
                        labels.append(key.value)
            if kw.arg == "help" and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value in (None, "")):
                has_help = True
        yield node, method, name, labels, has_help


class MetricNamingRule(Rule):
    """``metric.naming``: every literal ``veles_*`` metric must be a
    lowercase exposition token; counters end ``_total``, histograms end
    ``_seconds``, gauges carry neither suffix; label keys are valid and
    never the reserved ``le`` or ``__``-prefixed."""

    id = "metric.naming"
    family = "metric"
    doc = "veles_* metrics must follow the Prometheus grammar"

    def check_file(self, path, tree, lines):
        for node, method, name, labels, _ in iter_metric_calls(tree):
            where = node.lineno
            if not METRIC_NAME_RE.match(name) \
                    or not _METRIC_TOKEN_RE.match(name):
                yield Finding(
                    self.id, path, where,
                    "%r is not a valid lowercase veles_* metric token"
                    % name)
            if method in _COUNTER_METHODS \
                    and not name.endswith("_total"):
                yield Finding(
                    self.id, path, where,
                    "counter %r must end _total" % name)
            if method in _HISTOGRAM_METHODS \
                    and not name.endswith("_seconds"):
                yield Finding(
                    self.id, path, where,
                    "histogram %r must end _seconds" % name)
            if method in _GAUGE_METHODS \
                    and name.endswith(("_total", "_seconds")):
                yield Finding(
                    self.id, path, where,
                    "gauge %r carries a counter/histogram suffix"
                    % name)
            for label in labels:
                if not isinstance(label, str) \
                        or not LABEL_NAME_RE.match(label) \
                        or label == "le" or label.startswith("__"):
                    yield Finding(
                        self.id, path, where,
                        "bad label key %r on %r (reserved or invalid "
                        "exposition token)" % (label, name))


class MetricHelpRule(Rule):
    """``metric.help``: every metric FAMILY must carry a HELP string at
    (at least) one call site — a family whose every booking omits
    ``help=`` renders a bare ``# HELP`` line dashboards cannot
    explain. Cross-file: reported at the family's first call site.
    WHOLE-PACKAGE rule — on a partial-path run a family's help may
    legitimately live in an unanalyzed file; the CI gate always runs
    the full tree."""

    id = "metric.help"
    family = "metric"
    doc = "every veles_* family needs a HELP string somewhere"

    def configure(self, registry):
        super().configure(registry)
        self._first_site = {}   # name -> (path, line)
        self._has_help = set()

    def check_file(self, path, tree, lines):
        for node, _, name, _, has_help in iter_metric_calls(tree):
            if has_help:
                self._has_help.add(name)
            self._first_site.setdefault(name, (path, node.lineno))
        return ()

    def finalize(self):
        for name, (path, line) in sorted(self._first_site.items()):
            if name not in self._has_help:
                yield Finding(
                    self.id, path, line,
                    "metric family %r never passes help= at any call "
                    "site — add a HELP string at one booking site"
                    % name)


def default_rules():
    """Fresh instances of every shipped rule (order = catalog order)."""
    return [RecordPathRule(), LockOrderingRule(),
            UnpinnedOutShardingsRule(), LocalJitDispatchRule(),
            UnhashableStaticRule(), JitInLoopRule(), ShapeKeyRule(),
            DonationReadAfterDispatchRule(), SharedRmwRule(),
            SwapSeamRule(), MetricNamingRule(), MetricHelpRule()]
