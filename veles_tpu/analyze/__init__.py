"""Invariant-checking static analysis (``veles_tpu analyze``).

Encodes the codebase's hard-won invariants — the flight-recorder lock
discipline, retrace-hazard hygiene, donation safety, the thread-shared-
state census and the Prometheus metric grammar — as executable AST
rules gating CI on NEW violations only (docs/static_analysis.md).
"""

from veles_tpu.analyze.engine import (Finding, ParseError, Rule,
                                      run_analysis)
from veles_tpu.analyze.registry import (AnalysisRegistry,
                                        DEFAULT_REGISTRY)
from veles_tpu.analyze.rules import default_rules

__all__ = ["Finding", "ParseError", "Rule", "run_analysis",
           "AnalysisRegistry", "DEFAULT_REGISTRY", "default_rules"]
