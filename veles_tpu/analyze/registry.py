"""Declarative inputs to the analyzer: WHICH code owes WHICH invariant.

Two of the rule families cannot be inferred from syntax alone — they
encode deployment facts about this codebase's threading model:

- **record-path modules/functions** (``lock.record-path``): code on the
  flight-recorder discipline (PRs 10/12) — called from the serving hot
  path, possibly from several threads, and REQUIRED to stay lock-free,
  I/O-free and device-sync-free. Declared here as a mapping from a
  module path *suffix* to the set of function qualnames owing the
  discipline (``None`` = every function in the module).
- **shared classes** (``shared.rmw``): classes whose instances are
  reachable from BOTH the HTTP handler threads and the serving driver
  thread (or the fleet event loop), so attribute mutations must be
  GIL-atomic single ops or run under the class's lock. Declared as a
  mapping from module path suffix to ``{class name: exempt methods}``
  (``__init__`` is always exempt: no concurrency before publication).

To put a NEW module on the record path or declare a NEW shared class,
extend the literals below (or pass ``--record-path`` / ``--shared-class``
to the CLI for a one-off run) — docs/static_analysis.md walks through
both.

Deliberately NOT declared here:

- ``RequestLedger``/``FlightRecorder``/``MetricHistory`` as shared
  classes: they ARE mutated from several threads, but the flight-
  recorder discipline forbids them the lock that would satisfy
  ``shared.rmw`` — their counters are documented best-effort tallies
  (drift under contention is accepted; the bounded containers stay
  consistent because every container op is a single GIL-atomic call).
  Declaring them would make the two rule families contradict each
  other by construction.
"""

import os

#: module-path suffix -> set of "Class.method"/"function" qualnames on
#: the flight-recorder discipline, or None for the whole module
RECORD_PATH_FUNCTIONS = {
    "observe/reqledger.py": None,
    # note/note_span are the per-span record hooks; dump() runs on the
    # (rare) trip path and legitimately takes _dump_lock + writes
    "observe/flight.py": {"FlightRecorder.note",
                          "FlightRecorder.note_span"},
    # the sampler tick runs on the default-on background thread and on
    # deadline-sensitive governor fallbacks; incident writes happen in
    # _check_rules (anomaly firings only), which is NOT declared
    "observe/history.py": {"MetricHistory.maybe_sample",
                           "MetricHistory.sample",
                           "MetricHistory.record_control",
                           "MetricHistory._ingest",
                           "_Series.push"},
    # the fleet goodput observatory: the span ring sits on the slave's
    # span-finish path, the rest on the master's event loop per frame;
    # incident writes live in FleetScope.autopsy_tick, NOT declared
    "observe/fleetscope.py": {"SpanRing.note_span", "SpanRing.drain",
                              "ClockEstimate.observe",
                              "StepWindow.push",
                              "FleetScope.note_issue",
                              "FleetScope.note_update",
                              "FleetScope.book_update"},
    # the serving goodput observatory: every note_* sits on the
    # serving driver's per-dispatch hot path (and inject_waste on the
    # chaos monkey's before_step, same thread); incident writes live
    # in ServeScope.autopsy_tick, NOT declared
    "observe/servescope.py": {"ServeScope._mark",
                              "ServeScope.note_idle",
                              "ServeScope.note_admit",
                              "ServeScope.note_dispatch",
                              "ServeScope.note_collect",
                              "ServeScope.inject_waste",
                              "ServeScope.note_slot_admit",
                              "ServeScope.note_slot_first",
                              "ServeScope.note_slot_retire"},
    # the HBM attribution plane: scratch tags sit on the admission
    # handler/resolve paths, the lifecycle-edge snapshots on the
    # driver's rebuild/swap/promote seams, note_pool on the governor
    # tick — all GIL-atomic container ops. MemScope is deliberately
    # NOT a shared class (the FlightRecorder doctrine above: its
    # tallies are best-effort, its containers copy-on-write tuples
    # and bounded deques); incident writes live in flush_incidents,
    # NOT declared
    "observe/memscope.py": {"MemScope.scratch_note",
                            "MemScope.scratch_drop",
                            "MemScope.edge_begin",
                            "MemScope.edge_end",
                            "MemScope.note_pool"},
}

#: module-path suffix -> {class name: (exempt method names,)}; every
#: non-exempt method's read-modify-write attribute mutations must sit
#: under a ``with self.<lock>`` (attribute matching LOCK_ATTR_RE)
SHARED_CLASSES = {
    # handler threads admit/record, the driver resolves
    "serving.py": {"ServingHealth": ()},
    # the HTTP pool gate and the driver share the page pool + cache
    "parallel/kv_pool.py": {"PagePool": (), "PrefixCache": ()},
    # scrape threads read windows the driver/handlers feed
    "observe/slo.py": {"SLOEngine": ()},
    # every thread with a metric to book mutates the registry
    "observe/metrics.py": {"MetricsRegistry": ()},
    # jit wrappers on driver + prefetch threads book compile windows
    "observe/xla_stats.py": {"CompileTracker": ()},
    # router handler threads + attempt threads race inside each Lease;
    # handler threads and the control-plane poller share ElasticRouter
    # tallies
    "router.py": {"Lease": (), "ElasticRouter": ()},
    # router handler threads bump lease/failure tallies on a Replica
    # the poller thread scores (the plane's lifecycle state machine
    # itself is single-writer on the poller thread)
    "fleet/serve_plane.py": {"Replica": ()},
}

#: attribute names treated as locks by lock-nesting/census checks —
#: anchored to underscore/name boundaries so ``blocker``/``clock``
#: are NOT classified as locks (a false lock would silently satisfy
#: shared.rmw and mis-fire the lock rules)
LOCK_ATTR_PATTERN = r"(?:^|_)(?:lock|mutex)(?:_|$)"


class AnalysisRegistry:
    """One run's declarations (the default instance mirrors the
    literals above; tests build their own around fixture files)."""

    def __init__(self, record_path=None, shared_classes=None):
        self.record_path = dict(RECORD_PATH_FUNCTIONS
                                if record_path is None else record_path)
        self.shared_classes = dict(SHARED_CLASSES if shared_classes
                                   is None else shared_classes)

    def add_record_path(self, spec):
        """``PATH_SUFFIX[:func,Class.method,...]`` (CLI seam)."""
        path, _, funcs = spec.partition(":")
        names = {f.strip() for f in funcs.split(",") if f.strip()}
        self.record_path[path] = names or None

    def add_shared_class(self, spec):
        """``PATH_SUFFIX:ClassName`` (CLI seam)."""
        path, sep, cls = spec.partition(":")
        if not sep or not cls:
            raise ValueError(
                "shared-class spec %r is not PATH_SUFFIX:ClassName"
                % spec)
        self.shared_classes.setdefault(path, {})[cls] = ()

    @staticmethod
    def _norm(path):
        return path.replace(os.sep, "/") if os.sep != "/" else path

    @classmethod
    def _matches(cls, path, suffix):
        """Suffix match at a path-SEGMENT boundary: ``serving.py``
        matches ``veles_tpu/serving.py`` but never
        ``samples/llm_serving.py`` (a bare endswith would apply one
        module's declarations to any similarly-named file)."""
        norm = cls._norm(path)
        return norm == suffix or norm.endswith("/" + suffix)

    def record_path_functions(self, path):
        """The declared qualnames for ``path`` (``None`` = whole
        module, ``()`` = not a record-path module)."""
        for suffix, funcs in self.record_path.items():
            if self._matches(path, suffix):
                return funcs
        return ()

    def shared_classes_for(self, path):
        """``{class name: exempt methods}`` declared for ``path``."""
        out = {}
        for suffix, classes in self.shared_classes.items():
            if self._matches(path, suffix):
                out.update(classes)
        return out


DEFAULT_REGISTRY = AnalysisRegistry()
