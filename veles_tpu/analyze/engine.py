"""The invariant-checking rule engine: parse once, run every rule.

Twelve PRs of serving/fleet/observability work accumulated hard-won
invariants that existed only as prose in CHANGES.md or as one-off test
assertions — record paths must be lock-free (the flight-recorder
discipline, PRs 10/12), mesh-jitted builders must pin ``out_shardings``
or retrace-storm (PR 6), donated buffers must never be read after
dispatch (PR 9), metric names must follow the Prometheus grammar
(PR 5's naming lint). This package encodes them ONCE, as executable
AST rules, so every future change is checked for free
(docs/static_analysis.md is the catalog).

Design contract:

- a :class:`Finding` carries ``rule id + file:line + message`` — enough
  for a human to act and for the baseline to fingerprint;
- rules are pure AST visitors over one parsed module at a time
  (``check_file``), with an optional cross-file ``finalize`` hook for
  whole-package invariants (HELP-string presence needs every call site
  of a metric family before it can rule);
- unreadable files (syntax errors, undecodable bytes) are collected as
  :class:`ParseError` — the CLI exits 2 on them, never silently skips;
- no third-party imports: the analyzer must run in the leanest CI
  container that can run the test suite.
"""

import ast
import os

from veles_tpu.analyze.registry import DEFAULT_REGISTRY


class Finding:
    """One rule violation at one source location."""

    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule, path, line, message):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.message = message

    def format(self, relative_to=None):
        path = self.path
        if relative_to:
            try:
                path = os.path.relpath(path, relative_to)
            except ValueError:
                pass
        return "%s:%d: [%s] %s" % (path, self.line, self.rule,
                                   self.message)

    def __repr__(self):
        return "Finding(%r, %r, %d, %r)" % (self.rule, self.path,
                                            self.line, self.message)


class ParseError:
    """A file the analyzer could not read or parse (CLI exit 2)."""

    __slots__ = ("path", "message")

    def __init__(self, path, message):
        self.path = path
        self.message = message

    def format(self, relative_to=None):
        path = self.path
        if relative_to:
            try:
                path = os.path.relpath(path, relative_to)
            except ValueError:
                pass
        return "%s: UNREADABLE: %s" % (path, self.message)


class Rule:
    """Base class: subclasses set ``id``/``family``/``doc`` and
    implement :meth:`check_file`; cross-file rules accumulate there and
    emit from :meth:`finalize`."""

    id = None
    family = None
    doc = ""

    def configure(self, registry):
        """Called once per run with the :class:`AnalysisRegistry` in
        effect (the seam the fixture tests use to declare record-path
        modules and shared classes outside the real tree)."""
        self.registry = registry

    def check_file(self, path, tree, lines):
        """Yield :class:`Finding` for one parsed module."""
        return ()

    def finalize(self):
        """Yield findings that need the whole file set (default none)."""
        return ()


def iter_python_files(paths):
    """Expand files/directories into a sorted, deduplicated list of
    ``.py`` files (``__pycache__`` skipped)."""
    out = []
    seen = set()
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        full = os.path.join(dirpath, name)
                        if full not in seen:
                            seen.add(full)
                            out.append(full)
        elif path not in seen:
            seen.add(path)
            out.append(path)
    return out


def match_rules(rules, selector):
    """Filter rule instances by exact id or family prefix (the CLI's
    ``--rule``); unknown selectors raise so a typo cannot silently
    analyze nothing."""
    if not selector:
        return list(rules)
    picked = [r for r in rules
              if r.id == selector or r.family == selector
              or r.id.startswith(selector + ".")]
    if not picked:
        raise ValueError(
            "unknown rule %r (known: %s)"
            % (selector, ", ".join(sorted(r.id for r in rules))))
    return picked


def run_analysis(paths, rules=None, rule_filter=None, registry=None):
    """Run ``rules`` over every python file under ``paths``.

    Returns ``(findings, errors)`` — findings sorted by
    ``(path, line, rule)``, errors as :class:`ParseError` rows.
    """
    from veles_tpu.analyze.rules import default_rules

    registry = registry if registry is not None else DEFAULT_REGISTRY
    rules = list(rules) if rules is not None else default_rules()
    rules = match_rules(rules, rule_filter)
    for rule in rules:
        rule.configure(registry)
    findings, errors = [], []
    for path in iter_python_files(paths):
        try:
            with open(path, "rb") as fin:
                source = fin.read().decode("utf-8")
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError, UnicodeDecodeError) as exc:
            errors.append(ParseError(path, str(exc)))
            continue
        lines = source.splitlines()
        for rule in rules:
            findings.extend(rule.check_file(path, tree, lines))
    for rule in rules:
        findings.extend(rule.finalize())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, errors
