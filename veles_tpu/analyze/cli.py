"""``veles_tpu analyze [PATHS] [--rule ID] [--baseline PATH]
[--update-baseline]`` — the invariant gate.

Exit codes (the ``aot verify`` convention):

- **0** — clean: no findings beyond the baseline;
- **1** — findings: NEW violations printed one per line as
  ``path:line: [rule] message``;
- **2** — unreadable: a file failed to parse (syntax error, bad
  encoding) — the gate refuses to vouch for code it could not read.

``--update-baseline`` re-records every current finding (preserving
justifications of surviving fingerprints) and exits 0 — the workflow
for adopting the gate on a tree with triaged pre-existing findings.
"""

import argparse
import os
import sys

#: picked up from the working directory when --baseline is omitted —
#: `veles_tpu analyze veles_tpu/` run at the repo root gates against
#: the committed baseline with no extra flags
DEFAULT_BASELINE = "analyze_baseline.json"


def build_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu analyze",
        description="invariant-checking static analysis "
                    "(docs/static_analysis.md)")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to analyze "
                             "(default: the veles_tpu package)")
    parser.add_argument("--rule", default=None, metavar="ID",
                        help="run one rule id (e.g. metric.naming) or "
                             "family (e.g. retrace)")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help="baseline file of triaged findings "
                             "(default: ./%s when present)"
                             % DEFAULT_BASELINE)
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from the current "
                             "findings and exit 0")
    parser.add_argument("--record-path", action="append", default=[],
                        metavar="SUFFIX[:FUNC,...]",
                        help="declare an extra record-path module for "
                             "this run (see analyze/registry.py for "
                             "the committed declarations)")
    parser.add_argument("--shared-class", action="append", default=[],
                        metavar="SUFFIX:CLASS",
                        help="declare an extra thread-shared class "
                             "for this run")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    return parser


def main(argv=None):
    from veles_tpu.analyze.baseline import (apply_baseline,
                                            write_baseline)
    from veles_tpu.analyze.engine import run_analysis
    from veles_tpu.analyze.registry import AnalysisRegistry
    from veles_tpu.analyze.rules import default_rules

    parser = build_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print("%-32s %s" % (rule.id, rule.doc))
        return 0

    if args.update_baseline and args.rule:
        parser.error("--update-baseline cannot be combined with "
                     "--rule: a rule-filtered rewrite would silently "
                     "drop every other rule's baselined entries")

    paths = args.paths
    if not paths:
        paths = [os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))]
    baseline = args.baseline
    if baseline is None and os.path.exists(DEFAULT_BASELINE):
        baseline = DEFAULT_BASELINE

    registry = AnalysisRegistry()
    for spec in args.record_path:
        registry.add_record_path(spec)
    for spec in args.shared_class:
        try:
            registry.add_shared_class(spec)
        except ValueError as exc:
            parser.error(str(exc))

    try:
        findings, errors = run_analysis(paths, rule_filter=args.rule,
                                        registry=registry)
    except ValueError as exc:   # unknown --rule selector
        parser.error(str(exc))

    cwd = os.getcwd()
    if errors:
        for error in errors:
            print(error.format(relative_to=cwd), file=sys.stderr)
        print("%d unreadable file(s) — refusing to vouch for code "
              "the analyzer could not parse" % len(errors),
              file=sys.stderr)
        return 2

    if args.update_baseline:
        from veles_tpu.analyze.engine import iter_python_files
        target = baseline or DEFAULT_BASELINE
        # scope the rewrite to the files this run analyzed: entries
        # for other subtrees carry over untouched
        count = write_baseline(findings, target,
                               analyzed_paths=iter_python_files(paths))
        print("baseline %s: %d finding(s) recorded" % (target, count))
        return 0

    try:
        new, suppressed = apply_baseline(findings, baseline)
    except (OSError, ValueError) as exc:
        # a merge-mangled baseline is an "unreadable input", not "new
        # findings" — misreporting it as exit 1 sends the triager
        # hunting for violations that do not exist
        print("baseline %s: UNREADABLE: %s" % (baseline, exc),
              file=sys.stderr)
        return 2
    for finding in new:
        print(finding.format(relative_to=cwd))
    if new:
        print("%d new finding(s)%s — fix them or triage into the "
              "baseline with --update-baseline"
              % (len(new),
                 " (%d baselined)" % len(suppressed)
                 if suppressed else ""))
        return 1
    print("clean: 0 new findings%s"
          % (" (%d baselined)" % len(suppressed) if suppressed else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
