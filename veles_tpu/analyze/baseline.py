"""Committed baseline: CI gates on NEW violations only.

A static-analysis gate that fires on day-one findings gets disabled
within a week. The baseline file records every finding the team has
triaged as pre-existing (with an optional ``justification`` naming WHY
it is acceptable or deferred); ``veles_tpu analyze`` subtracts it, so
the exit code reflects only violations this change introduced.

Fingerprints are LINE-NUMBER-INDEPENDENT: ``sha1(rule, relative path,
stripped source line, occurrence index among identical lines)`` — an
unrelated edit above a baselined finding must not resurrect it, while
moving the offending line to a new file (or duplicating it) does
surface it again. Paths are stored relative to the baseline file's own
directory so the file is position-independent across checkouts.

``--update-baseline`` rewrites the file from the current findings,
preserving justifications of entries whose fingerprint survives.
"""

import hashlib
import json
import os


def fingerprint(rule, rel_path, line_text, occurrence):
    payload = "\0".join((rule, rel_path, line_text.strip(),
                         str(occurrence)))
    return hashlib.sha1(payload.encode("utf-8")).hexdigest()


def _finding_rows(findings, base_dir):
    """``(finding, fingerprint, rel_path, line_text)`` rows with
    per-(rule, path, line-text) occurrence counting."""
    counts = {}
    rows = []
    sources = {}
    for finding in findings:
        path = os.path.abspath(finding.path)
        if path not in sources:
            try:
                with open(path, "rb") as fin:
                    sources[path] = fin.read().decode(
                        "utf-8", "replace").splitlines()
            except OSError:
                sources[path] = []
        lines = sources[path]
        text = lines[finding.line - 1] \
            if 0 < finding.line <= len(lines) else ""
        rel = os.path.relpath(path, base_dir).replace(os.sep, "/")
        key = (finding.rule, rel, text.strip())
        occurrence = counts.get(key, 0)
        counts[key] = occurrence + 1
        rows.append((finding, fingerprint(finding.rule, rel, text,
                                          occurrence), rel, text))
    return rows


def load_baseline(path):
    """``{fingerprint: entry dict}`` from a baseline file (empty when
    the file does not exist — a missing baseline suppresses nothing)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as fin:
        data = json.load(fin)
    if not isinstance(data, dict) or "findings" not in data \
            or not isinstance(data["findings"], list):
        raise ValueError("baseline %s is not a "
                         '{"version": 1, "findings": [...]} document'
                         % path)
    out = {}
    for entry in data["findings"]:
        # a merge-mangled entry must surface as ValueError (CLI exit 2
        # / write_baseline rebuild), never as a KeyError traceback
        if not isinstance(entry, dict) or not entry.get("fingerprint"):
            raise ValueError(
                "baseline %s has an entry without a fingerprint "
                "(merge-mangled?): %r" % (path, entry))
        out[entry["fingerprint"]] = entry
    return out


def apply_baseline(findings, baseline_path):
    """Split findings into ``(new, suppressed)`` against the baseline
    at ``baseline_path``."""
    base_dir = os.path.dirname(os.path.abspath(baseline_path)) \
        if baseline_path else os.getcwd()
    entries = load_baseline(baseline_path)
    new, suppressed = [], []
    for finding, print_, _, _ in _finding_rows(findings, base_dir):
        (suppressed if print_ in entries else new).append(finding)
    return new, suppressed


def write_baseline(findings, baseline_path, analyzed_paths=None):
    """Rewrite the baseline from the current findings, preserving the
    ``justification`` of every surviving fingerprint; returns the
    entry count.

    ``analyzed_paths`` (absolute file paths this run actually looked
    at) scopes the rewrite: previous entries for files OUTSIDE the
    analyzed set are carried over untouched — updating the baseline
    from a subtree must not silently drop another subtree's triaged
    findings. ``None`` means a full rewrite."""
    base_dir = os.path.dirname(os.path.abspath(baseline_path)) \
        or os.getcwd()
    previous = {}
    try:
        previous = load_baseline(baseline_path)
    except ValueError:  # json.JSONDecodeError subclasses ValueError
        pass  # a corrupt baseline is rebuilt from scratch
    entries = []
    seen = set()
    for finding, print_, rel, text in _finding_rows(findings, base_dir):
        if print_ in seen:
            continue
        seen.add(print_)
        entry = {"rule": finding.rule, "path": rel,
                 "line": finding.line, "source": text.strip(),
                 "message": finding.message, "fingerprint": print_}
        justification = previous.get(print_, {}).get("justification")
        if justification:
            entry["justification"] = justification
        entries.append(entry)
    if analyzed_paths is not None:
        analyzed_rel = {
            os.path.relpath(os.path.abspath(p),
                            base_dir).replace(os.sep, "/")
            for p in analyzed_paths}
        for entry in previous.values():
            if entry.get("path") in analyzed_rel \
                    or entry["fingerprint"] in seen:
                continue
            # prune entries for deleted/renamed files — carried-over
            # fingerprints must still point at code that exists
            if not os.path.exists(os.path.join(base_dir,
                                               entry.get("path", ""))):
                continue
            seen.add(entry["fingerprint"])
            entries.append(entry)
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    tmp = baseline_path + ".tmp"
    with open(tmp, "w") as fout:
        json.dump({"version": 1, "findings": entries}, fout, indent=1,
                  sort_keys=True)
        fout.write("\n")
    os.replace(tmp, baseline_path)
    return len(entries)
