"""Accuracy-parity harness: a pass/fail artifact against the reference
anchors (VERDICT r2 #3).

The reference publishes three MNIST validation-error anchors
(``docs/source/manualrst_veles_example.rst:55-66``):

    MNIST784 (784→100 tanh→10 softmax)   1.92%  → bound 2.2%
    mnist "caffe" (LeNet-style convnet)   0.86%  → bound 1.0%
    mnist conv (tanh convnet)             0.73%  → bound 0.9%

``run_parity(mnist_dir=...)`` trains the three topologies with the
reference hyperparameters on real idx files and asserts those bounds.

Without MNIST (this build environment has zero egress and the idx
files exist nowhere in the image) the harness runs the same three
topology FAMILIES on the sklearn ``load_digits`` set — **real scanned
handwriting** (the UCI Optical Recognition of Handwritten Digits test
fold: 1797 8x8 scans from 43 writers; earlier rounds mislabeled this
tier "synthetic"), 1500 train / 297 validation — with ABSOLUTE bounds
chosen at the reference anchors' tightness class (VERDICT r4 #2/#8:
the 6% bounds were loose; these are sub-1% for both convnets):

    digits784 MLP                         measured 2.36%  → bound 3.0%
    digits "caffe" (relu convnet)         measured 0.00%  → bound 0.7%
    digits conv (tanh convnet)            measured 0.34%  → bound 0.7%

The convnet families train with the ``shift1`` in-jit augmentation
(``ops/augment.py`` — the reference ImageLoader's random crop-offset
role), which is what carries them past the anchor-class error rates.
Either way the outcome is written to ``PARITY.json``.

One command: ``python -m veles_tpu parity [--mnist-dir DIR] [--out F]``.
The exact layer stacks of the two convnets live in the absent znicz
submodule (SURVEY preamble); they are reconstructed LeNet-style from the
documented anchors and the caffe naming.
"""

import json
import os
import time

from veles_tpu.core import prng
from veles_tpu.core.config import root
from veles_tpu.core.logger import Logger
from veles_tpu.loader.base import VALID

#: (name, layer specs for 28x28x1 MNIST, trainer kwargs, bound %)
MNIST_TOPOLOGIES = (
    ("mnist784", [
        {"type": "all2all_tanh", "output_sample_shape": (100,)},
        {"type": "softmax", "output_sample_shape": (10,)},
    ], dict(learning_rate=0.03, gradient_moment=0.9, minibatch_size=100,
            max_epochs=50, fail_iterations=25, flat=True), 2.2),
    ("mnist_caffe", [
        {"type": "conv", "n_kernels": 20, "kx": 5, "ky": 5},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "conv", "n_kernels": 50, "kx": 5, "ky": 5},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "all2all_relu", "output_sample_shape": (500,)},
        {"type": "softmax", "output_sample_shape": (10,)},
    ], dict(learning_rate=0.01, gradient_moment=0.9, weights_decay=5e-4,
            minibatch_size=100, max_epochs=40, fail_iterations=20,
            flat=False), 1.0),
    ("mnist_conv", [
        {"type": "conv_tanh", "n_kernels": 32, "kx": 5, "ky": 5},
        {"type": "maxabs_pooling", "kx": 2, "ky": 2},
        {"type": "conv_tanh", "n_kernels": 64, "kx": 5, "ky": 5},
        {"type": "maxabs_pooling", "kx": 2, "ky": 2},
        {"type": "all2all_tanh", "output_sample_shape": (100,)},
        {"type": "softmax", "output_sample_shape": (10,)},
    ], dict(learning_rate=0.02, gradient_moment=0.9, minibatch_size=100,
            max_epochs=40, fail_iterations=20, flat=False), 0.9),
)

#: the same families on the real 8x8 UCI digits (297 validation
#: samples; 1 error = 0.337%); bounds are ABSOLUTE and deterministic
#: under the pinned seeds. All three train on NHWC data with the
#: shift1 augmentation (measured: 2.36% / 0.00% / 0.34%)
DIGITS_TOPOLOGIES = (
    ("digits784", [
        {"type": "all2all_tanh", "output_sample_shape": (100,)},
        {"type": "softmax", "output_sample_shape": (10,)},
    ], dict(learning_rate=0.03, gradient_moment=0.9, minibatch_size=100,
            max_epochs=170, fail_iterations=60, flat=False), 3.0),
    ("digits_caffe", [
        {"type": "conv", "n_kernels": 32, "kx": 3, "ky": 3},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "conv", "n_kernels": 64, "kx": 3, "ky": 3},
        {"type": "all2all_relu", "output_sample_shape": (128,)},
        {"type": "softmax", "output_sample_shape": (10,)},
    ], dict(learning_rate=0.01, gradient_moment=0.9, weights_decay=5e-4,
            minibatch_size=100, max_epochs=150, fail_iterations=60,
            flat=False), 0.7),
    ("digits_conv", [
        {"type": "conv_tanh", "n_kernels": 32, "kx": 3, "ky": 3},
        {"type": "maxabs_pooling", "kx": 2, "ky": 2},
        {"type": "conv_tanh", "n_kernels": 64, "kx": 3, "ky": 3},
        {"type": "all2all_tanh", "output_sample_shape": (128,)},
        {"type": "softmax", "output_sample_shape": (10,)},
    ], dict(learning_rate=0.02, gradient_moment=0.9, minibatch_size=100,
            max_epochs=220, fail_iterations=110, flat=False), 0.7),
)


#: THE canonical digits split: sklearn digits, RandomState(0)
#: permutation, [test=0, valid=297, train=1500]. The fusion/pod/fleet
#: parity tests (via ``tests/dataset_fixtures.py``) and this harness all
#: depend on the exact same bytes — change it HERE only.
DIGITS_CLASS_LENGTHS = [0, 297, 1500]


def digits_dataset(flat=True):
    import numpy
    from sklearn.datasets import load_digits
    digits = load_digits()
    X = digits.data.astype(numpy.float32)
    y = digits.target.astype(numpy.int32)
    perm = numpy.random.RandomState(0).permutation(len(X))
    X, y = X[perm], y[perm]
    if not flat:
        X = X.reshape(-1, 8, 8, 1)
    return X, y


def _train_one(name, layers, trainer, mnist_dir, log):
    """Train one topology; returns (val_error_pct, epochs, best_epoch)."""
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.standard import StandardWorkflow

    trainer = dict(trainer)
    flat = trainer.pop("flat")
    minibatch_size = trainer.pop("minibatch_size")
    max_epochs = trainer.pop("max_epochs")
    fail_iterations = trainer.pop("fail_iterations")
    prng.get("default").seed(1234)
    prng.get("loader").seed(5678)
    if mnist_dir:
        from veles_tpu.loader.mnist import MNISTLoader
        loader_cls = MNISTLoader
        loader_kwargs = dict(directory=mnist_dir, url_base=None,
                             flat=flat, minibatch_size=minibatch_size,
                             normalization_type="linear")
    else:
        from veles_tpu.loader.fullbatch import FullBatchLoader
        X, y = digits_dataset(flat)
        loader_cls = FullBatchLoader
        loader_kwargs = dict(data=X, labels=y,
                             class_lengths=DIGITS_CLASS_LENGTHS,
                             minibatch_size=minibatch_size,
                             normalization_type="linear")
        if not flat:
            # the +-1 px random-shift augmentation (in-jit, both
            # engines) is what carries the digits families to the
            # anchor-class error rates — see module docstring
            loader_kwargs["train_transform"] = "shift1"
    wf = StandardWorkflow(
        DummyLauncher(), layers=layers, loader_cls=loader_cls,
        loader_kwargs=loader_kwargs,
        decision_kwargs=dict(max_epochs=max_epochs,
                             fail_iterations=fail_iterations),
        name=name, **trainer)
    wf.initialize()
    wf.run()
    decision = wf.decision
    n_valid = max(wf.loader.effective_class_lengths[VALID], 1)
    best = decision.best_n_err[VALID]
    error_pct = 100.0 * best / n_valid if best is not None else 100.0
    log.info("%s: best validation error %.2f%% (%s/%d) at epoch %d "
             "after %d epochs", name, error_pct, best, n_valid,
             decision.best_epoch, decision.epochs_done)
    return error_pct, decision.epochs_done, decision.best_epoch


def run_parity(mnist_dir=None, out="PARITY.json", topologies=None):
    """Train the parity set and write the verdict artifact. Returns the
    verdict dict; ``pass`` is the overall outcome."""
    log = Logger(logger_name="parity")
    if mnist_dir is None:
        mnist_dir = os.environ.get("VELES_TPU_MNIST_DIR") or None
    mode = "real-mnist" if mnist_dir else "real-digits-8x8"
    table = topologies or (MNIST_TOPOLOGIES if mnist_dir
                           else DIGITS_TOPOLOGIES)
    if not mnist_dir:
        log.warning("no MNIST directory (set VELES_TPU_MNIST_DIR or "
                    "pass --mnist-dir): running the real-data 8x8 "
                    "digits tier (UCI handwritten scans) with absolute "
                    "bounds")
    saved = (root.common.disable.get("plotting", False),
             root.common.disable.get("snapshotting", False))
    root.common.disable.plotting = True
    root.common.disable.snapshotting = True
    results = []
    try:
        for name, layers, trainer, bound in table:
            start = time.time()
            try:
                error_pct, epochs, best_epoch = _train_one(
                    name, layers, trainer, mnist_dir, log)
                entry = {"name": name,
                         "val_error_pct": round(error_pct, 3),
                         "bound_pct": bound, "pass": error_pct <= bound,
                         "epochs": epochs, "best_epoch": best_epoch}
            except Exception as exc:  # one failure must not hide the rest
                log.exception("%s failed", name)
                entry = {"name": name, "error": "%s: %s"
                         % (type(exc).__name__, exc), "pass": False,
                         "bound_pct": bound}
            entry["seconds"] = round(time.time() - start, 1)
            results.append(entry)
    finally:
        # restore: callers (a pytest session, a notebook) keep their
        # own plotting/snapshotting behavior after the harness returns
        root.common.disable.plotting, \
            root.common.disable.snapshotting = saved
    verdict = {
        "mode": mode,
        "anchors": "docs/source/manualrst_veles_example.rst:55-66 "
                   "(1.92% / 0.86% / 0.73%)",
        "results": results,
        "pass": all(r["pass"] for r in results),
    }
    if out:
        with open(out, "w") as fout:
            json.dump(verdict, fout, indent=1)
        log.info("parity verdict (%s): %s -> %s", mode,
                 "PASS" if verdict["pass"] else "FAIL", out)
    return verdict


def main(argv=None):
    """``python -m veles_tpu parity`` entry."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="veles_tpu parity",
        description="train the reference parity topologies and write "
                    "a pass/fail PARITY.json")
    parser.add_argument("--mnist-dir", default=None,
                        help="directory with the 4 MNIST idx(.gz) files "
                             "(default: $VELES_TPU_MNIST_DIR, else the "
                             "synthetic-digits analogue runs)")
    parser.add_argument("--out", default="PARITY.json")
    args = parser.parse_args(argv)
    from veles_tpu.core.logger import setup_logging
    setup_logging()
    verdict = run_parity(mnist_dir=args.mnist_dir, out=args.out)
    print(json.dumps(verdict, indent=1))
    return 0 if verdict["pass"] else 1
