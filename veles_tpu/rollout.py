"""Blue-green rollout with SLO-burn auto-rollback.

The zero-downtime deploy plane's second half (docs/zero_downtime.md;
the first is ``ContinuousDecoder.swap_params`` behind the driver's
drain seam). ``GenerateAPI.begin_rollout`` builds + probes a SECOND
decode engine ("green") on the candidate weights while the primary
("blue") keeps serving; this module owns everything after that probe:

- **traffic shifting** — tenants hash to a FIXED point in [0, 1)
  (``crc32(tenant) / 10000``-bucketed); the rollout's current fraction
  is the cut line, so raising it only ever ADDS tenants to green — a
  tenant never flaps between engines mid-ladder, and the blue slice's
  token streams stay byte-identical to a no-deploy run (the
  bit-identity contract, asserted by ``tests/test_deploy.py``);
- **the rollback predicate** — the green slice's burn rate and TTFT
  trend against the BLUE slice's concurrent baseline (never an
  absolute threshold: if blue is burning too, the regression is the
  environment's, not the candidate's — rollback is suppressed and the
  suppression is itself a ledger-visible actuation). Both feeds are
  recorded as ``veles_ctrl_deploy_*`` control series in the
  MetricHistory, so the incident autopsy replays exactly what the
  predicate saw;
- **hysteresis + cooldown** — ``breach_for`` consecutive bad ticks
  roll back (one tick is noise); shifts wait out
  ``max(hold_s, cooldown_s)``; a suppression notes at most once per
  cooldown. Rollback drains green first — every green in-flight
  request finishes on the candidate weights (zero shed), then the
  driver retires the engine;
- **incident artifacts** — a rollback (or a swap-probe failure) fires
  a detector-owned anomaly rule (``external=True``, the
  fleetscope/servescope idiom: state synced HERE, never by the
  sampler) so the cooldown-limited incident bundle names the leading
  indicator — which plane broke first, burn or ttft — beside the
  history windows that show it.

Configuration: ``root.common.serve.rollout.*`` (see
:meth:`RolloutConfig.from_config`).
"""

import collections
import time
import zlib

from veles_tpu.core.logger import Logger

#: control-series names (recorded per tick, labels=(("version", role),))
BURN_SERIES = "veles_ctrl_deploy_burn"
TTFT_SERIES = "veles_ctrl_deploy_ttft_ms"
SWAP_SERIES = "veles_ctrl_deploy_swap_failed"

#: tenant-hash resolution: fractions are effectively quantized to
#: 1/10000, plenty for bounded tenant ids
_HASH_BUCKETS = 10000


class RolloutConfig:
    """Validated rollout knobs.

    - ``steps``: the traffic-fraction ladder (sorted, each in (0, 1];
      1.0 is appended when missing — a rollout always ends at full
      traffic or rolled back);
    - ``hold_s`` / ``cooldown_s``: minimum dwell per rung / minimum
      gap between ledger-visible actuations (shift, suppression);
    - ``window_s`` / ``min_requests``: the trend window and the
      zero-traffic guard — fewer green requests than ``min_requests``
      in the window means NO verdict (never a false rollback on an
      idle slice);
    - ``burn_ratio`` / ``burn_floor``: green rolls back when its burn
      >= ``burn_ratio * max(blue_burn, burn_floor)`` — the floor keeps
      a 0-burn blue baseline from making any green imperfection
      infinitely worse;
    - ``ttft_ratio`` / ``ttft_floor_s``: same shape for the TTFT mean;
    - ``blue_burn_veto``: blue burning at/above this suppresses
      rollback (the regression is ambient);
    - ``breach_for``: consecutive bad ticks before rolling back;
    - ``interval_s``: tick rate limit (rides the driver loop).
    """

    KEYS = ("steps", "hold_s", "cooldown_s", "window_s",
            "min_requests", "burn_ratio", "burn_floor", "ttft_ratio",
            "ttft_floor_s", "blue_burn_veto", "breach_for",
            "interval_s")

    def __init__(self, steps=(0.1, 0.5, 1.0), hold_s=20.0,
                 cooldown_s=30.0, window_s=120.0, min_requests=6,
                 burn_ratio=2.0, burn_floor=1.0, ttft_ratio=3.0,
                 ttft_floor_s=0.02, blue_burn_veto=6.0, breach_for=2,
                 interval_s=1.0, flag="root.common.serve.rollout"):
        if isinstance(steps, str):
            steps = tuple(float(s) for s in steps.split("+") if s)
        steps = tuple(float(s) for s in steps)
        if not steps:
            raise ValueError("%s: steps must not be empty" % flag)
        for frac in steps:
            if not 0.0 < frac <= 1.0:
                raise ValueError(
                    "%s: every step must be a traffic fraction in "
                    "(0, 1], got %r" % (flag, frac))
        if list(steps) != sorted(steps):
            raise ValueError("%s: steps %r must be ascending"
                             % (flag, steps))
        if steps[-1] < 1.0:
            steps = steps + (1.0,)
        self.steps = steps
        self.hold_s = float(hold_s)
        self.cooldown_s = float(cooldown_s)
        if self.hold_s < 0 or self.cooldown_s < 0:
            raise ValueError("%s: hold_s/cooldown_s must be >= 0"
                             % flag)
        self.window_s = float(window_s)
        if self.window_s <= 0:
            raise ValueError("%s: window_s must be > 0" % flag)
        self.min_requests = int(min_requests)
        if self.min_requests < 1:
            raise ValueError("%s: min_requests must be >= 1" % flag)
        self.burn_ratio = float(burn_ratio)
        self.ttft_ratio = float(ttft_ratio)
        if self.burn_ratio < 1.0 or self.ttft_ratio < 1.0:
            raise ValueError(
                "%s: burn_ratio/ttft_ratio must be >= 1 (green is "
                "compared AGAINST blue)" % flag)
        self.burn_floor = float(burn_floor)
        self.ttft_floor_s = float(ttft_floor_s)
        if self.burn_floor <= 0 or self.ttft_floor_s <= 0:
            raise ValueError(
                "%s: burn_floor/ttft_floor_s must be > 0 (the ratio "
                "needs a nonzero baseline)" % flag)
        self.blue_burn_veto = float(blue_burn_veto)
        if self.blue_burn_veto <= 0:
            raise ValueError("%s: blue_burn_veto must be > 0" % flag)
        self.breach_for = int(breach_for)
        if self.breach_for < 1:
            raise ValueError("%s: breach_for must be >= 1" % flag)
        self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            raise ValueError("%s: interval_s must be > 0" % flag)

    @classmethod
    def from_config(cls, flag="root.common.serve.rollout"):
        """Build from ``root.common.serve.rollout.*`` (defaults apply
        for any unset key)."""
        from veles_tpu.core.config import root
        cfg = root.common.serve.rollout
        kwargs = {}
        for key in cls.KEYS:
            value = cfg.get(key, None)
            if value is not None:
                kwargs[key] = value
        return cls(flag=flag, **kwargs)


def _history():
    """The process MetricHistory, or None (a rollout without one
    still shifts/rolls back — only the autopsy trail is thinner)."""
    try:
        from veles_tpu.observe.history import get_metric_history
        return get_metric_history()
    except Exception:
        return None


def ensure_deploy_rules(history):
    """Register the detector-owned deploy anomaly rules (idempotent by
    name). ``external=True``: the ROLLOUT syncs their state and
    decides firing — the sampler never evaluates them (its window
    semantics would race the predicate's and double-fire)."""
    from veles_tpu.observe.history import AnomalyRule

    have = {rule.name for rule in history.rules}
    specs = (
        ("deploy_green_burn", BURN_SERIES),
        ("deploy_green_ttft", TTFT_SERIES),
        ("deploy_swap_probe", SWAP_SERIES),
    )
    out = {}
    for name, series in specs:
        if name not in have:
            rule = AnomalyRule(name, series, kind="threshold",
                               op=">=", threshold=0.0, for_samples=1,
                               cooldown_s=5.0, exclude_labels=())
            rule.external = True
            history.add_rule(rule)
        out[name] = next(r for r in history.rules if r.name == name)
    return out


def _fire_rule(history, rule, value, labels, now, reason):
    """Manually fire one detector-owned rule (the servescope idiom):
    sync its breach state, bump the anomaly counters, note the flight
    ring, trigger the cooldown-limited incident artifact."""
    rule.last_value = value
    rule.streak = max(rule.streak, 1)
    if rule.breach_since is None:
        rule.breach_since = now
    rule.breach_value = value
    rule.breach_labels = tuple(labels)
    if rule.last_fired is not None \
            and now - rule.last_fired < rule.cooldown_s:
        return None
    rule.last_fired = now
    rule.fired_total += 1
    firing = {"rule": rule.name, "series": rule.series,
              "kind": rule.kind, "value": round(float(value), 6),
              "labels": [list(kv) for kv in (labels or ())],
              "breach_since": rule.breach_since, "mono": now,
              "reason": reason}
    history.anomalies_total += 1
    try:
        if history.registry.enabled:
            history.registry.incr(
                "veles_anomaly_fired_total",
                labels={"rule": rule.name},
                help="anomaly-rule firings (observe/history.py)")
    except Exception:
        pass
    try:
        from veles_tpu.observe.flight import get_flight_recorder
        get_flight_recorder().note(
            "anomaly", rule=rule.name, series=rule.series,
            value=firing["value"], breach_since=rule.breach_since)
    except Exception:
        pass
    return history.incidents.trigger(history, rule, firing, now=now)


def _clear_rules(history):
    """Drop the deploy rules' breach state (terminal rollout states):
    a finished rollout must not keep polluting LATER incidents'
    leading-indicator ordering."""
    if history is None:
        return
    for rule in history.rules:
        if rule.name.startswith("deploy_"):
            rule.streak = 0
            rule.breach_since = None
            rule.breach_value = None
            rule.breach_labels = None


def note_swap_failure(reason, version=None, now=None):
    """Book a refused hot-swap (``GenerateAPI._apply_swap``'s failure
    path) into the observability plane: the ``deploy_swap_probe``
    rule fires and the incident artifact names the swap probe as the
    leading indicator. Never raises — a broken autopsy must not mask
    the (already handled) swap failure."""
    history = _history()
    if history is None:
        return None
    if now is None:
        now = time.monotonic()
    try:
        labels = (("version", str(version or "swap")),)
        history.record_control(SWAP_SERIES, 1.0, labels=labels,
                               now=now)
        rules = ensure_deploy_rules(history)
        path = _fire_rule(history, rules["deploy_swap_probe"], 1.0,
                          labels, now, reason)
        # one-shot event, not an ongoing breach: clear so the next
        # incident's leading indicator is not anchored here forever
        _clear_rules(history)
        return path
    except Exception:
        import logging
        logging.getLogger("serve.Rollout").exception(
            "swap-failure bookkeeping failed (swallowed)")
        return None


class BlueGreenRollout(Logger):
    """One rollout's controller. Owned by the GenerateAPI driver
    thread (``tick`` and every state transition run on it — no lock);
    the request-feed methods (:meth:`note_ttft`,
    :meth:`note_resolved`) only append to bounded deques, safe from
    the driver or a handler's backstop under the GIL.

    States: ``shifting`` -> ``promote_ready`` -> ``promoted`` on the
    happy path; ``rolling_back`` -> ``rolled_back`` when the
    predicate (or an engine failure / breaker trip) ends it.
    """

    def __init__(self, version, config=None, clock=time.monotonic):
        super().__init__(logger_name="serve.Rollout")
        self.version = str(version)
        self.config = config if config is not None else RolloutConfig()
        self._clock = clock
        self.state = "shifting"
        self.reason = None
        #: index into config.steps — the CURRENT fraction rung
        self.step_index = 0
        self.started_at = None
        self._last_shift = None
        self._last_tick = None
        self._last_suppress = None
        self._breaches = 0
        self.suppressed_total = 0
        #: per-role request feeds: (mono, value) / (mono, ok)
        self._ttft = {"green": collections.deque(maxlen=2048),
                      "blue": collections.deque(maxlen=2048)}
        self._resolved = {"green": collections.deque(maxlen=4096),
                          "blue": collections.deque(maxlen=4096)}

    # -- routing ----------------------------------------------------------
    @property
    def fraction(self):
        """The green traffic fraction in effect."""
        if self.state in ("promote_ready", "promoted"):
            return 1.0
        if self.state in ("rolling_back", "rolled_back"):
            return 0.0
        return self.config.steps[self.step_index]

    def routes_green(self, tenant):
        """Engine choice for one tenant: its FIXED hash point against
        the current fraction. Raising the fraction only ADDS tenants
        to green; a tenant never moves back to blue mid-ladder (and
        blue tenants' streams stay byte-identical to a no-deploy
        run)."""
        point = (zlib.crc32(str(tenant or "").encode("utf-8"))
                 % _HASH_BUCKETS) / float(_HASH_BUCKETS)
        return point < self.fraction

    # -- request feeds (any thread) ---------------------------------------
    def note_ttft(self, role, seconds, now=None):
        feed = self._ttft.get(role)
        if feed is not None:
            feed.append((now if now is not None else self._clock(),
                         float(seconds)))

    def note_resolved(self, role, ok, now=None):
        feed = self._resolved.get(role)
        if feed is not None:
            feed.append((now if now is not None else self._clock(),
                         bool(ok)))

    # -- the predicate (driver thread) ------------------------------------
    def _window_stats(self, role, now):
        """(total, failures, mean_ttft_s|None) over the trailing
        window for one role."""
        horizon = now - self.config.window_s
        total = fails = 0
        for stamp, ok in self._resolved[role]:
            if stamp >= horizon:
                total += 1
                if not ok:
                    fails += 1
        ttfts = [v for t, v in self._ttft[role] if t >= horizon]
        mean = sum(ttfts) / len(ttfts) if ttfts else None
        return total, fails, mean

    def _burn(self, api, role, total, fails):
        """The role's burn rate: the SLO engine's per-version slice
        when one is configured (the REAL objectives), else the raw
        failure share against an implied 99%% availability target.
        None = no traffic."""
        engine = getattr(api, "slo", None)
        if engine is not None:
            try:
                row = engine.version_burn(role)
            except Exception:
                row = None
            if row is not None:
                return float(row["burn_rate"])
        if not total:
            return None
        return (fails / float(total)) / 0.01

    def tick(self, api, now=None):
        """One predicate pass (rate-limited; rides the driver loop
        beside the governor's tick). Reads both slices, records the
        control series, syncs the detector-owned rules, and either
        shifts, holds, suppresses, or rolls back."""
        if self.state not in ("shifting", "promote_ready"):
            return
        if now is None:
            now = self._clock()
        if self._last_tick is not None \
                and now - self._last_tick < self.config.interval_s:
            return
        self._last_tick = now
        if self.started_at is None:
            self.started_at = now
            self._last_shift = now
        cfg = self.config
        g_total, g_fails, g_ttft = self._window_stats("green", now)
        b_total, b_fails, b_ttft = self._window_stats("blue", now)
        g_burn = self._burn(api, "green", g_total, g_fails)
        b_burn = self._burn(api, "blue", b_total, b_fails)
        history = _history()
        if history is not None:
            for role, burn, ttft in (("green", g_burn, g_ttft),
                                     ("blue", b_burn, b_ttft)):
                labels = (("version", role),)
                if burn is not None:
                    history.record_control(BURN_SERIES, burn,
                                           labels=labels, now=now)
                if ttft is not None:
                    history.record_control(TTFT_SERIES, ttft * 1000.0,
                                           labels=labels, now=now)
        if g_total < cfg.min_requests:
            # the zero-traffic guard: an idle green slice yields NO
            # verdict — neither a rollback nor a shift-justifying
            # clean bill; the streak resets so stale breaches from a
            # busier rung cannot roll back an idle one
            self._breaches = 0
            return
        burn_bad = g_burn is not None and g_burn >= cfg.burn_ratio \
            * max(b_burn if b_burn is not None else 0.0,
                  cfg.burn_floor)
        ttft_bad = g_ttft is not None and g_ttft >= cfg.ttft_ratio \
            * max(b_ttft if b_ttft is not None else 0.0,
                  cfg.ttft_floor_s)
        self._sync_rules(history, burn_bad, ttft_bad, g_burn, g_ttft,
                         now)
        if not burn_bad and not ttft_bad:
            self._breaches = 0
            self._maybe_shift(api, now)
            return
        if b_burn is not None and b_burn >= cfg.blue_burn_veto:
            # blue is burning too: the regression is ambient, not the
            # candidate's — suppress (and say so, cooldown-limited)
            self._breaches = 0
            self.suppressed_total += 1
            if self._last_suppress is None \
                    or now - self._last_suppress >= cfg.cooldown_s:
                self._last_suppress = now
                self._note(api, "deploy_rollback_suppressed",
                           reason="blue baseline burning (burn %.3g "
                           ">= veto %.3g) — green's regression is "
                           "ambient" % (b_burn, cfg.blue_burn_veto),
                           green_burn=g_burn, blue_burn=b_burn)
            return
        self._breaches += 1
        if self._breaches >= cfg.breach_for:
            which = "burn" if burn_bad else "ttft"
            detail = ("green burn %.3g vs blue %.3g (ratio %.3g)"
                      % (g_burn or 0.0, b_burn or 0.0, cfg.burn_ratio)
                      if burn_bad else
                      "green ttft %.1fms vs blue %.1fms (ratio %.3g)"
                      % ((g_ttft or 0.0) * 1000.0,
                         (b_ttft or 0.0) * 1000.0, cfg.ttft_ratio))
            self._rollback(api, which, detail, history,
                           g_burn, g_ttft, now)

    def _sync_rules(self, history, burn_bad, ttft_bad, g_burn, g_ttft,
                    now):
        """Mirror the predicate's per-plane verdicts onto the
        detector-owned rules so the incident's leading indicator
        orders burn vs ttft by who breached FIRST."""
        if history is None:
            return
        rules = ensure_deploy_rules(history)
        for name, bad, value in (
                ("deploy_green_burn", burn_bad, g_burn),
                ("deploy_green_ttft", ttft_bad,
                 (g_ttft or 0.0) * 1000.0)):
            rule = rules[name]
            if value is not None:
                rule.last_value = value
            if bad:
                rule.streak += 1
                if rule.breach_since is None:
                    rule.breach_since = now
                rule.breach_value = value
                rule.breach_labels = (("version", "green"),)
            else:
                rule.streak = 0
                rule.breach_since = None
                rule.breach_value = None
                rule.breach_labels = None

    def _maybe_shift(self, api, now):
        """Advance one rung (hysteresis: the dwell must have elapsed
        AND the window produced a clean verdict this tick)."""
        if self.state != "shifting":
            return
        if self._last_shift is not None and now - self._last_shift \
                < max(self.config.hold_s, self.config.cooldown_s):
            return
        self._last_shift = now
        if self.step_index + 1 < len(self.config.steps):
            self.step_index += 1
            self._note(api, "deploy_shift",
                       reason="slice healthy for the dwell",
                       fraction=self.fraction)
        else:
            self.state = "promote_ready"
            self._note(api, "deploy_promote_ready",
                       reason="full traffic healthy for the dwell")

    def _rollback(self, api, which, detail, history, g_burn, g_ttft,
                  now):
        """The auto-rollback: state flips NOW (the router stops
        sending green immediately); the driver finalizes once green
        drains — zero shed. The incident artifact names the leading
        plane."""
        self.state = "rolling_back"
        self.reason = "green %s regression: %s" % (which, detail)
        if history is not None:
            rules = ensure_deploy_rules(history)
            rule = rules["deploy_green_burn" if which == "burn"
                         else "deploy_green_ttft"]
            value = (g_burn if which == "burn"
                     else (g_ttft or 0.0) * 1000.0)
            _fire_rule(history, rule, value or 0.0,
                       (("version", "green"),), now, self.reason)
        self._note(api, "deploy_rollback", reason=self.reason,
                   green_burn=g_burn,
                   green_ttft_ms=(g_ttft or 0.0) * 1000.0)
        self.warning("rolling back %s: %s", self.version, self.reason)

    # -- lifecycle (driver thread) ----------------------------------------
    def start(self, api):
        self.started_at = self._clock()
        self._last_shift = self.started_at
        self._note(api, "deploy_start", reason="green probe passed",
                   fraction=self.fraction)

    def abort(self, reason, api=None):
        """Hard stop (engine failure / breaker trip): green's
        in-flight work was shed by the caller; the state machine
        lands terminal with the reason."""
        self.state = "rolled_back"
        self.reason = str(reason)
        _clear_rules(_history())
        if api is not None:
            self._note(api, "deploy_abort", reason=self.reason)

    def finish_rollback(self, api):
        self.state = "rolled_back"
        _clear_rules(_history())
        self._note(api, "deploy_rolled_back",
                   reason=self.reason or "")

    def finish_promote(self, api):
        self.state = "promoted"
        _clear_rules(_history())
        self._note(api, "deploy_promoted",
                   reason="green is the primary now")

    # -- bookkeeping -------------------------------------------------------
    def _note(self, api, action, reason="", **attrs):
        """Every shift/suppression/rollback/promote is a
        ledger-visible governor actuation; without a governor the
        flight ring still gets the entry under the same kind."""
        attrs.setdefault("version", self.version)
        attrs.setdefault("state", self.state)
        governor = getattr(api, "governor", None)
        if governor is not None:
            try:
                governor.note_deploy(action, api, reason=reason,
                                     **attrs)
                return
            except Exception:
                self.exception("governor deploy note failed (kept)")
        try:
            from veles_tpu.observe.flight import get_flight_recorder
            get_flight_recorder().note("governor", action=action,
                                       reason=reason, **attrs)
        except Exception:
            pass
        self.info("rollout %s (%s)%s", action, self.version,
                  (": " + reason) if reason else "")

    def snapshot(self):
        """The /healthz + debug view."""
        return {"version": self.version, "state": self.state,
                "fraction": self.fraction,
                "step_index": self.step_index,
                "breaches": self._breaches,
                "suppressed_total": self.suppressed_total,
                "reason": self.reason}
