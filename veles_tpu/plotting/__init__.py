"""veles_tpu.plotting: live training visualization (reference
``veles/plotter.py``, ``plotting_units.py``, ``graphics_server.py``)."""

from veles_tpu.plotting.server import GraphicsServer  # noqa: F401
from veles_tpu.plotting.units import (  # noqa: F401
    AccumulatingPlotter, AutoHistogramPlotter, Histogram, ImagePlotter,
    ImmediatePlotter, MatrixPlotter, MultiHistogram, Plotter, SlaveStats,
    TableMaxMin)
