"""Graphics server: the render backend behind Plotter units.

TPU-native re-design of reference ``veles/graphics_server.py:73-245`` +
``graphics_client.py``. The reference strip-pickled each Plotter, published
it over ZMQ PUB (inproc/ipc/epgm multicast) and rendered in a separate
``graphics_client.py`` process (Qt4Agg/WebAgg/Pdf).

Here the transport is a plain queue + one render thread: plotters enqueue
*snapshots* — ``(plotter_class, figure name, plain-data dict)`` — and the
render thread draws them with matplotlib Agg and writes image files under
``root.common.dirs.plots``. Snapshots are picklable by construction, so a
remote viewer transport (fleet protocol / web) can be layered on without
touching the units; ``add_listener`` callbacks fire after each render and
feed the web-status dashboard's plot list.

Backends: ``file`` (PNG, default), ``pdf``, ``none`` (drop everything —
the test default, reference ``config.py:193``).
"""

import os
import queue
import threading

from veles_tpu.core.config import root
from veles_tpu.core.logger import Logger


class GraphicsServer(Logger):
    """Render queue + worker thread (reference ``GraphicsServer`` role)."""

    def __init__(self, backend=None, directory=None):
        super().__init__()
        self.backend = backend or root.common.get("graphics_backend", "file")
        self.directory = directory or root.common.dirs.get(
            "plots", os.path.join(root.common.dirs.get("cache", "."),
                                  "plots"))
        self._queue = queue.Queue()
        self._listeners = []
        self._rendered = {}
        self._thread = None
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._render_loop, name="graphics-server",
                    daemon=True)
                self._thread.start()

    def shutdown(self):
        if self._thread is not None and self._thread.is_alive():
            self._queue.put(None)
            self._thread.join(timeout=10)

    def flush(self, timeout=180):
        """Block until everything enqueued so far has rendered. The
        timeout is generous: a COLD matplotlib (first import + font
        cache rebuild) can take >30 s on a loaded host, and an expired
        flush silently loses renders (observed as a flaky missing-plot
        assertion under the full-suite commit gate)."""
        if self._thread is None or not self._thread.is_alive():
            return
        done = threading.Event()
        self._queue.put(done)
        if not done.wait(timeout=timeout):
            self.warning(
                "flush timed out after %.0fs — renders enqueued before "
                "it may be missing", timeout)

    # -- producer side -------------------------------------------------------
    def enqueue(self, plotter):
        """Queue one snapshot of ``plotter`` for rendering."""
        if self.backend == "none":
            return
        snapshot = plotter.snapshot()
        self._ensure_thread()
        self._queue.put((type(plotter), plotter.name, snapshot))

    def add_listener(self, callback):
        """``callback(name, path)`` after each rendered figure."""
        self._listeners.append(callback)

    @property
    def rendered(self):
        """name -> last written file path."""
        return dict(self._rendered)

    # -- render thread -------------------------------------------------------
    def _render_loop(self):
        import matplotlib
        matplotlib.use("Agg", force=True)
        import matplotlib.pyplot as pp

        while True:
            item = self._queue.get()
            if item is None:
                return
            if isinstance(item, threading.Event):
                item.set()
                continue
            cls, name, snapshot = item
            try:
                figure = pp.figure(name)
                figure.clf()
                cls.redraw(pp, figure, snapshot)
                path = self._write(figure, name)
                self._rendered[name] = path
                for listener in self._listeners:
                    listener(name, path)
            except Exception as exc:
                self.warning("failed to render %s: %s", name, exc)

    def _write(self, figure, name):
        os.makedirs(self.directory, exist_ok=True)
        ext = "pdf" if self.backend == "pdf" else "png"
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in name)
        path = os.path.join(self.directory, "%s.%s" % (safe, ext))
        tmp = path + ".tmp"
        figure.savefig(tmp, format=ext)
        os.replace(tmp, path)
        return path
