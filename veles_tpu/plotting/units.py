"""Stock plotter units.

TPU-native re-design of reference ``veles/plotter.py:48-161`` (the Plotter
unit contract) and ``veles/plotting_units.py:52-822`` (the nine stock
plotters). The split of responsibilities is redesigned for the in-process
render thread (see ``plotting/server.py``):

- ``fill()`` — host-side accumulation from linked attrs, every run;
- ``snapshot()`` — plain-data dict (picklable) of what redraw needs;
- ``redraw(pp, figure, data)`` — a *classmethod* pure renderer: it takes
  only the snapshot, so it can run on the render thread (or a remote
  viewer) without touching live unit state — the role the reference's
  strip-pickle + ZMQ shipping played.

Throttling (``redraw_threshold`` seconds between redraws, reference
``plotter.py:148-152``) and the global ``root.common.disable.plotting``
gate are in the base ``run()``.
"""

import time

import numpy

from veles_tpu.core.config import root
from veles_tpu.core.units import Unit


class Plotter(Unit):
    """Base plotter unit (reference ``plotter.py:48``)."""

    hide_from_registry = True
    VIEW_GROUP = "PLOTTER"

    def __init__(self, workflow, **kwargs):
        self.redraw_threshold = kwargs.pop("redraw_threshold", 2.0)
        super().__init__(workflow, **kwargs)
        self._remembers_gates = False

    def init_unpickled(self):
        super().init_unpickled()
        self._last_redraw_ = 0.0
        self._server_ = None

    @property
    def graphics_server(self):
        if self._server_ is None:
            launcher = getattr(self.workflow, "workflow", None)
            self._server_ = getattr(launcher, "graphics_server", None)
        return self._server_

    @graphics_server.setter
    def graphics_server(self, value):
        self._server_ = value

    def initialize(self, **kwargs):
        server = kwargs.get("graphics_server")
        if server is not None:
            self._server_ = server

    def run(self):
        self.fill()
        if root.common.disable.get("plotting", False):
            return
        if time.time() - self._last_redraw_ < self.redraw_threshold:
            return
        server = self.graphics_server
        if server is None:
            return
        self._last_redraw_ = time.time()
        server.enqueue(self)

    # -- the plotter contract -------------------------------------------------
    def fill(self):
        """Accumulate from linked attrs (host-side, cheap)."""

    def snapshot(self):
        """Plain-data dict consumed by :meth:`redraw`."""
        raise NotImplementedError

    @classmethod
    def redraw(cls, pp, figure, data):
        """Render ``data`` onto ``figure`` (render-thread side)."""
        raise NotImplementedError


class AccumulatingPlotter(Plotter):
    """Time-series of a scalar (e.g. error %%) with a last-N window,
    least-squares polynomial smoothing and a whole-history minimap
    (reference ``plotting_units.py:52-181``).

    Link ``input`` (+ optional ``input_field``/``input_offset``) from the
    producing unit; plotters sharing a ``name`` share a figure."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "AccumulatingPlotter")
        self.plot_style = kwargs.pop("plot_style", "k-")
        self.ylim = kwargs.pop("ylim", None)
        self.last = kwargs.pop("last", 11)
        self.fit_poly_power = kwargs.pop("fit_poly_power", 2)
        self.minimap_size = kwargs.pop("minimap", 0.25)
        self.label = kwargs.pop("label", "")
        super().__init__(workflow, **kwargs)
        self.values = []
        self.input_field = None
        self.input_offset = 0
        self.demand("input")

    def fill(self):
        value = self.input
        if self.input_field is not None:
            value = (value[self.input_field]
                     if isinstance(self.input_field, int)
                     else getattr(value, self.input_field))
        if isinstance(value, numpy.ndarray):
            value = value[self.input_offset]
        if value is not None:
            self.values.append(float(value))

    def snapshot(self):
        return {"values": list(self.values), "style": self.plot_style,
                "ylim": self.ylim, "last": self.last,
                "poly": self.fit_poly_power, "minimap": self.minimap_size,
                "label": self.label}

    @classmethod
    def redraw(cls, pp, figure, data):
        values = data["values"]
        if not values:
            return
        axes = figure.add_subplot(111)
        axes.grid(True)
        if data["ylim"]:
            axes.set_ylim(*data["ylim"])
        last = data["last"]
        window = values[-last:] if last else values
        begin = len(values) - len(window)
        xs = numpy.arange(len(window)) + begin
        if data["poly"] and len(window) > data["poly"]:
            smooth_x = numpy.linspace(begin, begin + len(window) - 1, 100)
            smooth_y = numpy.poly1d(numpy.polyfit(
                xs, window, data["poly"]))(smooth_x)
            axes.plot(smooth_x, smooth_y, data["style"], linewidth=2)
            axes.plot(xs, window, data["style"][:-1] + "o")
        else:
            axes.plot(xs, window, data["style"][:-1] + "-", marker="o",
                      label=data["label"] or None)
        if data["minimap"] and len(values) > len(window):
            mini = figure.add_axes((1 - data["minimap"], 1 - data["minimap"],
                                    data["minimap"], data["minimap"]))
            mini.xaxis.set_visible(False)
            mini.yaxis.set_visible(False)
            mini.plot(values, data["style"])
        if data["label"]:
            axes.legend(loc=2)


class MatrixPlotter(Plotter):
    """Confusion-matrix style table: cell counts plus per-row/column
    totals, rendered as an annotated heatmap (reference
    ``plotting_units.py:184-365``). Link ``input`` to the confusion
    matrix and ``reversed_labels_mapping`` from the loader."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "MatrixPlotter")
        super().__init__(workflow, **kwargs)
        self.reversed_labels_mapping = None
        self.demand("input")

    def snapshot(self):
        if self.input is None:  # producer has nothing yet (e.g. fused)
            return {"matrix": [], "labels": []}
        matrix = numpy.asarray(getattr(self.input, "mem", self.input))
        labels = self.reversed_labels_mapping
        if labels is None:
            labels = [str(i) for i in range(matrix.shape[0])]
        return {"matrix": matrix.tolist(),
                "labels": [str(l) for l in labels]}

    @classmethod
    def redraw(cls, pp, figure, data):
        if not data["matrix"]:
            return
        matrix = numpy.asarray(data["matrix"], numpy.float64)
        labels = data["labels"]
        axes = figure.add_subplot(111)
        axes.imshow(matrix, cmap="Blues", interpolation="nearest")
        n = matrix.shape[0]
        threshold = matrix.max() / 2 if matrix.size else 0
        for i in range(n):
            for j in range(matrix.shape[1]):
                axes.text(j, i, "%d" % matrix[i, j], ha="center",
                          va="center",
                          color="white" if matrix[i, j] > threshold
                          else "black")
        axes.set_xticks(range(len(labels)))
        axes.set_xticklabels(labels, rotation=45)
        axes.set_yticks(range(len(labels)))
        axes.set_yticklabels(labels)
        axes.set_xlabel("predicted")
        axes.set_ylabel("target")


class ImagePlotter(Plotter):
    """Grid of input arrays drawn as images (reference
    ``plotting_units.py:368-477``)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "ImagePlotter")
        self.yuv = kwargs.pop("yuv", False)
        super().__init__(workflow, **kwargs)
        self.inputs = []
        self.input_fields = []

    def fill(self):
        pass

    def snapshot(self):
        images = []
        for inp, field in zip(self.inputs,
                              self.input_fields or [None] * len(self.inputs)):
            value = inp
            if field is not None:
                value = (inp[field] if isinstance(field, int)
                         else getattr(inp, field))
            arr = numpy.asarray(getattr(value, "mem", value))
            # numpy arrays are already picklable plain data — copying
            # decouples from live buffers without a tolist() explosion
            images.append(numpy.array(arr))
        return {"images": images}

    @classmethod
    def redraw(cls, pp, figure, data):
        images = data["images"]
        if not images:
            return
        cols = int(numpy.ceil(numpy.sqrt(len(images))))
        rows = int(numpy.ceil(len(images) / cols))
        for i, img in enumerate(images):
            axes = figure.add_subplot(rows, cols, i + 1)
            axes.axis("off")
            if img.ndim == 3 and img.shape[-1] in (3, 4):
                span = img.max() - img.min() or 1.0
                axes.imshow((img - img.min()) / span)
            else:
                axes.imshow(img.squeeze(), cmap="gray",
                            interpolation="nearest")


class ImmediatePlotter(Plotter):
    """Up to three series plotted directly from linked arrays each run
    (reference ``plotting_units.py:480-533``)."""

    STYLES = ["k-", "g-", "r-"]

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "ImmediatePlotter")
        self.ylim = kwargs.pop("ylim", None)
        super().__init__(workflow, **kwargs)
        self.inputs = []
        self.input_fields = []

    def snapshot(self):
        series = []
        for inp, field in zip(self.inputs,
                              self.input_fields or [None] * len(self.inputs)):
            value = inp if field is None else (
                inp[field] if isinstance(field, int) else getattr(inp, field))
            series.append(numpy.ravel(
                numpy.asarray(getattr(value, "mem", value))).tolist())
        return {"series": series, "ylim": self.ylim}

    @classmethod
    def redraw(cls, pp, figure, data):
        axes = figure.add_subplot(111)
        axes.grid(True)
        if data["ylim"]:
            axes.set_ylim(*data["ylim"])
        for i, series in enumerate(data["series"]):
            axes.plot(series, cls.STYLES[i % len(cls.STYLES)])


class Histogram(Plotter):
    """Bar histogram of provided ``x``/``y`` arrays (reference
    ``plotting_units.py:536-626``)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "Histogram")
        super().__init__(workflow, **kwargs)
        self.demand("x", "y")

    def snapshot(self):
        return {"x": numpy.ravel(numpy.asarray(
                    getattr(self.x, "mem", self.x))).tolist(),
                "y": numpy.ravel(numpy.asarray(
                    getattr(self.y, "mem", self.y))).tolist()}

    @classmethod
    def redraw(cls, pp, figure, data):
        axes = figure.add_subplot(111)
        xs, ys = data["x"], data["y"]
        if not xs:
            return
        width = ((max(xs) - min(xs)) / max(len(xs), 1)) * 0.8 or 0.8
        axes.bar(xs, ys, width=width, color="#ffa0ef", edgecolor="lavender")
        axes.grid(True)


class AutoHistogramPlotter(Plotter):
    """Histogram with automatic binning (Sturges' rule) over a linked
    value array (reference ``plotting_units.py:629-678``)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "AutoHistogram")
        super().__init__(workflow, **kwargs)
        self.demand("input")

    def snapshot(self):
        values = numpy.ravel(numpy.asarray(
            getattr(self.input, "mem", self.input)))
        nbins = max(1, int(numpy.ceil(numpy.log2(len(values)) + 1))) \
            if len(values) else 1
        return {"values": values.tolist(), "bins": nbins}

    @classmethod
    def redraw(cls, pp, figure, data):
        if not data["values"]:
            return
        axes = figure.add_subplot(111)
        axes.hist(data["values"], bins=data["bins"], color="#ffa0ef")
        axes.grid(True)


class MultiHistogram(Plotter):
    """Grid of per-row histograms of a weights matrix (reference
    ``plotting_units.py:681-766``)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "MultiHistogram")
        self.hist_number = kwargs.pop("hist_number", 16)
        self.n_bars = kwargs.pop("n_bars", 25)
        super().__init__(workflow, **kwargs)
        self.demand("input")

    def snapshot(self):
        matrix = numpy.asarray(getattr(self.input, "mem", self.input))
        matrix = matrix.reshape(matrix.shape[0], -1)
        n = min(self.hist_number, matrix.shape[0])
        return {"rows": [matrix[i].tolist() for i in range(n)],
                "bins": self.n_bars}

    @classmethod
    def redraw(cls, pp, figure, data):
        rows = data["rows"]
        if not rows:
            return
        cols = int(numpy.ceil(numpy.sqrt(len(rows))))
        grid = int(numpy.ceil(len(rows) / cols))
        for i, row in enumerate(rows):
            axes = figure.add_subplot(grid, cols, i + 1)
            axes.hist(row, bins=data["bins"], color="#ffa0ef")
            axes.xaxis.set_visible(False)
            axes.yaxis.set_visible(False)


class TableMaxMin(Plotter):
    """Text table of max/min over linked arrays (reference
    ``plotting_units.py:769-819``)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "TableMaxMin")
        super().__init__(workflow, **kwargs)
        self.inputs = []
        self.input_names = []

    def snapshot(self):
        rows = []
        for inp, name in zip(self.inputs, self.input_names):
            arr = numpy.asarray(getattr(inp, "mem", inp))
            rows.append((str(name), float(arr.max()), float(arr.min())))
        return {"rows": rows}

    @classmethod
    def redraw(cls, pp, figure, data):
        axes = figure.add_subplot(111)
        axes.axis("off")
        table = [["name", "max", "min"]] + [
            [n, "%.6f" % mx, "%.6f" % mn] for n, mx, mn in data["rows"]]
        axes.table(cellText=table, loc="center")


class SlaveStats(Plotter):
    """Fleet observability table: per-slave power/jobs from the master's
    ``fleet_status()`` (reference ``plotting_units.py:822+`` SlaveStats).
    Link ``fleet_server`` to the fleet Server instance."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "SlaveStats")
        self.period = kwargs.pop("period", 1)
        super().__init__(workflow, **kwargs)
        self.fleet_server = None

    def snapshot(self):
        status = (self.fleet_server.fleet_status()
                  if self.fleet_server is not None else {})
        return {"status": status}

    @classmethod
    def redraw(cls, pp, figure, data):
        axes = figure.add_subplot(111)
        axes.axis("off")
        slaves = data["status"].get("slaves", [])
        table = [["id", "mid", "power", "jobs"]] + [
            [str(s.get("id")), str(s.get("mid")),
             "%.1f" % float(s.get("power", 0)),
             str(s.get("jobs_done", s.get("jobs", 0)))]
            for s in slaves]
        axes.table(cellText=table, loc="center")
