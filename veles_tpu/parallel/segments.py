"""Partial fusion: compile maximal jit-able runs of the tick chain.

SURVEY §7's hard part "tick fusion vs dynamic gates", second tier. The
full :mod:`veles_tpu.parallel.fused` engine recognizes the standard
forward/GD topology and compiles whole class sweeps; everything it
declines used to fall all the way to per-unit graph dispatch (the
VERDICT r2 "170x cliff"). This module closes the gap for ANY workflow
whose compute units are :class:`~veles_tpu.nn.jit_unit.JitUnit`\\ s:

- the repeater cycle is extracted as a linear unit chain;
- maximal runs of consecutive JitUnits with compatible gates collapse
  into one :class:`FusedSegment` each — a single jitted composite of the
  member ``compute()`` functions, chained through the shared Array
  slots, ONE XLA dispatch per tick instead of one per unit;
- host units (a custom unit spliced into the chain, the Decision, a
  non-standard evaluator's host logic) stay host-side between segments,
  preserving the reference's per-unit control semantics
  (``workflow.py:347-365``) exactly.

The partition rule for gates mirrors the reference's runtime gate
checks: members may join a segment only when they carry the IDENTICAL
``(gate_skip, gate_block)`` signature — the very same workflow-assigned
Bool objects, or both untouched birth gates. The per-tick gate decision
then applies to the whole segment at once — identical to graph mode,
where the shared Bool would have gated every member individually.

Numerical identity with graph mode is structural: the composite calls
the same bound ``compute()`` methods on the same inputs in the same
order — only the dispatch granularity changes (``tests/test_segments.py``
proves weight equality).
"""

import jax

from veles_tpu.core.mutable import Bool
from veles_tpu.core.units import Unit
from veles_tpu.memory import Array
from veles_tpu.nn.jit_unit import JitUnit


def chain_of(workflow):
    """The repeater cycle as an ordered unit list, starting at the unit
    the loader fires (the loader itself stays host — it owns serving).
    Returns None when the cycle is not a linear chain (fan-out inside
    the cycle is graph-mode territory)."""
    loader = getattr(workflow, "loader", None)
    repeater = getattr(workflow, "repeater", None)
    if loader is None or repeater is None:
        return None
    # "unit can reach the repeater along links_to without passing
    # through the loader" == one reverse BFS from the repeater over
    # links_from that never expands THROUGH the loader: O(V+E) once,
    # instead of a fresh forward DFS per query
    reaches = {repeater}
    frontier = [repeater]
    while frontier:
        node = frontier.pop()
        if node is loader:
            continue  # the loader may start a path, never sit inside one
        for prev in node.links_from:
            if prev not in reaches:
                reaches.add(prev)
                frontier.append(prev)

    chain = []
    current = loader
    while True:
        successors = [u for u in current.links_to
                      if u is not repeater and u in reaches]
        if current.links_to.get(repeater) and not successors:
            return chain  # closed the cycle
        if len(successors) != 1:
            return None  # fan-out inside the cycle (or a dead end)
        current = successors[0]
        if current in chain or current is loader:
            return None  # inner cycle that is not the repeater loop
        chain.append(current)


def _default_skip(unit):
    """True when the unit still carries its untouched birth gate — the
    workflow never assigned a control Bool, so in graph mode nothing
    would flip it between ticks. (Identity, not value: a shared control
    Bool like ``decision.gd_skipped`` is False at enable() time but
    toggles every tick.) A runtime safety net in FusedSegment.run still
    catches direct ``.set()`` mutation of a birth gate."""
    return unit.gate_skip is getattr(unit, "_born_gate_skip", None)


def _default_block(unit):
    return unit.gate_block is getattr(unit, "_born_gate_block", None)


def _gate_signature(unit):
    return (None if _default_skip(unit) else id(unit.gate_skip),
            None if _default_block(unit) else id(unit.gate_block))


def _fusible(unit):
    """A unit the composite can trace: a JitUnit with a real compute()
    and declared slots (custom JitUnits qualify automatically)."""
    return (isinstance(unit, JitUnit)
            and type(unit).compute is not JitUnit.compute
            and not getattr(unit, "no_fusion", False))


def partition(chain):
    """Split the chain into runs: ``[("segment", [units...]) |
    ("host", unit), ...]``. A segment extends while members are fusible
    and their gates are compatible (same non-default Bool objects, or
    constant-false defaults)."""
    result = []
    run = []
    run_sig = None

    def flush():
        nonlocal run, run_sig
        if len(run) >= 2:
            result.append(("segment", run))
        else:
            result.extend(("host", u) for u in run)
        run, run_sig = [], None

    for unit in chain:
        if not _fusible(unit):
            flush()
            result.append(("host", unit))
            continue
        sig = _gate_signature(unit)
        if run and sig != run_sig:
            # EXACT signature match only: letting a default-gate unit
            # join a run that adopts a neighbor's control Bool would
            # skip/block it when that Bool fires — graph mode would have
            # run it (correctness beats fusion greed here)
            flush()
        run.append(unit)
        run_sig = sig
    flush()
    return result


class FusedSegment(Unit):
    """One jitted composite of a run of consecutive JitUnits.

    The members stay constructed (they own the weights, serve the fleet
    and export paths, and remain the user's composition API) but are
    detached from the control graph; this unit takes their place and
    executes their chained computes as one XLA dispatch. Slot traffic is
    preserved: external inputs are read from the members' Array slots at
    call time, results are scattered back into the members' output
    slots, so everything outside the segment (Decision accumulators,
    plotters, Snapshotter, the fleet's generate/apply) sees exactly the
    graph-mode state.
    """

    hide_from_registry = True
    VIEW_GROUP = "WORKER"
    #: execution strategy, not topology (see Workflow.checksum)
    EPHEMERAL = True

    def __init__(self, workflow, members, **kwargs):
        kwargs.setdefault("name", "segment[%s..%s]"
                          % (members[0].name, members[-1].name))
        super().__init__(workflow, **kwargs)
        self.members = list(members)

    def init_unpickled(self):
        super().init_unpickled()
        self._plan_ = None
        self._jitted_ = None

    def _build_plan(self):
        """Static dataflow plan over the members' slot graph. Array slots
        are keyed by OBJECT identity — ``link_attrs`` shares the Array
        objects, so a producer's output slot IS the consumer's input
        slot."""
        ext = []        # (unit, attr) fetched at call time
        ext_index = {}  # id(Array) | (unit id, attr) -> ext position
        produced = {}   # id(Array) -> value-env position (last writer)
        steps = []      # (unit, in_refs, out_positions)
        n_values = 0
        for unit in self.members:
            in_refs = []
            for name in unit.INPUTS:
                slot = getattr(unit, name)
                if isinstance(slot, Array):
                    key = id(slot)
                    if key in produced:
                        in_refs.append((True, produced[key]))
                        continue
                else:
                    key = (id(unit), name)
                if key not in ext_index:
                    ext_index[key] = len(ext)
                    ext.append((unit, name))
                in_refs.append((False, ext_index[key]))
            outs = []
            for name in unit.OUTPUTS:
                slot = getattr(unit, name)
                pos = n_values
                n_values += 1
                if isinstance(slot, Array):
                    produced[id(slot)] = pos
                outs.append(pos)
            steps.append((unit, in_refs, outs))
        # scatter the FINAL value of every written slot (identity-deduped:
        # a slot rewritten later in the chain scatters once)
        scatter = []
        seen = set()
        for unit, _, outs in steps:
            for name, pos in zip(unit.OUTPUTS, outs):
                slot = getattr(unit, name)
                key = id(slot) if isinstance(slot, Array) else (id(unit),
                                                                name)
                if isinstance(slot, Array) and produced[key] != pos:
                    continue  # overwritten later in the segment
                if key in seen:
                    continue
                seen.add(key)
                scatter.append((unit, name, pos))
        self._plan_ = (ext, steps, scatter, n_values)

    def _build_jitted(self):
        ext, steps, scatter, n_values = self._plan_

        def composite(ext_values):
            env = [None] * n_values
            for unit, in_refs, outs in steps:
                args = [env[i] if internal else ext_values[i]
                        for internal, i in in_refs]
                res = unit.compute(*args)
                if len(outs) == 1:
                    res = (res,)
                for pos, val in zip(outs, res):
                    env[pos] = val
            return tuple(env[pos] for _, _, pos in scatter)

        self._jitted_ = jax.jit(composite)

    def run(self):
        for member in self.members:
            if (member.gate_skip is getattr(member, "_born_gate_skip",
                                            None)
                    and bool(member.gate_skip)) or (
                    member.gate_block is getattr(member,
                                                 "_born_gate_block", None)
                    and bool(member.gate_block)):
                # somebody .set() a birth gate the partition classified
                # as constant: honor graph semantics on the slow path
                if not getattr(self, "_warned_slow_", False):
                    self.warning("%s: a member's default gate was "
                                 "mutated after fusion; falling back to "
                                 "per-unit dispatch", self.name)
                    self._warned_slow_ = True
                for unit in self.members:
                    if bool(unit.gate_block):
                        return
                    if not bool(unit.gate_skip):
                        unit.run()
                return
        if self._plan_ is None:
            self._build_plan()
            self._build_jitted()
        ext, steps, scatter, _ = self._plan_
        values = []
        for unit, name in ext:
            slot = getattr(unit, name)
            if isinstance(slot, Array):
                if slot.data is None:
                    raise ValueError("%s: input slot %s.%s is empty"
                                     % (self.name, unit.name, name))
                values.append(slot.data)
            else:
                values.append(slot)
        results = self._jitted_(tuple(values))
        for (unit, name, _), value in zip(scatter, results):
            slot = getattr(unit, name)
            if isinstance(slot, Array):
                slot.data = value
            else:
                setattr(unit, name, value)


def enable(workflow):
    """Splice FusedSegments into the workflow's repeater cycle. Returns
    the list of created segments ([] when nothing fused — not a linear
    cycle, or no run of 2+ compatible JitUnits). Call between
    construction and ``initialize()`` (StandardWorkflow does this
    automatically when the full fused engine declines)."""
    chain = chain_of(workflow)
    if not chain:
        return []
    parts = partition(chain)
    if not any(kind == "segment" for kind, _ in parts):
        return []
    repeater = workflow.repeater
    segments = []
    # rebuild the cycle's control links: predecessors of the first
    # member outside the segment now fire the segment, and the segment
    # fires the last member's outside successors
    for kind, payload in parts:
        if kind != "segment":
            continue
        members = payload
        member_set = set(members)
        segment = FusedSegment(workflow, members)
        # segment gates = the members' shared (non-default) gates
        # (partition guarantees every member carries the SAME pair)
        for member in members:
            if not _default_skip(member):
                segment.gate_skip = member.gate_skip
            if not _default_block(member):
                segment.gate_block = member.gate_block
        # rewire ALL outside links of EVERY member, not just the chain
        # endpoints: a monitor hanging off a mid-segment member must
        # still fire (after the segment — its data is final then), and
        # an outside provider into a mid-segment member still holds the
        # segment's AND gate
        predecessors, successors = [], []
        for member in members:
            predecessors.extend(u for u in member.links_from
                                if u not in member_set
                                and u not in predecessors)
            successors.extend(u for u in list(member.links_to)
                              if u not in member_set
                              and u not in successors)
        segment.link_from(*predecessors)
        for successor in successors:
            successor.link_from(segment)
        for member in members:
            member.unlink_all()
        segments.append(segment)
    _ = repeater  # the cycle closes through the existing repeater links
    return segments
