"""veles_tpu.parallel: device meshes and distributed execution.

The reference's only intra-model distribution is master/slave data
parallelism over Twisted/ZeroMQ (SURVEY §2.5). The TPU design has two
tiers:

- **pod mode** (this package): synchronous SPMD over a ``jax.sharding.Mesh``
  — data/tensor parallel shardings of one fused train step, gradient merge
  as ``psum`` over ICI. The idiomatic path for any fixed pod slice.
- **fleet mode** (``veles_tpu.fleet``): host-level elastic master/slave
  orchestration preserving the reference's job/update, drop/requeue,
  respawn semantics over DCN, used for dynamic clusters, genetics and
  ensembles.
"""

from veles_tpu.parallel.mesh import build_mesh, mesh_axes  # noqa: F401
from veles_tpu.parallel.step import build_train_step  # noqa: F401
