"""Expert parallelism over the ``expert`` mesh axis.

Additive beyond the reference (no model sharding of any kind, SURVEY
§2.5): a GShard-style top-1 mixture-of-experts feed-forward, sharded so
each device group holds one slice of the experts and tokens move to
their expert via ``lax.all_to_all`` over ICI — the canonical TPU MoE
dataflow:

    gate (replicated) → top-1 route → capacity-bounded dense dispatch
    (static shapes: XLA cannot compile data-dependent token counts) →
    all_to_all(tokens → expert shards) → expert FFN (batched matmul on
    the MXU) → all_to_all back → combine weighted by gate probability.

Tokens over an expert's capacity are dropped (standard GShard
semantics); size capacity by ``capacity_factor`` to trade padding FLOPs
for drop rate.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.parallel.mesh import shard_map


def init_moe_params(rng, n_experts, d_model, d_hidden):
    """Gate + per-expert FFN weights (host numpy in, pytree out)."""
    scale = 1.0 / (d_model ** 0.5)
    return {
        "gate": (rng.randn(d_model, n_experts) * scale).astype("float32"),
        "w1": (rng.randn(n_experts, d_model, d_hidden) * scale).astype(
            "float32"),
        "b1": jnp.zeros((n_experts, d_hidden), jnp.float32),
        "w2": (rng.randn(n_experts, d_hidden, d_model) * scale).astype(
            "float32"),
        "b2": jnp.zeros((n_experts, d_model), jnp.float32),
    }


def shard_moe_params(params, mesh):
    """Experts sharded over 'expert'; the gate replicated."""
    def put(name, a):
        spec = P() if name == "gate" else P("expert")
        return jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec))
    return {k: put(k, v) for k, v in params.items()}


def make_moe_ffn(mesh, n_experts, capacity_factor=2.0):
    """Compile ``moe(params, x) -> (y, aux)`` over the mesh.

    ``x`` is (tokens, d_model) sharded over 'expert' (the token dim acts
    as the data dim of this axis); ``aux`` carries the dropped-token
    fraction for load-balancing diagnostics.
    """
    ep = mesh.shape["expert"]
    assert n_experts % ep == 0, "n_experts must divide the expert axis"
    e_local = n_experts // ep

    @partial(shard_map, mesh=mesh,
             in_specs=({"gate": P(), "w1": P("expert"), "b1": P("expert"),
                        "w2": P("expert"), "b2": P("expert")},
                       P("expert")),
             out_specs=(P("expert"), P()))
    def moe(p, x_local):
        t_local, d_model = x_local.shape
        capacity = max(1, int(t_local * capacity_factor / n_experts))
        # --- route (every device computes its own tokens' gates) -----
        logits = x_local @ p["gate"]                     # (T, E)
        probs = jax.nn.softmax(logits, axis=1)
        choice = jnp.argmax(probs, axis=1)               # (T,)
        gate_val = jnp.max(probs, axis=1)                # (T,)
        onehot = jax.nn.one_hot(choice, n_experts)       # (T, E)
        # position of each token within its expert's queue
        position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
        kept = (position < capacity) * onehot            # (T, E)
        dropped = 1.0 - kept.sum(axis=1)
        pos = (position * kept).sum(axis=1).astype(jnp.int32)
        # dense dispatch tensor (T, E, C): static shapes for XLA
        dispatch = kept[:, :, None] * jax.nn.one_hot(pos, capacity)[
            :, None, :]
        # (E, C, D): each expert's padded token buffer from THIS shard
        expert_in = jnp.einsum("tec,td->ecd", dispatch, x_local)
        # --- all_to_all: experts gather their tokens from all shards --
        # (E, C, D) -> (e_local, ep*C, D): split the expert dim across
        # the axis, concatenate the shard dim into the token dim
        expert_in = expert_in.reshape(ep, e_local, capacity, d_model)
        expert_in = lax.all_to_all(expert_in, "expert", 0, 0,
                                   tiled=False)           # (ep, eL, C, D)
        expert_in = expert_in.transpose(1, 0, 2, 3).reshape(
            e_local, ep * capacity, d_model)
        # --- expert FFN (batched matmul on the MXU) -------------------
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", expert_in, p["w1"])
                        + p["b1"][:, None, :])
        out = jnp.einsum("ech,ehd->ecd", h, p["w2"]) + p["b2"][:, None, :]
        # --- all_to_all back ------------------------------------------
        out = out.reshape(e_local, ep, capacity, d_model).transpose(
            1, 0, 2, 3)
        out = lax.all_to_all(out, "expert", 0, 0, tiled=False)
        out = out.reshape(n_experts, capacity, d_model)   # (E, C, D)
        # --- combine ---------------------------------------------------
        y = jnp.einsum("tec,ecd->td", dispatch, out) * gate_val[:, None]
        return y, lax.pmean(jnp.mean(dropped), "expert")

    return moe


def make_moe_train_step(mesh, n_experts, capacity_factor=2.0,
                        learning_rate=0.01):
    """Compile a TRAIN step through the sharded MoE: grads flow through
    the dense dispatch/combine tensors and both ``all_to_all``\\ s (their
    transpose is the reverse all_to_all), and through the top-1 gate the
    GShard way — the routing argmax is non-differentiable, but the
    combine is weighted by the gate PROBABILITY, so the gate weights
    learn from d(loss)/d(gate_val). An MSE objective against per-token
    targets keeps the step self-contained.

    Returns ``step(params, x, targets) -> (new_params, loss)`` with
    ``x``/``targets`` sharded over the expert axis (token-major).
    """
    moe = make_moe_ffn(mesh, n_experts, capacity_factor)

    def loss_fn(params, x, targets):
        y, _ = moe(params, x)
        return jnp.mean((y - targets) ** 2)

    @jax.jit
    def step(params, x, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, targets)
        new = jax.tree.map(lambda w, g: w - learning_rate * g,
                           params, grads)
        return new, loss

    return step


def reference_moe(params, x):
    """Dense single-device reference (no capacity drops) for parity
    tests: every token goes through its argmax expert exactly."""
    logits = x @ params["gate"]
    probs = jax.nn.softmax(logits, axis=1)
    choice = jnp.argmax(probs, axis=1)
    gate_val = jnp.max(probs, axis=1)
    w1 = params["w1"][choice]                   # (T, D, H)
    b1 = params["b1"][choice]
    w2 = params["w2"][choice]
    b2 = params["b2"][choice]
    h = jax.nn.relu(jnp.einsum("td,tdh->th", x, w1) + b1)
    out = jnp.einsum("th,thd->td", h, w2) + b2
    return out * gate_val[:, None]
