"""Paged KV pool with shared-prefix reuse for the slot engine.

The dense slot engine (``parallel/decode.py``) backs every slot with a
``(L, S, ..., max_len)`` slab: HBM is reserved for ``slots x max_len``
whether or not tokens exist, a thousand requests sharing a system
prompt each re-prefill it, and the slab shape caps concurrency far
below what live tokens require. This module re-expresses the SAME slot
math over a single page pool (ROADMAP open item 2; *Compiler-First
State Space Duality and Portable O(1) Autoregressive Caching for
Inference*, PAPERS.md arxiv 2603.09555 — cache state as a
compiler-visible pool addressed by a page table, not a per-request
dense allocation):

- **device side**: one ``(L, pages, page_size, H, D)`` pool (int8-KV
  tier: head-major ``(L, pages, H, D, page_size)`` q8 + per-position
  scales, exactly the dense slab's recipe) plus the slot control
  leaves. Attention is a page-table GATHER over each slot's live pages;
  appends are the same per-slot ``dynamic_update_slice`` as the dense
  engine, targeted through the page table. One compiled program per
  (bucket, group, pages-per-slot bucket), so the ``observe/xla_stats``
  counters and the no-recompile-storm guarantees carry over.
- **host side**: :class:`PagePool` — free list, per-page refcounts, the
  LRU :class:`PrefixCache` (token prefixes hashed at page granularity),
  page reservations for pool-aware admission control, and the
  page-release-rate window that prices ``Retry-After``.

Numerical contract (the existing CPU bit-identity idiom, extended):
masked positions contribute EXACT zeros to the softmax, and gathered
pages reproduce the slab values bit-for-bit, so paged ``slot_step`` /
``slot_admit_many`` stream tokens identical to the dense engine and to
greedy ``generate()`` on CPU — including shared-prefix admissions,
whose unique tail runs a prefix-masked forward over the pooled prefix
pages (``tests/test_paged.py`` pins all of it, bf16 and int8-KV).

Sharing rules (docs/paged_kv.md): only WHOLE pages are shared, the
divergent / partial tail always prefills into fresh pages, and a
slot's appends land at positions past its prompt — so a shared page is
never written by construction (copy-on-write degenerates to
"divergence allocates, sharing never mutates"). The int8-KV tier
reuses prefixes only at exact-prompt granularity: its pool stores
ROUNDED K/V while the dense prefill attends exact values, so a
partial-hit tail would not be bit-identical — full-prompt hits restore
the original (exact-prefill) logits and stay exact.
"""

import functools
import hashlib
import threading
import time

import numpy

import jax
import jax.numpy as jnp
from jax import lax

from veles_tpu.observe.xla_stats import instrument
from veles_tpu.ops.quant import int8_cache_attend, matmul_any
from veles_tpu.parallel.transformer_step import _block_qkv, _head, _mlp

#: page 0 is the SCRATCH page: never allocated, the target of every
#: padding page-table entry and of inactive lanes' harmless appends —
#: its contents are garbage by definition and always masked.
SCRATCH_PAGE = 0


def init_paged_state(n_blocks, pages, page_size, heads, head_dim,
                     vocab, slots, dtype=jnp.float32, quantized=False,
                     mesh=None, mesh_axis="model"):
    """Pool + control state for ``slots`` concurrent sequences over
    ``pages`` pages of ``page_size`` positions (page 0 is scratch, so
    ``pages - 1`` are allocatable).

    Float tier: K/V ``(L, P, page_size, H, D)`` — the dense slab's
    layout with the slot dim replaced by pages. ``quantized=True``
    stores the int8-KV tier: head-major ``(L, P, H, D, page_size)`` q8
    with ``(L, P, H, page_size)`` f32 scales (``init_slot_state``'s
    recipe page-for-slab). ``mesh`` creates the pool in-layout: pages
    shard over their HEADS dim on ``mesh_axis`` exactly like
    ``slot_state_specs`` shards the slab, control leaves replicated."""
    from veles_tpu.parallel.decode import shard_slot_tree

    base = {
        "lengths": jnp.zeros((slots,), jnp.int32),
        "logits": jnp.zeros((slots, vocab), jnp.float32),
        "req_key": jax.random.split(jax.random.key(0), slots),
        "step": jnp.zeros((slots,), jnp.int32),
    }
    if quantized:
        qshape = (n_blocks, pages, heads, head_dim, page_size)
        sshape = (n_blocks, pages, heads, page_size)
        state = dict(base,
                     k=jnp.zeros(qshape, jnp.int8),
                     v=jnp.zeros(qshape, jnp.int8),
                     k_scale=jnp.zeros(sshape, jnp.float32),
                     v_scale=jnp.zeros(sshape, jnp.float32))
    else:
        shape = (n_blocks, pages, page_size, heads, head_dim)
        state = dict(base, k=jnp.zeros(shape, dtype),
                     v=jnp.zeros(shape, dtype))
    if mesh is not None:
        state = shard_slot_tree(
            state, mesh, paged_state_specs(quantized, axis=mesh_axis))
    return state


def paged_state_specs(quantized=False, axis="model"):
    """PartitionSpec dict for the paged state: pool pages shard over
    their HEADS dim (the slot-slab serving layout, page-for-slab),
    control leaves replicate."""
    from jax.sharding import PartitionSpec as P

    if quantized:
        kv = P(None, None, axis, None, None)    # (L, P, H, D, ps)
        scale = P(None, None, axis, None)       # (L, P, H, ps)
        extra = {"k_scale": scale, "v_scale": scale}
    else:
        kv = P(None, None, None, axis, None)    # (L, P, ps, H, D)
        extra = {}
    return dict({"k": kv, "v": kv, "lengths": P(), "logits": P(),
                 "req_key": P(), "step": P()}, **extra)


def _page_size_of(state):
    """Static page size from the pool leaf shape (minor for the int8
    head-major layout, axis 2 for float)."""
    return (state["k"].shape[-1] if "k_scale" in state
            else state["k"].shape[2])


#: the paged-state leaves whose bytes belong to the PAGE POOL rather
#: than the decoder's control state — what memscope charges the
#: ``kv_pool`` owner (observe/memscope.py)
PAGED_KV_LEAVES = ("k", "v", "k_scale", "v_scale")


def paged_kv_bytes(state):
    """Device bytes of the page arrays inside a paged decode state
    (both tiers: float K/V, or int8 K/V + f32 scales). The decoder
    stamps ``pool.page_bytes = paged_kv_bytes(state) // pool.pages``
    so attribution splits one pytree between the ``kv_pool`` and
    ``decode_state`` owners without double-counting."""
    total = 0
    for leaf in PAGED_KV_LEAVES:
        arr = state.get(leaf)
        if arr is not None:
            total += getattr(arr, "nbytes", 0) or 0
    return total


def _pad_positions(val, t_padded):
    """Zero-pad the positions axis (axis 2 of an (L, B, T, ...) stack)
    up to ``t_padded`` — whole-page scatter granularity."""
    t = val.shape[2]
    if t == t_padded:
        return val
    pad = [(0, 0)] * val.ndim
    pad[2] = (0, t_padded - t)
    return jnp.pad(val, pad)


def _scatter_pages(state, page_ids, k_all, v_all):
    """Write stacked prefill K/V (L, B, T, H, D) into the pool pages
    ``page_ids`` (B, NP) — positions padded to whole pages (stale
    padding positions are rewritten by a sequence's own appends before
    any mask exposes them, the dense engine's doctrine). Duplicate
    rows (group padding) carry equal values, so the scatter is
    well-defined. Returns the updated pool leaves as a dict."""
    n_pages = page_ids.shape[1]
    ps = _page_size_of(state)
    new = {}
    if "k_scale" in state:
        from veles_tpu.parallel.decode import _quantize_kv
        for name, val in (("k", k_all), ("v", v_all)):
            q8, scale = _quantize_kv(val)        # (L,B,T,H,D), (L,B,T,H)
            q8 = _pad_positions(q8, n_pages * ps)
            scale = _pad_positions(scale, n_pages * ps)
            lb = q8.shape[:2]
            q8 = q8.reshape(lb + (n_pages, ps) + q8.shape[3:])
            scale = scale.reshape(lb + (n_pages, ps) + scale.shape[3:])
            # pool is head-major (L,P,H,D,ps) / (L,P,H,ps)
            new[name] = state[name].at[:, page_ids].set(
                jnp.transpose(q8, (0, 1, 2, 4, 5, 3)))
            new[name + "_scale"] = state[name + "_scale"].at[
                :, page_ids].set(jnp.transpose(scale, (0, 1, 2, 4, 3)))
    else:
        for name, val in (("k", k_all), ("v", v_all)):
            val = _pad_positions(val.astype(state[name].dtype),
                                 n_pages * ps)
            lb = val.shape[:2]
            val = val.reshape(lb + (n_pages, ps) + val.shape[3:])
            new[name] = state[name].at[:, page_ids].set(val)
    return new


def _gather_block_float(state, block, page_table):
    """Float tier: (S, PB, ps, H, D) gather -> (S, PB*ps, H, D) — the
    dense ``new_k[i][:, :span]`` slice, page-addressed. Page-table rows
    list a slot's pages in logical order; padding entries point at the
    scratch page, whose garbage the mask zeroes exactly."""
    slots, pb = page_table.shape
    ps = state["k"].shape[2]
    k = state["k"][block][page_table]
    v = state["v"][block][page_table]
    shape = (slots, pb * ps) + k.shape[3:]
    return k.reshape(shape), v.reshape(shape)


def _gather_block_int8(state, block, page_table):
    """int8 tier: gathered pages re-laid head-major positions-minor —
    (S, H, D, PB*ps) q8 + (S, H, PB*ps) scales, the dequant-fused
    attend kernel's layout."""
    slots, pb = page_table.shape
    ps = state["k"].shape[-1]
    out = []
    for name in ("k", "v"):
        q8 = state[name][block][page_table]       # (S, PB, H, D, ps)
        q8 = jnp.transpose(q8, (0, 2, 3, 1, 4)).reshape(
            (slots,) + q8.shape[2:4] + (pb * ps,))
        scale = state[name + "_scale"][block][page_table]  # (S,PB,H,ps)
        scale = jnp.transpose(scale, (0, 2, 1, 3)).reshape(
            (slots, scale.shape[2], pb * ps))
        out.extend((q8, scale))
    return out


def _paged_admit_many(params, embed_table, heads, state, slots,
                      page_ids, prompt_x, req_keys, lengths):
    """Cold paged admission: the dense ``_slot_admit_many`` with the
    slab scatter replaced by a page scatter. ``page_ids`` (B, NP) maps
    each row's bucket positions onto its allocated pages; everything
    else — the shared ``_prefill_forward``, the control-row scatters,
    the duplicate-row group padding — is the dense idiom verbatim, so
    the stored K/V are bit-identical to the slab's."""
    from veles_tpu.parallel.decode import _prefill_forward

    with jax.named_scope("paged.admit"):
        logits, k_all, v_all, lengths = _prefill_forward(
            params, prompt_x, heads, lengths)
    new = dict(
        state,
        lengths=state["lengths"].at[slots].set(lengths),
        logits=state["logits"].at[slots].set(logits.astype(jnp.float32)),
        req_key=state["req_key"].at[slots].set(req_keys),
        step=state["step"].at[slots].set(jnp.zeros_like(lengths)),
    )
    new.update(_scatter_pages(state, page_ids, k_all, v_all))
    return new


def _paged_admit_tail(params, embed_table, heads, state, slots,
                      prefix_pages, tail_pages, tail_x, req_keys,
                      lengths):
    """Prefix-hit admission: prefill ONLY the unique tail. The shared
    prefix (``prefix_pages`` (B, PP) — whole pages, page-aligned) is
    gathered from the pool as attention context; the tail tokens
    (``tail_x`` (B, Tt, E), right-padded to the tail bucket) run the
    block stack with a prefix-offset causal mask and scatter their K/V
    into the fresh ``tail_pages`` (B, NT). ``lengths`` (B,) are the
    true TOTAL lengths (shared + true tail).

    Bit-identity: tail activations depend only on the prefix K/V
    (causality), the gathered pages hold the slab-exact values, and
    masked columns contribute exact zeros — so the tail's logits equal
    the dense full prefill's on CPU (the established span/bucket
    invariance idiom; float tier only — the int8-KV pool stores
    rounded K/V, so its hits are exact-prompt-only)."""
    batch, t_tail, embed = tail_x.shape
    ps = _page_size_of(state)
    shared = prefix_pages.shape[1] * ps
    # column c visible to tail query j iff c <= shared + j: the full
    # causal mask restricted to the tail rows, prefix columns first
    mask = (jnp.arange(shared + t_tail)[None, None, None, :]
            <= shared + jnp.arange(t_tail)[None, None, :, None])
    x = tail_x
    ks, vs = [], []
    with jax.named_scope("paged.admit_tail"):
        for i, blk in enumerate(params["blocks"]):
            q, k, v = _block_qkv(blk, x, heads)
            ks.append(k)
            vs.append(v)
            kp, vp = _gather_block_float(state, i, prefix_pages)
            k_cat = jnp.concatenate([kp.astype(q.dtype), k], axis=1)
            v_cat = jnp.concatenate([vp.astype(q.dtype), v], axis=1)
            # the SAME XLA attention the dense prefill's small-shape
            # path runs (ops/attention.attention), with the causal
            # mask made explicit to carry the prefix offset
            att = jax.nn.dot_product_attention(
                q, k_cat, v_cat, scale=float(1.0 / numpy.sqrt(
                    embed // heads)), mask=mask)
            x = x + matmul_any(att.reshape(batch, t_tail, embed),
                               blk["wout"]) + blk["bout"]
            x = _mlp(blk, x)
    tail_len = lengths - shared
    last = jnp.take_along_axis(
        x, jnp.maximum(tail_len - 1, 0)[:, None, None], axis=1)[:, 0]
    logits = _head(params, last)
    new = dict(
        state,
        lengths=state["lengths"].at[slots].set(lengths),
        logits=state["logits"].at[slots].set(logits.astype(jnp.float32)),
        req_key=state["req_key"].at[slots].set(req_keys),
        step=state["step"].at[slots].set(jnp.zeros_like(lengths)),
    )
    new.update(_scatter_pages(state, tail_pages, jnp.stack(ks),
                              jnp.stack(vs)))
    return new


def _paged_admit_hit(state, slots, lengths, logits, req_keys):
    """Full-prompt prefix hit: ~0 admission — the shared pages are
    already resident, so only the control rows are written. ``logits``
    (B, V) are the ORIGINAL cold prefill's last-position logits
    (cached device-side), so the first emitted token is bit-identical
    to the dense admission's."""
    with jax.named_scope("paged.admit_hit"):
        return dict(
            state,
            lengths=state["lengths"].at[slots].set(lengths),
            logits=state["logits"].at[slots].set(
                logits.astype(jnp.float32)),
            req_key=state["req_key"].at[slots].set(req_keys),
            step=state["step"].at[slots].set(jnp.zeros_like(lengths)),
        )


def _paged_slot_step(params, embed_table, heads, state, page_table,
                     active, temperature=1.0, sample=False, top_k=0):
    """One decode step across all slots — the dense ``_slot_step``
    with the slab slice replaced by a page-table gather and the append
    target routed through the table. ``page_table`` (S, PB) int32 lists
    each slot's live pages in logical order (padding/retired rows point
    at scratch); the attended span is ``PB * page_size`` — the host
    sizes PB to the longest live sequence plus the dispatch's appends,
    so per-step cost scales with live tokens, one compiled program per
    PB (the pages-per-slot bucket).

    Two attend formulations behind ONE jitted signature: the portable
    page-table GATHER (the CPU bit-identity reference), or — when
    ``ops/paged_attention.use_paged_kernel()`` says so — the fused
    Pallas kernel that walks the table directly and attends only each
    slot's LIVE pages (span/page overshoot deleted at the kernel
    level). The probe is read at TRACE time, so the ``paged.step`` /
    ``paged.dispatch`` instrument names, the AOT facade and the
    sharded-fns surface are identical either way; flipping the probe
    does not invalidate already-traced programs (tests
    ``jax.clear_caches()`` around it)."""
    from veles_tpu.ops import paged_attention as pgatt
    from veles_tpu.parallel.decode import _cache_attend, _pick_token

    slots = state["lengths"].shape[0]
    quantized = "k_scale" in state
    ps = _page_size_of(state)
    pb = page_table.shape[1]
    span = pb * ps
    use_kernel = pgatt.use_paged_kernel()
    lengths = state["lengths"]
    if sample:
        step_keys = jax.vmap(jax.random.fold_in)(state["req_key"],
                                                 state["step"])
        tok_in = jax.vmap(
            lambda l, k: _pick_token(l[None], k, temperature, True,
                                     top_k)[0])(state["logits"],
                                                step_keys)
    else:
        tok_in = jnp.argmax(state["logits"], axis=-1)
    x = embed_table[tok_in][:, None, :]
    embed = x.shape[-1]
    visible = jnp.arange(span)[None, :] <= lengths[:, None]
    if quantized:
        mask_addend = jnp.where(visible, 0.0, -1e30).astype(jnp.float32)
        # python float (weak type): `q * inv_sqrt` must NOT promote a
        # bf16 q to f32 (see decode.decode_step)
        inv_sqrt = (embed // heads) ** -0.5
    else:
        mask = visible[:, None, None, :]
    if use_kernel:
        # the kernel resolves visibility from the prefetched lengths
        # itself — no gathered span, no span-wide mask materialized
        block_h = pgatt._tuned_block_h(ps, embed // heads, heads)
    new_k, new_v = state["k"], state["v"]
    new_ks = state.get("k_scale")
    new_vs = state.get("v_scale")
    from veles_tpu.parallel.decode import _quantize_kv
    for i, blk in enumerate(params["blocks"]):
        q, k, v = _block_qkv(blk, x, heads)
        # per-slot append through the page table: position p lives in
        # the slot's logical page p // ps at offset p % ps. Unrolled
        # dynamic_update_slice per slot, NOT one scatter (the dense
        # engine's measured XLA-on-TPU preference). Tail pages are
        # slot-private by construction (shared prefix pages are never
        # an append target — docs/paged_kv.md), and a retired lane's
        # clamped/zero table row routes its harmless write to scratch.
        if quantized:
            kq, ks = _quantize_kv(k)         # (S,1,H,D), (S,1,H)
            vq, vs = _quantize_kv(v)
            for s in range(slots):
                pos = lengths[s]
                page = page_table[s, jnp.minimum(pos // ps, pb - 1)]
                off = pos % ps
                new_k = lax.dynamic_update_slice(
                    new_k, jnp.transpose(kq[s:s + 1], (0, 2, 3, 1))[None],
                    (i, page, 0, 0, off))
                new_v = lax.dynamic_update_slice(
                    new_v, jnp.transpose(vq[s:s + 1], (0, 2, 3, 1))[None],
                    (i, page, 0, 0, off))
                new_ks = lax.dynamic_update_slice(
                    new_ks, jnp.transpose(ks[s:s + 1], (0, 2, 1))[None],
                    (i, page, 0, off))
                new_vs = lax.dynamic_update_slice(
                    new_vs, jnp.transpose(vs[s:s + 1], (0, 2, 1))[None],
                    (i, page, 0, off))
            if use_kernel:
                att = pgatt.paged_attend_int8(
                    (q * inv_sqrt)[:, 0], new_k[i], new_ks[i],
                    new_v[i], new_vs[i], page_table, lengths,
                    page_size=ps, block_h=block_h)[:, None]
            else:
                pool = dict(state, k=new_k, v=new_v, k_scale=new_ks,
                            v_scale=new_vs)
                k8, kscale, v8, vscale = _gather_block_int8(pool, i,
                                                            page_table)
                att = int8_cache_attend(q * inv_sqrt, k8, kscale, v8,
                                        vscale, mask_addend)
        else:
            for s in range(slots):
                pos = lengths[s]
                page = page_table[s, jnp.minimum(pos // ps, pb - 1)]
                off = pos % ps
                new_k = lax.dynamic_update_slice(
                    new_k, k[s:s + 1][None].astype(new_k.dtype),
                    (i, page, off, 0, 0))
                new_v = lax.dynamic_update_slice(
                    new_v, v[s:s + 1][None].astype(new_v.dtype),
                    (i, page, off, 0, 0))
            if use_kernel:
                att = pgatt.paged_attend(
                    q[:, 0], new_k[i], new_v[i], page_table, lengths,
                    page_size=ps, block_h=block_h)[:, None]
            else:
                pool = dict(state, k=new_k, v=new_v)
                k_g, v_g = _gather_block_float(pool, i, page_table)
                att = _cache_attend(q, k_g, v_g, mask)
        att = att.astype(x.dtype)
        x = x + matmul_any(att.reshape(slots, 1, embed),
                           blk["wout"]) + blk["bout"]
        x = _mlp(blk, x)
    logits = _head(params, x[:, 0]).astype(jnp.float32)
    new_state = dict(
        state, k=new_k, v=new_v,
        lengths=jnp.where(active, lengths + 1, lengths),
        logits=jnp.where(active[:, None], logits, state["logits"]),
        step=jnp.where(active, state["step"] + 1, state["step"]),
    )
    if quantized:
        new_state["k_scale"] = new_ks
        new_state["v_scale"] = new_vs
    return new_state, tok_in


def _paged_slot_step_many(params, embed_table, heads, state, page_table,
                          active, n, temperature=1.0, sample=False,
                          top_k=0):
    """``n`` lockstep paged steps as ONE ``lax.scan`` dispatch. The
    page table is constant across the chunk — the host pre-maps every
    page the chunk's appends can touch (``PB * page_size`` covers the
    longest live sequence plus the whole chunk), so mid-chunk page
    boundary crossings route through the same table."""
    def body(state, _):
        state, emitted = _paged_slot_step(
            params, embed_table, heads, state, page_table, active,
            temperature, sample, top_k)
        return state, emitted

    with jax.named_scope("paged.dispatch"):
        return lax.scan(body, state, None, length=n)


def _paged_restore(state, page_ids, values):
    """Rebuild path: scatter preserved page payloads (one stacked
    array per pool leaf, (L, NP, ...page shape)) back into a FRESH
    pool at the re-allocated ``page_ids`` (NP,) — restoring the prefix
    cache across a breaker rebuild is a copy, never a re-prefill."""
    with jax.named_scope("paged.restore"):
        new = dict(state)
        for name, val in values.items():
            new[name] = state[name].at[:, page_ids].set(
                val.astype(state[name].dtype))
        return new


# -- the jitted single-chip surface -----------------------------------------
# One compiled program per (bucket, group, pages bucket) via the jit
# cache; instrument() books compiles/hits per name so the dispatch-count
# and recompile-storm CI hooks extend to the paged engine unchanged.

paged_admit_many = instrument("paged.admit", functools.partial(
    jax.jit, static_argnames=("heads",),
    donate_argnames=("state",))(_paged_admit_many))
paged_admit_tail = instrument("paged.admit_tail", functools.partial(
    jax.jit, static_argnames=("heads",),
    donate_argnames=("state",))(_paged_admit_tail))
paged_admit_hit = instrument("paged.admit_hit", functools.partial(
    jax.jit, donate_argnames=("state",))(_paged_admit_hit))
paged_slot_step = instrument("paged.step", functools.partial(
    jax.jit, static_argnames=("heads", "sample", "top_k"),
    donate_argnames=("state",))(_paged_slot_step))
paged_slot_step_many = instrument("paged.dispatch", functools.partial(
    jax.jit, static_argnames=("heads", "n", "sample", "top_k"),
    donate_argnames=("state",))(_paged_slot_step_many))
paged_restore = instrument("paged.restore", functools.partial(
    jax.jit, donate_argnames=("state",))(_paged_restore))


#: (mesh, axis, quantized) -> pinned jit objects, same doctrine as
#: decode._SHARDED_SLOT_FNS: output shardings pinned to the canonical
#: layout so a donated state never drifts and defeats the jit cache;
#: check-then-insert locked so racing builders share one jit object.
_SHARDED_PAGED_FNS = {}
_SHARDED_PAGED_LOCK = threading.Lock()


def sharded_paged_fns(mesh, mesh_axis="model", quantized=False):
    """The sharded paged engine's jitted call surface: the SAME raw
    functions as the single-chip programs (one copy of the math — the
    bit-identity contract), jitted per layout with the state outputs
    pinned to :func:`paged_state_specs` and small operands replicated.
    Returns ``(admit, admit_tail, admit_hit, step, step_many,
    restore)``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    key = (mesh, mesh_axis, bool(quantized))
    with _SHARDED_PAGED_LOCK:
        fns = _SHARDED_PAGED_FNS.get(key)
    if fns is not None:
        return fns
    state_sh = {
        name: NamedSharding(mesh, spec)
        for name, spec in paged_state_specs(quantized,
                                            axis=mesh_axis).items()}
    replicated = NamedSharding(mesh, P())
    admit = instrument("paged.admit", jax.jit(
        _paged_admit_many, static_argnames=("heads",),
        donate_argnames=("state",), out_shardings=state_sh))
    admit_tail = instrument("paged.admit_tail", jax.jit(
        _paged_admit_tail, static_argnames=("heads",),
        donate_argnames=("state",), out_shardings=state_sh))
    admit_hit = instrument("paged.admit_hit", jax.jit(
        _paged_admit_hit, donate_argnames=("state",),
        out_shardings=state_sh))
    step = instrument("paged.step", jax.jit(
        _paged_slot_step,
        static_argnames=("heads", "sample", "top_k"),
        donate_argnames=("state",),
        out_shardings=(state_sh, replicated)))
    step_many = instrument("paged.dispatch", jax.jit(
        _paged_slot_step_many,
        static_argnames=("heads", "n", "sample", "top_k"),
        donate_argnames=("state",),
        out_shardings=(state_sh, replicated)))
    restore = instrument("paged.restore", jax.jit(
        _paged_restore, donate_argnames=("state",),
        out_shardings=state_sh))
    fns = (admit, admit_tail, admit_hit, step, step_many, restore)
    with _SHARDED_PAGED_LOCK:
        fns = _SHARDED_PAGED_FNS.setdefault(key, fns)
    return fns


# -- host side ---------------------------------------------------------------

def _prefix_key(tokens):
    """Stable content hash of a token prefix (collisions are guarded
    by an exact token comparison on lookup)."""
    return hashlib.sha1(
        numpy.ascontiguousarray(tokens, numpy.int32).tobytes()
    ).hexdigest()


def _boundary_keys(tokens, page_size, whole):
    """Prefix keys of every whole-page boundary (``tokens[:k*ps]`` for
    k=1..whole) in one O(T) pass: a single incremental SHA-1 advanced
    page by page and copied at each boundary. Hashing each boundary
    from scratch is O(T^2/page_size) bytes per admission — quadratic
    in the prompt; the digests are byte-identical to
    :func:`_prefix_key` of the same prefix."""
    data = numpy.ascontiguousarray(tokens, numpy.int32)
    hasher = hashlib.sha1()
    keys = []
    for k in range(whole):
        hasher.update(data[k * page_size:(k + 1) * page_size]
                      .tobytes())
        keys.append(hasher.copy().hexdigest())
    return keys


class PrefixCache:
    """Refcount-backed LRU cache of page-granular token prefixes.

    Lives OUTSIDE the device state so a breaker rebuild can carry it
    across decoders: each entry holds the prefix tokens, the page ids
    (re-mapped on restore), the original cold prefill's last-position
    logits (full-prompt hits admit with zero prefill), and a
    device-array shadow of each page's payload for the restore scatter.
    Counters are cumulative across rebuilds (the Prometheus contract).
    """

    def __init__(self, max_entries=256):
        import collections

        self.max_entries = int(max_entries)
        self.entries = collections.OrderedDict()   # key -> entry
        self.page_shadow = {}                      # page id -> {leaf: arr}
        self.counters = {"hits": 0, "misses": 0, "evictions": 0}

    def __len__(self):
        return len(self.entries)


class PagePool:
    """Host-side page table: free list, per-page refcounts, the prefix
    cache, admission reservations and the page-release-rate window.

    Thread model: the decoder driver thread owns admissions/frees; the
    HTTP admission gate reserves from handler threads — every mutation
    takes the one RLock. Refcounts: a live slot holds one ref per
    mapped page; each prefix-cache entry holds one ref per page it
    names (nested boundary entries stack refs naturally). A page frees
    when its count reaches zero; cache entries are evicted LRU-first
    when an allocation runs short."""

    def __init__(self, pages, page_size, cache=None):
        import collections

        if pages < 2:
            raise ValueError("pool needs >= 2 pages (page 0 is "
                             "scratch), got %d" % pages)
        if page_size < 1:
            raise ValueError("page_size must be >= 1, got %d"
                             % page_size)
        self.pages = int(pages)
        self.page_size = int(page_size)
        self._lock = threading.RLock()
        self._free = list(range(self.pages - 1, SCRATCH_PAGE, -1))
        self._refs = {}
        self._reserved = 0
        #: (monotonic stamp, pages freed) — the observed release rate
        #: that prices Retry-After for pool-aware backpressure
        self._freed_events = collections.deque(maxlen=512)
        self.cache = cache if cache is not None else PrefixCache()
        #: device bytes per page across every KV leaf — the decoder
        #: stamps this once from its paged state (the page ARRAYS live
        #: in the decode state pytree; the pool only owns the table),
        #: so memscope attribution can charge the pool its footprint
        #: without double-counting the state tree
        self.page_bytes = 0

    # -- accounting -------------------------------------------------------
    @property
    def capacity(self):
        """Allocatable pages (scratch excluded)."""
        return self.pages - 1

    @property
    def free_pages(self):
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self):
        with self._lock:
            return self.capacity - len(self._free)

    def hbm_bytes(self):
        """Device footprint of the page arrays this pool tables:
        pages x page_bytes. Lock-free (two write-once ints) — this is
        a memscope accountant and runs at metrics scrape time."""
        return self.pages * self.page_bytes

    def shadow_bytes(self):
        """Host bytes pinned by the prefix cache's page shadows (the
        re-materialization copies that survive a breaker rebuild).
        Iterates a point-in-time list copy without the lock — an
        approximate byte count is fine for attribution, and a memscope
        accountant must never contend with the admission path."""
        total = 0
        for leaves in list(self.cache.page_shadow.values()):
            for arr in list(leaves.values()):
                total += getattr(arr, "nbytes", 0) or 0
        return total

    def snapshot(self):
        with self._lock:
            counters = dict(self.cache.counters)
            hits = counters.get("hits", 0)
            misses = counters.get("misses", 0)
            return {
                "pages_total": self.capacity,
                "pages_used": self.capacity - len(self._free),
                "pages_free": len(self._free),
                "page_size": self.page_size,
                "reserved_pages": self._reserved,
                "prefix_entries": len(self.cache),
                "prefix_hits": hits,
                "prefix_misses": misses,
                "prefix_evictions": counters.get("evictions", 0),
                "prefix_hit_rate": (round(hits / (hits + misses), 4)
                                    if hits + misses else None),
            }

    # -- alloc / free -----------------------------------------------------
    def alloc(self, n):
        """Allocate ``n`` pages (refcount 1 each), evicting LRU prefix
        entries under pressure; returns the page ids or ``None`` when
        the pool cannot satisfy the request even after eviction."""
        if n <= 0:
            return []
        with self._lock:
            while len(self._free) < n and self._evict_lru():
                pass
            if len(self._free) < n:
                return None
            pages = [self._free.pop() for _ in range(n)]
            for page in pages:
                self._refs[page] = 1
            return pages

    def retain(self, pages):
        """Add one ref per page (a slot mapping shared prefix pages)."""
        with self._lock:
            for page in pages:
                self._refs[page] += 1

    def release(self, pages):
        """Drop one ref per page; refcount-0 pages return to the free
        list (and feed the release-rate window)."""
        freed = 0
        with self._lock:
            for page in pages:
                refs = self._refs.get(page)
                if refs is None:
                    continue
                if refs <= 1:
                    del self._refs[page]
                    self._free.append(page)
                    self.cache.page_shadow.pop(page, None)
                    freed += 1
                else:
                    self._refs[page] = refs - 1
            if freed:
                self._freed_events.append((time.monotonic(), freed))

    def _evict_lru(self):
        """Drop the least-recently-used prefix entry; True when one
        was evicted (its refs released — pages used by live slots stay
        resident until those slots retire)."""
        cache = self.cache
        if not cache.entries:
            return False
        key, entry = next(iter(cache.entries.items()))
        del cache.entries[key]
        cache.counters["evictions"] += 1
        self.release(entry["pages"])
        return True

    def flush_prefix_cache(self):
        """Drop EVERY prefix entry (and its page refs) — the weight
        hot-swap seam (docs/zero_downtime.md): cached pages hold KV
        bytes and logits computed under the OLD weights, so one
        reused prefix after a swap would splice stale activations
        into new-weight streams. Pages still mapped by live slots
        stay resident until those slots retire (they finish on the
        old weights by the drain contract). Returns the number of
        entries dropped."""
        dropped = 0
        with self._lock:
            while self._evict_lru():
                dropped += 1
            self.cache.page_shadow.clear()
        return dropped

    # -- admission reservations (pool-aware backpressure) -----------------
    def try_reserve(self, n):
        """Reserve worst-case page demand for one admission: the sum of
        live reservations never exceeds capacity, so an admitted
        request can always be satisfied (prefix sharing and eviction
        only ever FREE pages relative to the worst case) — the
        no-deadlock invariant ``ServingHealth.try_admit`` gates on."""
        with self._lock:
            if self._reserved + n > self.capacity:
                return False
            self._reserved += n
            return True

    def unreserve(self, n):
        with self._lock:
            self._reserved = max(0, self._reserved - n)

    def release_rate(self, window=60.0):
        """Observed page releases per second over the trailing window
        (0.0 when nothing freed yet)."""
        now = time.monotonic()
        with self._lock:
            events = [(t, n) for t, n in self._freed_events
                      if now - t <= window]
        if not events:
            return 0.0
        span = max(now - events[0][0], 1e-3)
        return sum(n for _, n in events) / span

    def retry_after(self, need, fallback=1.0):
        """Honest Retry-After for a pool rejection: how long the
        observed release rate needs to free ``need`` pages, clamped to
        [1, 60] seconds; the fallback covers a cold window."""
        rate = self.release_rate()
        if rate <= 0:
            return max(1.0, float(fallback))
        return float(min(60.0, max(1.0, need / rate)))

    # -- prefix cache -----------------------------------------------------
    def lookup(self, tokens, allow_partial=True):
        """Longest page-granular cached prefix of ``tokens``; returns
        ``(entry, shared_len)`` with the shared pages RETAINED for the
        caller's slot, or ``(None, 0)`` on a miss. A full-prompt match
        requires stored logits (otherwise the last page is treated as
        tail so the admission can recompute them); ``allow_partial=
        False`` (the int8-KV tier) accepts exact-prompt hits only.

        NO counters move here: the caller books :meth:`book_hit` /
        :meth:`book_miss` once the admission commits, so a hit rolled
        back by :meth:`unlookup` (no pages for the tail) or a blocked
        request re-scanned every driver pass never skews the
        exported-monotone ``veles_prefix_cache_*_total`` counters."""
        ps = self.page_size
        tokens = numpy.asarray(tokens, numpy.int32)
        n = len(tokens)
        # boundary keys hashed OUTSIDE the lock (one O(T) incremental
        # pass): the HTTP gate's try_reserve shares this lock
        keys = _boundary_keys(tokens, ps, n // ps)
        with self._lock:
            for k in range(n // ps, 0, -1):
                shared = k * ps
                if shared == n:
                    pass          # full hit: needs stored logits
                elif not allow_partial:
                    continue
                key = keys[k - 1]
                entry = self.cache.entries.get(key)
                if entry is None:
                    continue
                if not numpy.array_equal(entry["tokens"],
                                         tokens[:shared]):
                    continue      # hash collision: not a match
                if shared == n and entry["logits"] is None:
                    continue
                self.cache.entries.move_to_end(key)
                self.retain(entry["pages"])
                return entry, shared
            return None, 0

    def book_hit(self):
        """Count one prefix-cache hit — called by the admission path
        AFTER the hit commits (slot taken, tail pages allocated), never
        at lookup time, so the counter stays monotone under rollback."""
        with self._lock:
            self.cache.counters["hits"] += 1

    def book_miss(self):
        """Count one prefix-cache miss — like :meth:`book_hit`, booked
        when the COLD admission commits, not at lookup time: a pool-
        blocked request re-scanned at the queue front every driver pass
        must not inflate ``veles_prefix_cache_misses_total`` (and
        crater the hit rate) while it waits."""
        with self._lock:
            self.cache.counters["misses"] += 1

    def unlookup(self, entry):
        """Roll a :meth:`lookup` hit back (the caller could not admit
        — e.g. no pages for the tail): drop the retained refs. The hit
        was never booked (:meth:`book_hit` runs only on commit), so a
        retried admission still books exactly once."""
        with self._lock:
            self.release(entry["pages"])

    def insert(self, tokens, pages, state, logits=None):
        """Publish an admission's full pages into the cache: one
        entry per page boundary (``tokens[:k*ps]`` for every whole
        page k), each holding refs on its pages, with the prefill
        logits attached to the exact-length boundary. Pure host
        bookkeeping — page payload shadows are captured lazily at
        breaker-trip time (:meth:`capture_shadows`), never on the
        admission hot path."""
        ps = self.page_size
        tokens = numpy.asarray(tokens, numpy.int32)
        whole = len(tokens) // ps
        if whole == 0:
            return
        keys = _boundary_keys(tokens, ps, whole)  # outside the lock
        with self._lock:
            for k in range(1, whole + 1):
                shared = k * ps
                key = keys[k - 1]
                entry = self.cache.entries.get(key)
                boundary_logits = (logits if shared == len(tokens)
                                   else None)
                if entry is not None:
                    self.cache.entries.move_to_end(key)
                    if entry["logits"] is None \
                            and boundary_logits is not None:
                        entry["logits"] = boundary_logits
                    continue
                entry_pages = list(pages[:k])
                self.retain(entry_pages)
                self.cache.entries[key] = {
                    "tokens": tokens[:shared].copy(),
                    "pages": entry_pages,
                    "length": shared,
                    "logits": boundary_logits,
                }
            while len(self.cache.entries) > self.cache.max_entries:
                self._evict_lru()

    def capture_shadows(self, state):
        """Copy every cached-but-unshadowed page's payload to host —
        the rebuild-adoption prelude (``GenerateAPI._rebuild`` runs it
        on the dying decoder), NOT the admission hot path: cached
        pages are read-only by construction (appends land past the
        prompt, divergence allocates fresh pages), so the bytes
        captured at trip time equal the bytes at publication — and
        cold admissions never pay the per-page device sync + D2H
        transfer that each :func:`_shadow_page` blocks on."""
        with self._lock:
            named = {page for entry in self.cache.entries.values()
                     for page in entry["pages"]}
            missing = [page for page in named
                       if page not in self.cache.page_shadow]
        # D2H outside the lock: entry refs pin the pages, and the HTTP
        # pool gate must not stall on the transfer
        shadows = {page: _shadow_page(state, page) for page in missing}
        with self._lock:
            still = {page for entry in self.cache.entries.values()
                     for page in entry["pages"]}
            for page, shadow in shadows.items():
                # a page evicted (freed) during the copy may already be
                # recycled under a NEW prefix — a stale shadow for it
                # would restore wrong bytes
                if page in still:
                    self.cache.page_shadow.setdefault(page, shadow)

    def restore_entries(self, state, restore_fn):
        """Adopt a previous decoder's prefix cache into THIS (fresh)
        pool: allocate new pages for the union of cached pages, scatter
        the shadowed payloads back with ``restore_fn(state, page_ids,
        values) -> state``, and re-point every entry. Entries whose
        shadow is gone (or that no longer fit) are dropped. Returns the
        updated device state."""
        cache = self.cache
        with self._lock:
            old_pages = []
            for entry in cache.entries.values():
                for page in entry["pages"]:
                    if page not in old_pages:
                        old_pages.append(page)
            old_pages = [p for p in old_pages if p in cache.page_shadow]
            # drop entries referencing unshadowed pages outright
            # (capture_shadows failed or never ran for them) — counted
            # as evictions like every other path that loses an entry
            for key in [k for k, e in cache.entries.items()
                        if any(p not in cache.page_shadow
                               for p in e["pages"])]:
                del cache.entries[key]
                cache.counters["evictions"] += 1
            shadow = dict(cache.page_shadow)
            cache.page_shadow = {}
            # oldest entries drop first when the fresh pool is smaller.
            # Sized against the FREE LIST directly: alloc()'s own LRU
            # eviction cannot help here — the surviving entries name
            # OLD-pool page ids, so evicting them frees nothing in
            # this pool.
            while old_pages and len(self._free) < len(old_pages):
                cache.entries.popitem(last=False)
                # rebuild-pressure drops ARE evictions: an operator
                # watching veles_prefix_cache_evictions_total after a
                # breaker trip must see entries leave, not just the
                # entries gauge fall
                cache.counters["evictions"] += 1
                still = set()
                for entry in cache.entries.values():
                    still.update(entry["pages"])
                old_pages = [p for p in old_pages if p in still]
            if not old_pages:
                cache.entries.clear()
                return state
            new_ids = self.alloc(len(old_pages))
            mapping = dict(zip(old_pages, new_ids))
            for old, new in mapping.items():
                cache.page_shadow[new] = shadow[old]
            # entry refs: alloc gave each new page one ref; add the
            # remaining (entries-per-page - 1) refs
            counts = {}
            for entry in cache.entries.values():
                entry["pages"] = [mapping[p] for p in entry["pages"]]
                for page in entry["pages"]:
                    counts[page] = counts.get(page, 0) + 1
            for page, count in counts.items():
                if count > 1:
                    self.retain([page] * (count - 1))
            # pages shadowed but no longer named by any entry (their
            # entries were dropped above for referencing some OTHER
            # unshadowed page): freed, unshadowed, and excluded from
            # the scatter — restoring them would KeyError on the
            # popped shadow
            orphan = [p for p in new_ids if p not in counts]
            if orphan:
                self.release(orphan)
                for page in orphan:
                    self.cache.page_shadow.pop(page, None)
                new_ids = [p for p in new_ids if p in counts]
        page_ids = jnp.asarray(new_ids, jnp.int32)
        values = _stack_shadow(self.cache.page_shadow, new_ids)
        if values:
            state = restore_fn(state, page_ids, values)
        return state


def _shadow_page(state, page):
    """HOST copies of one page's payload across every pool leaf —
    they survive the pool's donation (rebuild restores them with a
    scatter, never a re-prefill) without doubling the cached pages'
    HBM; the device round-trip only happens on the rare rebuild."""
    return {name: numpy.asarray(state[name][:, page])
            for name in ("k", "v", "k_scale", "v_scale")
            if name in state}


def _stack_shadow(page_shadow, page_ids):
    """Stack per-page shadows into one (L, NP, ...) host array per
    leaf for the restore scatter."""
    if not page_ids:
        return {}
    leaves = page_shadow[page_ids[0]].keys()
    return {name: numpy.stack([page_shadow[p][name] for p in page_ids],
                              axis=1)
            for name in leaves}


def pages_for(positions, page_size):
    """Pages needed to hold ``positions`` tokens (>= 1)."""
    return max(1, -(-int(positions) // int(page_size)))


def default_pool_pages(slots, max_len, page_size, chunk=1):
    """Slab-equivalent pool size: every slot full to ``max_len`` plus
    the dispatch overshoot for chunks up to ``chunk``, plus the
    scratch page — the one formula the decoder default,
    ``init_slot_state`` and the bench all share, so 'same HBM as the
    dense slab' means the same thing everywhere.

    The overshoot term is load-bearing: ``dispatch_chunk`` advances
    lanes past retirement and pre-maps ``slot_len + chunk`` positions
    before every dispatch, so under the lag-1 pipeline a slot legally
    running ``prompt + budget == max_len`` demands pages for up to
    ``max_len - 1 + 2 * chunk`` positions near the end of its decode.
    The dense slab absorbs that with a clamped ``dynamic_update_slice``;
    a pool sized without the slack raises mid-decode on workloads the
    slab serves."""
    return int(slots) * pages_for(int(max_len) + 2 * int(chunk),
                                  page_size) + 1
