"""FusedTick: the product-path tick compiler.

SURVEY §7.1's headline design translation, wired into the REAL workflow
loop: the reference executes one trip around the Repeater loop as a chain
of per-unit kernel launches (loader gather → forward ops → evaluator →
per-layer GD updates, reference ``workflow.py:347-365``); here the whole
tick is ONE jitted XLA computation, including the minibatch gather from
the device-resident dataset and the normalizer — zero host round trips
per tick, params donated through the step so weights never leave HBM.

``StandardWorkflow`` builds its unit graph as usual (the units remain the
composition API, the weight owners, and the fleet/graph execution path),
then — in standalone mode, when the topology is recognizably a
forward/GD chain — splices a :class:`FusedTick` unit in place of the
compute chain:

    start → repeater → loader → FusedTick → decision → {repeater, end}

The backward math is ``jax.grad`` of the same masked loss the evaluator
computes, which is numerically identical to the hand-chained GD units
(``tests/test_nn.py::test_gd_matches_autodiff`` proves the equivalence;
``tests/test_fused.py`` proves end-to-end weight equality per epoch).

Sharding: with a mesh (pod mode) the tick is ``shard_map``-ped over the
``data`` axis — each device gathers its own index shard from the
replicated originals, gradients/metrics are merged over ICI by the
mapreduce primitives (``parallel/mapreduce.py``: ``reduce_sum`` at the
configured ``root.common.fleet.reduce`` tier, f32 == the plain psum) —
the synchronous SPMD answer to the reference's master/slave update
merge. Tensor parallelism for dense chains stays in ``parallel.step``.

Control-plane fleet mode (``root.common.fleet.plane = "control"``,
``docs/compiler_fleet.md``): a SLAVE's tick keeps its params
device-resident across jobs (no per-job refresh from the unit Arrays —
the wire no longer carries weights), stashes a one-slot rollback before
every train tick so a re-issued job (lost update) replays from exactly
the pre-job state, and writes the unit Arrays only at epoch fences
(feeding the fence-sync payload the client ships to the master).
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from veles_tpu.core.units import Unit
from veles_tpu.parallel import mapreduce
from veles_tpu.parallel.mesh import shard_map
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.ops import activations as act_lib, losses
from veles_tpu.ops.gather import gather_minibatch
from veles_tpu.loader.normalization import normalizer_registry

#: forward-unit class name → fused layer kind
_DENSE = "dense"
_CONV = "conv"
_ATTN = "attention"
_FFN = "ffn"
_NORM = "layer_norm"
_POOL_KINDS = {"MaxPooling": "max", "AvgPooling": "avg",
               "MaxAbsPooling": "maxabs"}


#: per-leaf update policy: (leaf key, forward attr, gd velocity attr,
#: uses learning_rate_bias, gets l2/l1 decay) — encodes each graph-mode
#: GD unit's exact update math so fused results match bit-for-bit logic
_WB_LEAVES = (("w", "weights", "_velocity_w", False, True),
              ("b", "bias", "_velocity_b", True, False))
_ATTN_LEAVES = (("w", "weights", "_velocity_w", False, True),
                ("b", "bias", "_velocity_b", True, True),
                ("ow", "out_weights", "_velocity_ow", False, True),
                ("ob", "out_bias", "_velocity_ob", True, True))


def extract_model_spec(workflow):
    """Static per-layer config from the workflow's forwards/gds chains.
    Returns a spec list, or None when a layer type is not fusible (the
    caller then stays on graph mode)."""
    from veles_tpu.nn.all2all import All2All, All2AllSoftmax
    from veles_tpu.nn.attention import (GDLayerNorm, GDSelfAttention,
                                        GDTokenFFN, LayerNorm,
                                        SelfAttention, TokenFFN)
    from veles_tpu.nn.conv import Conv, GDConv
    from veles_tpu.nn.gd import GradientDescent
    from veles_tpu.nn.pooling import GDPooling, Pooling

    known_computes = {getattr(cls, "compute", None) for cls in (
        All2All, All2AllSoftmax, Conv, SelfAttention, TokenFFN,
        LayerNorm, Pooling, GradientDescent, GDConv, GDSelfAttention,
        GDTokenFFN, GDLayerNorm, GDPooling)}

    def modified(unit):
        """A subclass that overrides compute() carries custom math the
        spec tables cannot express — fusing it by isinstance would
        silently run the BASE math (the spec is built from class
        attributes, not the override). Such chains belong to the
        sweep/segment tiers, which compose the units' own computes."""
        return (unit is not None
                and getattr(type(unit), "compute", None)
                not in known_computes)

    specs = []
    for i, fwd in enumerate(workflow.forwards):
        gd = workflow.gds[i] if workflow.gds else None
        if modified(fwd) or modified(gd):
            return None
        if isinstance(fwd, All2All):
            spec = {"kind": _DENSE, "activation": fwd.ACTIVATION,
                    "leaves": _WB_LEAVES}
        elif isinstance(fwd, Conv):
            spec = {"kind": _CONV, "activation": fwd.ACTIVATION,
                    "sliding": fwd.sliding, "padding": fwd.padding,
                    "leaves": _WB_LEAVES}
        elif isinstance(fwd, SelfAttention):
            spec = {"kind": _ATTN, "heads": fwd.heads,
                    "causal": fwd.causal,
                    "residual": getattr(fwd, "residual", False),
                    "leaves": _ATTN_LEAVES}
        elif isinstance(fwd, TokenFFN):
            spec = {"kind": _FFN, "activation": fwd.activation,
                    "residual": fwd.residual, "leaves": _ATTN_LEAVES}
        elif isinstance(fwd, LayerNorm):
            spec = {"kind": _NORM, "eps": fwd.eps, "leaves": _WB_LEAVES}
        elif isinstance(fwd, Pooling):
            spec = {"kind": _POOL_KINDS.get(type(fwd).__name__),
                    "window": (fwd.ky, fwd.kx), "sliding": fwd.sliding}
            if spec["kind"] is None:
                return None
        else:
            return None
        if "leaves" in spec:
            if gd is None or not hasattr(gd, "learning_rate"):
                return None
            spec["has_params"] = True
            # per-layer solver (momentum/adam/adagrad) — the fused update must
            # run each GD unit's exact math (gd.py make_updater)
            spec["solver"] = getattr(gd, "solver", "momentum")
        specs.append(spec)
    return specs


def get_hypers(workflow):
    """Per-layer hyperparameter vectors, read fresh from the GD units'
    ``_hyper`` slots each tick — so ``set_learning_rate()`` annealing keeps
    working in fused mode without retracing (the gd.py contract)."""
    return [gd._hyper.data if getattr(fwd, "weights", None) is not None
            else None
            for fwd, gd in zip(workflow.forwards, workflow.gds)]


def get_params(workflow, specs):
    """Snapshot the unit chain's weights into the per-layer pytree:
    ``{"p": {leaf: tensor}, "v": {leaf: velocity}}`` per layer (plus
    ``"s"`` second moments + ``"t"`` step count for stateful solvers), leaves
    named by each spec's update-policy table."""
    params = []
    for fwd, gd, spec in zip(workflow.forwards, workflow.gds, specs):
        if not spec.get("has_params"):
            params.append({})
            continue
        p, v = {}, {}
        entry = {"p": p, "v": v}
        stateful = spec.get("solver", "momentum") != "momentum"
        if stateful:
            entry["s"] = {}
            step = gd._step.data
            entry["t"] = (step if step is not None
                          else jnp.zeros((), jnp.float32))
        for leaf, fwd_attr, vel_attr, _, _ in spec["leaves"]:
            p[leaf] = getattr(fwd, fwd_attr).data
            vel = getattr(gd, vel_attr).data
            v[leaf] = vel if vel is not None else jnp.zeros_like(p[leaf])
            if stateful:
                sec = getattr(gd,
                              vel_attr.replace("_velocity",
                                               "_second")).data
                entry["s"][leaf] = (sec if sec is not None
                                    else jnp.zeros_like(p[leaf]))
        params.append(entry)
    return params


def set_params(workflow, params, specs):
    """Write fused-step results back into the shared unit Array slots (so
    the Snapshotter, exporters, and graph mode all see current weights).

    COPIES, not aliases: the train step donates its params argument, so an
    alias stored in a unit Array would be a deleted buffer one tick later
    (and the Snapshotter may read it concurrently from a pool thread)."""
    for fwd, gd, p, spec in zip(workflow.forwards, workflow.gds, params,
                                specs):
        if not p:
            continue
        stateful = spec.get("solver", "momentum") != "momentum"
        for leaf, fwd_attr, vel_attr, _, _ in spec["leaves"]:
            getattr(fwd, fwd_attr).data = jnp.copy(p["p"][leaf])
            getattr(gd, vel_attr).data = jnp.copy(p["v"][leaf])
            if stateful:
                getattr(gd, vel_attr.replace("_velocity", "_second")
                        ).data = jnp.copy(p["s"][leaf])
        if stateful:
            gd._step.data = jnp.copy(p["t"])


def _layer_forward(spec):
    """Pure forward for one layer, matching the forward unit's compute."""
    kind = spec["kind"]
    if kind == _DENSE:
        from veles_tpu.ops.gemm import dense_layer
        activation = spec["activation"]

        def fwd(p, x):
            x = x.reshape(x.shape[0], -1)
            # one fused kernel (matmul + bias + activation epilogue)
            # when the shapes qualify for the Pallas path; XLA dot with
            # its own epilogue fusion otherwise — see ops/gemm.py
            return dense_layer(x, p["w"], p["b"], activation=activation,
                               out_dtype=jnp.float32)
        return fwd
    if kind == _CONV:
        from veles_tpu.ops.gemm import conv2d
        act = act_lib.ACTIVATIONS[spec["activation"]][0]
        sliding, padding = spec["sliding"], spec["padding"]

        def fwd(p, x):
            # same precision-policy conv as the graph unit (bit-identical
            # by construction — one shared implementation)
            return act(conv2d(x, p["w"], sliding, padding) + p["b"])
        return fwd
    if kind == _ATTN:
        from veles_tpu.ops.attention import attention_block
        heads, causal = spec["heads"], spec["causal"]
        residual = spec.get("residual", False)

        def fwd(p, x):
            # THE SAME implementation the graph unit runs
            # (nn.attention.SelfAttention._forward delegates there too)
            return attention_block(x, p["w"], p["b"], p["ow"], p["ob"],
                                   heads, causal, residual)
        return fwd
    if kind == _FFN:
        from veles_tpu.ops.attention import ffn_block
        activation = spec["activation"]
        residual = spec.get("residual", True)

        def fwd(p, x):
            # mirrors nn.attention.TokenFFN._forward exactly
            return ffn_block(x, p["w"], p["b"], p["ow"], p["ob"],
                             activation, residual)
        return fwd
    if kind == _NORM:
        eps = spec["eps"]

        def fwd(p, x):
            # mirrors nn.attention.LayerNorm._forward exactly
            mean = jnp.mean(x, axis=-1, keepdims=True)
            var = jnp.var(x, axis=-1, keepdims=True)
            return (x - mean) * lax.rsqrt(var + eps) * p["w"] + p["b"]
        return fwd
    # pooling (mirrors nn.pooling semantics exactly)
    ky, kx = spec["window"]
    window = (1, ky, kx, 1)
    strides = (1,) + tuple(spec["sliding"]) + (1,)
    if kind == "max":
        return lambda p, x: lax.reduce_window(
            x, -jnp.inf, lax.max, window, strides, "VALID")
    if kind == "avg":
        return lambda p, x: lax.reduce_window(
            x, 0.0, lax.add, window, strides, "VALID") / (kx * ky)

    def maxabs(p, x):
        # signed value of the max-|x| element, built from the two
        # DIFFERENTIABLE reduce_windows (a custom absmax reducer has no
        # reverse-mode rule — the train step must grad through pooling)
        mx = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                               "VALID")
        mn = lax.reduce_window(x, jnp.inf, lax.min, window, strides,
                               "VALID")
        return jnp.where(jnp.abs(mx) >= jnp.abs(mn), mx, mn)
    return maxabs


def _freeze(obj):
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_freeze(v) for v in obj)
    return obj


#: (frozen specs, norm_type, mesh id) → compiled step tuple. Rebuilding a
#: workflow with the same topology reuses the SAME jitted callables, so
#: jax's in-process trace cache (and the persistent XLA cache) hit.
_TICK_CACHE = {}


def _tick_key(specs, norm_type, with_confusion, augment, loss_kind,
              grad_reduce, mesh):
    """The tick cache key: topology + every engine knob the trace
    folds in. ONE copy — :func:`build_tick` and the AOT adoption seam
    (:func:`install_tick_steps`) must agree on it exactly, or a loaded
    artifact would silently shadow (or miss) the live programs."""
    from veles_tpu.core.config import root
    return (_freeze(specs), norm_type, with_confusion, augment,
            loss_kind, grad_reduce, None if mesh is None else id(mesh),
            root.common.engine.get("precision_level", 0),
            str(root.common.engine.get("compute_dtype", "bfloat16")),
            bool(root.common.engine.get("use_pallas", False)),
            bool(root.common.engine.get("pallas_epilogue", False)))


def install_tick_steps(steps, specs, norm_type="none", mesh=None,
                       with_confusion=True, augment="none",
                       loss_kind="softmax", grad_reduce="f32"):
    """Seed the tick cache for this topology with caller-provided step
    callables — the seam the AOT loader (``veles_tpu/aot/loader.py``)
    slots loaded compiled programs into: a later :func:`build_tick`
    with the same key returns THESE steps, so ``FusedTick`` (and the
    fleet wrappers above it) run artifact programs unchanged. Returns
    the previous cache entry (None when the tick was never built)."""
    key = _tick_key(specs, norm_type, with_confusion, augment,
                    loss_kind, grad_reduce, mesh)
    previous = _TICK_CACHE.get(key)
    _TICK_CACHE[key] = tuple(steps)
    return previous


def build_tick(specs, norm_type="none", mesh=None,
               with_confusion=True, augment="none",
               loss_kind="softmax", grad_reduce="f32"):
    """Compile the fused engine.

    Returns ``(train_step, eval_step, train_sweep, eval_sweep)``:

    - ``train_step(params, hypers, norm, data, labels, indices, valid,
      seed) -> (params, (loss, n_err))`` — one minibatch: gather →
      normalize → [augment] → forward → masked softmax xent → grad →
      per-layer momentum/decay update. ``hypers`` (per-layer 5-vectors
      from :func:`get_hypers`) and ``norm`` (normalizer-state dict) are
      traced inputs so annealing and dataset changes never retrace;
      ``augment="mirror"`` applies the loader's in-jit random-mirror
      transform to TRAIN batches, keyed by the loader-drawn ``seed`` —
      the exact math of ``FullBatchImageLoader._augment_jit``, so fused
      and graph mode stay numerically identical;
    - ``eval_step(params, norm, data, labels, indices, valid) ->
      (loss, n_err)`` — forward + metrics only (VALID/TEST sweeps, GD
      skipped exactly as the Decision unit's ``gd_skipped`` gate does in
      graph mode);
    - ``train_sweep(params, hypers, norm, data, labels, index_matrix,
      valid_sizes, total_valid) -> (params, (loss, n_err))`` — a whole
      class sweep as ONE dispatch: ``lax.scan`` over the minibatch rows
      (identical per-row math), metrics summed over the sweep. This is
      what makes the product path dispatch-bound-free: one XLA call per
      class per epoch instead of one per minibatch;
    - ``eval_sweep(...)`` likewise without updates.

    ``grad_reduce`` selects the mesh gradient-merge wire tier
    (``parallel/mapreduce.py``): ``"f32"`` (default, == the plain
    psum), ``"bf16"``, or ``"int8"`` (quantized all-reduce with
    per-leaf scales). Metric scalars always reduce exact. Callers
    building for a mesh normally go through
    ``mapreduce.fleet_train_step``, which also instruments the
    programs for the /metrics plane.
    """
    key = _tick_key(specs, norm_type, with_confusion, augment,
                    loss_kind, grad_reduce, mesh)
    cached = _TICK_CACHE.get(key)
    if cached is not None:
        return cached
    layer_fwds = [_layer_forward(s) for s in specs]
    data_ax = mesh.shape.get("data", 1) if mesh is not None else 1
    with_confusion = with_confusion and loss_kind == "softmax"

    # normalizer coefficients ride in through the traced ``norm`` dict
    # (``jit_state()``), so re-analyzed datasets never retrace the tick
    norm_cls = normalizer_registry[norm_type]

    def gather_norm(data, labels, indices, norm):
        batch, lab = gather_minibatch(data, indices, labels)
        return norm_cls.apply_state(jnp, batch, norm), lab

    def apply_augment(batch, seed):
        # the SAME traced functions the graph path jits — numeric
        # parity with the loaders' fill_minibatch is structural
        from veles_tpu.ops.augment import TRANSFORMS
        transform = TRANSFORMS.get(augment)
        if transform is None:
            return batch
        return transform(batch, seed)

    def model_forward(wb, x):
        for fwd, p in zip(layer_fwds, wb):
            x = fwd(p, x)
        return x

    def local_mask(n_local, valid):
        pos = jnp.arange(n_local)
        if data_ax > 1:
            pos = pos + lax.axis_index("data") * n_local
        return (pos < valid).astype(jnp.float32)

    def metrics_of(wb, batch, lab, mask, valid):
        """``lab`` is int labels (softmax) or float targets (mse) — both
        gathered from the device-resident originals by the same indices."""
        logits = model_forward(wb, batch)
        if loss_kind == "mse":
            _, loss_sum, _ = losses.masked_mse(logits, lab, mask, valid)
            return loss_sum, jnp.int32(0), logits
        _, loss_sum, n_err, _ = losses.masked_softmax_xent(
            logits, lab, mask, valid)
        return loss_sum, n_err, logits

    # cores return the UNNORMALIZED loss_sum; wrappers divide by the
    # relevant valid count (per minibatch or per sweep)
    def core_train(params, hypers, norm, data, labels, indices, valid,
                   seed):
        batch, lab = gather_norm(data, labels, indices, norm)
        batch = apply_augment(batch, seed)
        mask = local_mask(indices.shape[0], valid)
        wb = [p["p"] if p else {} for p in params]

        def loss_fn(wb):
            loss_sum, n_err, _ = metrics_of(wb, batch, lab, mask, valid)
            return loss_sum / valid, (loss_sum, n_err)

        (_, (loss_sum, n_err)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(wb)
        if data_ax > 1:
            # the in-program fleet aggregation (parallel/mapreduce.py):
            # gradients merge at the configured wire tier (f32 IS the
            # plain psum, bit-identical to the pre-tier programs);
            # metric scalars always reduce exact
            grads = mapreduce.reduce_sum(grads, "data",
                                         precision=grad_reduce)
            loss_sum = mapreduce.reduce_sum(loss_sum, "data")
            n_err = mapreduce.reduce_sum(n_err, "data")
        new = []
        for p, g, hyper, spec in zip(params, grads, hypers, specs):
            if not p:
                new.append({})
                continue
            from veles_tpu.nn.gd import make_updater
            lr, lr_b, l2, l1 = hyper[0], hyper[1], hyper[2], hyper[3]
            solver = spec.get("solver", "momentum")
            step = p["t"] + 1.0 if solver != "momentum" else None
            upd = make_updater(solver, hyper, step)
            entry = {"p": {}, "v": {}}
            if solver != "momentum":
                entry["s"], entry["t"] = {}, step
            # per-leaf policy from the spec table: which rate applies
            # and whether l2/l1 decay does — matching each graph-mode GD
            # unit's exact update math (same make_updater)
            for leaf, _, _, use_lr_b, decay in spec["leaves"]:
                w, gw, vel = p["p"][leaf], g[leaf], p["v"][leaf]
                if decay:
                    gw = gw + l2 * w + l1 * jnp.sign(w)
                w2, v2, s2 = upd(w, gw, vel,
                                 p["s"][leaf] if solver != "momentum"
                                 else None,
                                 lr_b if use_lr_b else lr)
                entry["p"][leaf] = w2
                entry["v"][leaf] = v2
                if solver != "momentum":
                    entry["s"][leaf] = s2
            new.append(entry)
        return new, (loss_sum, n_err)

    def core_eval(params, norm, data, labels, indices, valid):
        """Eval additionally emits the confusion-matrix increment (when
        the evaluator asked for it), so the MatrixPlotter / Decision
        accumulation work in fused mode too."""
        batch, lab = gather_norm(data, labels, indices, norm)
        mask = local_mask(indices.shape[0], valid)
        wb = [p["p"] if p else {} for p in params]
        loss_sum, n_err, logits = metrics_of(wb, batch, lab, mask, valid)
        cm = (losses.confusion_matrix(logits, lab, logits.shape[-1], mask)
              if with_confusion else jnp.zeros((1, 1), jnp.int32))
        if data_ax > 1:
            loss_sum = mapreduce.reduce_sum(loss_sum, "data")
            n_err = mapreduce.reduce_sum(n_err, "data")
            cm = mapreduce.reduce_sum(cm, "data")
        return loss_sum, n_err, cm

    def local_train(params, hypers, norm, data, labels, indices, valid,
                    seed):
        new, (loss_sum, n_err) = core_train(params, hypers, norm, data,
                                            labels, indices, valid, seed)
        return new, (loss_sum / valid, n_err)

    def local_eval(params, norm, data, labels, indices, valid):
        loss_sum, n_err, cm = core_eval(params, norm, data, labels,
                                        indices, valid)
        return loss_sum / valid, n_err, cm

    def local_train_sweep(params, hypers, norm, data, labels,
                          index_matrix, valid_sizes, total_valid,
                          seeds):
        def body(carry, xs):
            indices, valid, seed = xs
            new, (loss_sum, n_err) = core_train(
                carry, hypers, norm, data, labels, indices,
                valid.astype(jnp.float32), seed)
            return new, (loss_sum, n_err)

        params, (loss_sums, n_errs) = lax.scan(
            body, params, (index_matrix, valid_sizes, seeds))
        return params, (jnp.sum(loss_sums) / total_valid,
                        jnp.sum(n_errs))

    def local_eval_sweep(params, norm, data, labels, index_matrix,
                         valid_sizes, total_valid):
        def body(carry, xs):
            indices, valid = xs
            return carry, core_eval(params, norm, data, labels, indices,
                                    valid.astype(jnp.float32))

        _, (loss_sums, n_errs, cms) = lax.scan(
            body, 0, (index_matrix, valid_sizes))
        return (jnp.sum(loss_sums) / total_valid, jnp.sum(n_errs),
                jnp.sum(cms, axis=0))

    if data_ax == 1:
        steps = (jax.jit(local_train, donate_argnums=(0,)),
                 jax.jit(local_eval),
                 jax.jit(local_train_sweep, donate_argnums=(0,)),
                 jax.jit(local_eval_sweep))
        _TICK_CACHE[key] = steps
        return steps
    eval_specs = (P(), P(), P(), P(), P("data"), P())
    train_specs = (P(),) + eval_specs + (P(),)  # + seed
    eval_sweep_specs = (P(), P(), P(), P(), P(None, "data"), P(), P())
    train_sweep_specs = (P(),) + eval_sweep_specs + (P(),)  # + seeds
    train = shard_map(local_train, mesh=mesh, in_specs=train_specs,
                      out_specs=(P(), (P(), P())))
    evaluate = shard_map(local_eval, mesh=mesh, in_specs=eval_specs,
                         out_specs=(P(), P(), P()))
    train_sweep = shard_map(
        local_train_sweep, mesh=mesh, in_specs=train_sweep_specs,
        out_specs=(P(), (P(), P())))
    eval_sweep = shard_map(
        local_eval_sweep, mesh=mesh, in_specs=eval_sweep_specs,
        out_specs=(P(), P(), P()))
    steps = (jax.jit(train, donate_argnums=(0,)), jax.jit(evaluate),
             jax.jit(train_sweep, donate_argnums=(0,)),
             jax.jit(eval_sweep))
    _TICK_CACHE[key] = steps
    return steps


def supports(workflow, mesh=None):
    """True when the workflow's compute chain can run as a fused tick."""
    from veles_tpu.loader.fullbatch import (FullBatchLoader,
                                            FullBatchLoaderMSE)
    from veles_tpu.nn.evaluator import EvaluatorMSE, EvaluatorSoftmax

    loader = getattr(workflow, "loader", None)
    if not isinstance(loader, FullBatchLoader) or not loader.on_device:
        return False
    evaluator = getattr(workflow, "evaluator", None)
    if isinstance(evaluator, EvaluatorMSE):
        # regression tick: targets gathered from the device-resident
        # original_targets exactly like labels
        if not isinstance(loader, FullBatchLoaderMSE):
            return False
    elif not isinstance(evaluator, EvaluatorSoftmax):
        return False
    if getattr(loader, "has_fill_transforms", False):
        # the fused gather bypasses fill_minibatch — fusion stays on
        # only for transforms the tick replicates in-jit itself
        # (single-device: per-sample randomness draws over the GLOBAL
        # minibatch, which a data-sharded tick could not reproduce)
        from veles_tpu.ops.augment import TRANSFORMS
        if getattr(loader, "jit_transform", None) not in TRANSFORMS \
                or mesh is not None:
            return False
    if extract_model_spec(workflow) is None:
        return False
    # the control chain must be EXACTLY the standard topology: a custom
    # unit spliced into the cycle (it wouldn't appear in .forwards/.gds)
    # must not be silently dropped by the fused splice — such chains
    # belong to the partial-fusion tier (parallel/segments.py)
    from veles_tpu.parallel.segments import chain_of
    chain = chain_of(workflow)
    expected = (list(workflow.forwards) + [workflow.evaluator,
                                           workflow.decision]
                + list(reversed(workflow.gds)))
    if chain != expected:
        return False
    if mesh is not None:
        data_ax = mesh.shape.get("data", 1)
        if loader.max_minibatch_size % data_ax:
            return False
    return True


class FusedTick(Unit):
    """One workflow tick as one fused XLA computation.

    Reads the loader's served indices + epoch flags, runs the train or
    eval step for the tick's sample class, writes the metric scalars into
    the evaluator's slots (lazy device values — the Decision unit reads
    them at epoch boundaries exactly as in graph mode), and writes weights
    back into the unit Arrays at epoch boundaries so the Snapshotter and
    fleet paths always see current state.
    """

    hide_from_registry = True
    VIEW_GROUP = "WORKER"
    #: execution strategy, not topology: excluded from the workflow
    #: checksum so fused slaves pair with graph masters
    EPHEMERAL = True

    def __init__(self, workflow, mesh=None, pipelined=False, **kwargs):
        super().__init__(workflow, **kwargs)
        # trailing underscore: a jax Mesh holds Device objects and cannot
        # be pickled — a resumed pod-mode snapshot falls back to the
        # single-device fused tick unless the caller re-supplies a mesh
        self.mesh_ = mesh
        #: pipelined epoch mode: the Decision materializes each epoch's
        #: metrics one epoch late (pipeline_depth=1) so the per-epoch
        #: device sync overlaps the next epoch's compute. The tick then
        #: keeps a one-slot params history so (a) the unit Arrays always
        #: hold the weights the CURRENTLY-ATTRIBUTED metrics scored and
        #: (b) a lagged no-improvement stop can roll back the one
        #: speculatively-trained epoch — outputs stay identical to the
        #: unpipelined run.
        self.pipelined = pipelined
        self.ticks = 0

    @property
    def mesh(self):
        return self.mesh_

    def init_unpickled(self):
        super().init_unpickled()
        if not hasattr(self, "mesh_"):
            self.mesh_ = None
        self._params_ = None
        self._steps_ = None
        self._norm_ = None
        self._specs_ = None
        #: control-plane fleet: params snapshot taken before the last
        #: TRAIN tick — a re-issued job (lost update) rolls back to it
        self._rollback_ = None
        self._wrote_eval_params_ = False
        if not hasattr(self, "pipelined"):
            self.pipelined = False
        self._eval_stash_ = None  # params evaluated one epoch ago
        self._stashed_this_epoch_ = False

    def initialize(self, **kwargs):
        wf = self.workflow
        loader = wf.loader
        if not loader.on_device:
            # the loader's HBM-OOM fallback kicked in during load_data —
            # fused gather from host originals would re-transfer the whole
            # dataset every tick; revert to graph mode
            self.warning("dataset fell back to host: disabling fused mode")
            if wf.is_slave:
                wf._disable_fused_slave()
            else:
                wf._disable_fused()
            return
        if self.mesh_ is not None:
            # a resumed snapshot can acquire a mesh the original build
            # never validated (supports() runs before the splice only)
            data_ax = self.mesh_.shape.get("data", 1)
            if loader.max_minibatch_size % data_ax:
                self.warning(
                    "minibatch size %d does not divide by the mesh data "
                    "axis %d — running the fused tick single-device",
                    loader.max_minibatch_size, data_ax)
                self.mesh_ = None
        for fwd in wf.forwards:
            weights = getattr(fwd, "weights", None)
            if weights is not None and weights.data is None:
                return True  # retry after the forwards initialize
        if self.pipelined:
            if (not getattr(loader, "sweep_serving", False)
                    or loader.effective_class_lengths[VALID] == 0):
                # lagged improvement tracking needs a VALID sweep; and
                # without sweep serving there is no per-epoch sync to
                # hide in the first place
                self.warning("pipelined mode needs sweep serving and a "
                             "validation split: disabling")
                self.pipelined = False
            wf.decision.pipeline_depth = 1 if self.pipelined else 0
        from veles_tpu.nn.evaluator import EvaluatorMSE
        self._loss_kind_ = ("mse" if isinstance(wf.evaluator,
                                                EvaluatorMSE)
                            else "softmax")
        self._specs_ = extract_model_spec(wf)
        self._norm_ = {k: jnp.asarray(v) for k, v in
                       loader.normalizer.jit_state().items()}
        if self.mesh_ is not None:
            # meshed ticks build through the mapreduce layer: same
            # compiled programs (build_tick underneath, f32 reduce ==
            # the old psum) plus xla_stats instrumentation and the
            # configured gradient-reduce wire tier
            self._steps_ = mapreduce.fleet_train_step(
                self.mesh_, self._specs_, loader.normalization_type,
                with_confusion=getattr(wf.evaluator,
                                       "compute_confusion", True),
                augment=getattr(loader, "jit_transform", None)
                or "none",
                loss_kind=self._loss_kind_)
        else:
            self._steps_ = build_tick(
                self._specs_, loader.normalization_type, self.mesh_,
                with_confusion=getattr(wf.evaluator,
                                       "compute_confusion", True),
                augment=getattr(loader, "jit_transform", None)
                or "none",
                loss_kind=self._loss_kind_)

    def run(self):
        import numpy
        wf = self.workflow
        loader = wf.loader
        control = wf.is_slave and self._control_plane()
        if self._params_ is None or (wf.is_slave and not control):
            # copy: the unit Arrays keep their own buffers — ours get
            # donated through the train step. A data-plane SLAVE
            # refreshes every tick: the master overwrites the unit
            # Arrays between jobs (apply_data_from_master). A
            # CONTROL-plane slave keeps its params device-resident —
            # the wire no longer carries weights, so the local replica
            # is the authoritative mid-epoch state
            self._params_ = jax.tree.map(
                jnp.copy, get_params(wf, self._specs_))
        train_step, eval_step, train_sweep, eval_sweep = self._steps_
        norm = self._norm_
        data = loader.original_data.data
        if getattr(self, "_loss_kind_", "softmax") == "mse":
            # regression: the "labels" lane carries the float targets
            labels = loader.original_targets.data
        else:
            labels = loader.labels_for_gather()
        indices = loader.minibatch_indices.data
        valid = numpy.float32(max(loader.minibatch_valid_size, 1))
        training = loader.minibatch_class == TRAIN
        if control:
            # one-slot rollback stash: a job whose update frame is
            # lost gets re-issued by the master; the replay must start
            # from exactly the pre-job params (sync-mode pipelining
            # bounds the unacknowledged depth to one). Eval ticks
            # mutate nothing — no slot, rollback_job is then a no-op
            self._rollback_ = (jax.tree.map(jnp.copy, self._params_)
                               if training else None)
        if getattr(loader, "sweep_serving", False):
            sizes = loader.sweep_valid_sizes
            if training:
                seeds = getattr(loader, "sweep_transform_seeds", None)
                if seeds is None:
                    seeds = numpy.zeros(len(sizes), numpy.int64)
                self._params_, (loss, n_err) = train_sweep(
                    self._params_, get_hypers(wf), norm, data, labels,
                    indices, sizes, valid, seeds)
            else:
                loss, n_err, cm = eval_sweep(self._params_, norm, data,
                                             labels, indices, sizes,
                                             valid)
        elif training:
            seed = numpy.int64(getattr(loader, "minibatch_transform_seed",
                                       0))
            self._params_, (loss, n_err) = train_step(
                self._params_, get_hypers(wf), norm, data, labels,
                indices, valid, seed)
        else:
            loss, n_err, cm = eval_step(self._params_, norm, data,
                                        labels, indices, valid)
        evaluator = wf.evaluator
        evaluator.loss.data = loss
        if getattr(evaluator, "n_err", None) is not None:
            evaluator.n_err.data = n_err
        if not training \
                and getattr(self, "_loss_kind_", "softmax") != "mse" \
                and getattr(evaluator, "compute_confusion", True):
            # eval passes also emit the confusion increment, so the
            # Decision accumulation + MatrixPlotter work in fused mode
            evaluator.confusion_matrix.data = cm
        self.ticks += 1
        if wf.is_slave:
            if control:
                # control plane: the unit Arrays are written only at
                # EPOCH FENCES — they feed the bulk fence-sync payload
                # the client ships (docs/compiler_fleet.md); per-job
                # updates carry scalars only
                if bool(loader.epoch_ended):
                    set_params(wf, self._params_, self._specs_)
            elif training:
                # data plane (one tick per job): write the trained
                # weights straight back so generate_data_for_master
                # ships them; epoch accounting lives on the master
                set_params(wf, self._params_, self._specs_)
            return
        if not training and loader.epoch_ended_for_class:
            # write the EVALUATED weights into the unit Arrays now —
            # they stay untouched through the upcoming train sweep, so a
            # Snapshotter firing on ``improved`` captures exactly the
            # weights that scored the validation metric (the reference's
            # snapshot-on-improved semantics; with the decision's
            # deferred sweep materialization ``improved`` fires on the
            # epoch-end tick, after this epoch's training)
            if self.pipelined:
                # metrics are attributed one epoch late: the Arrays must
                # lag the same way. Rotate the one-slot history — write
                # the params the PREVIOUS epoch evaluated, stash the
                # ones this epoch's eval sweep is scoring right now.
                if not self._stashed_this_epoch_:
                    current = jax.tree.map(jnp.copy, self._params_)
                    if self._eval_stash_ is not None:
                        set_params(wf, self._eval_stash_, self._specs_)
                    self._eval_stash_ = current
                    self._stashed_this_epoch_ = True
            else:
                set_params(wf, self._params_, self._specs_)
            self._wrote_eval_params_ = True
        if loader.epoch_ended:
            # the eval-tick write stands in for the epoch-end one ONLY
            # when a VALID class exists — improvement then tracks the
            # eval metric. Without VALID samples the Decision tracks
            # THIS epoch's train error, so the Arrays must follow the
            # post-train state (a TEST-only eval write would pin them
            # one epoch behind the tracked metric)
            eval_covers = (getattr(self, "_wrote_eval_params_", False)
                           and loader.effective_class_lengths[VALID] > 0)
            if training and not eval_covers:
                set_params(wf, self._params_, self._specs_)
            self._wrote_eval_params_ = False
            self._stashed_this_epoch_ = False

    @staticmethod
    def _control_plane():
        from veles_tpu.fleet import fleet_control_plane
        return fleet_control_plane()

    def rollback_job(self):
        """Control-plane fleet: undo the LAST job's local application.
        Returns True when params were actually restored (the last job
        was a train tick); False when there was nothing to undo (eval
        tick — idempotent to re-run). Called by the fleet client when
        the master re-issues work whose update never arrived."""
        if self._rollback_ is None:
            return False
        self._params_ = self._rollback_
        self._rollback_ = None
        return True

    def reset_residency(self):
        """Drop the device-resident params so the next tick refreshes
        from the unit Arrays — called after a master handshake applied
        fresh initial weights (master restart / first join in
        control-plane mode)."""
        self._params_ = None
        self._rollback_ = None

    def advance_eval_params(self):
        """Write the one-slot history's evaluated params into the unit
        Arrays — the Decision calls this when a multi-epoch drain is
        about to attribute an improvement to the NEWER epoch, whose
        evaluated weights sit in the stash (see _drain_epochs)."""
        if self._eval_stash_ is not None:
            set_params(self.workflow, self._eval_stash_, self._specs_)
            self._eval_stash_ = None

    def rollback_speculative(self):
        """A lagged stop decision arrived AFTER one more epoch was
        speculatively dispatched: restore the params to the stopping
        epoch's post-train state (the one-slot stash holds exactly it —
        pipeline depth is 1)."""
        if self._eval_stash_ is not None:
            self._params_ = self._eval_stash_
            self._eval_stash_ = None

    def sync_params(self):
        """Write the CURRENT (post-train) params into the unit Arrays —
        called when the workflow finishes so exports, results and the
        final snapshot see the last training state."""
        if self._params_ is not None and self._specs_ is not None:
            set_params(self.workflow, self._params_, self._specs_)
