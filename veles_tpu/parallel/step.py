"""The tick compiler: one fused SPMD train step for a unit-chain workflow.

SURVEY §7's central design translation: the reference executes a tick as a
chain of per-unit kernel launches (loader gather → forward GEMMs →
evaluator → per-layer GD updates); here the whole tick is traced into ONE
jitted, mesh-sharded computation. The unit graph remains the composition
API — this module *extracts* the static spec (layer activations,
hyperparameters, normalization) from the live units and emits the fused
function, so graph-mode and fused-mode are numerically identical.

Shardings (over ``veles_tpu.parallel.mesh`` axes):

- **data**: batch rows; gradients are ``psum``-merged over ICI — the
  synchronous TPU answer to the reference's master/slave update merge;
- **model**: Megatron-style column sharding of every layer's weights;
  activations ``all_gather``-ed between layers, weight-gradient slices
  computed locally, input-error partial sums ``psum``-ed.

Params/state live as a pytree ``{"w": [...], "b": [...], "vw": [...],
"vb": [...]}`` donated through the step, so weights stay device-resident
across the epoch with zero host traffic.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.ops import activations as act_lib, losses
from veles_tpu.parallel.mesh import shard_map
from veles_tpu.ops.gemm import matmul


def extract_layer_spec(workflow):
    """Static per-layer config from a workflow's forwards/gds chains."""
    spec = []
    for i, fwd in enumerate(workflow.forwards):
        gd = workflow.gds[i] if workflow.gds else None
        spec.append({
            "activation": fwd.ACTIVATION,
            "learning_rate": gd.learning_rate if gd else 0.0,
            "learning_rate_bias": (
                gd.learning_rate_bias if gd and gd.learning_rate_bias
                is not None else (gd.learning_rate if gd else 0.0)),
            "weights_decay": gd.weights_decay if gd else 0.0,
            "l1_vs_l2": gd.l1_vs_l2 if gd else 0.0,
            "gradient_moment": gd.gradient_moment if gd else 0.0,
        })
    return spec


def get_params(workflow):
    """Snapshot the unit chain's weights into the fused-step pytree."""
    return {
        "w": [fwd.weights.data for fwd in workflow.forwards],
        "b": [fwd.bias.data for fwd in workflow.forwards],
        "vw": [gd._velocity_w.data if gd._velocity_w.data is not None
               else jnp.zeros_like(fwd.weights.data)
               for gd, fwd in zip(workflow.gds, workflow.forwards)],
        "vb": [gd._velocity_b.data if gd._velocity_b.data is not None
               else jnp.zeros_like(fwd.bias.data)
               for gd, fwd in zip(workflow.gds, workflow.forwards)],
    }


def set_params(workflow, params):
    """Write fused-step results back into the shared unit Array slots."""
    for i, fwd in enumerate(workflow.forwards):
        fwd.weights.data = params["w"][i]
        fwd.bias.data = params["b"][i]
        workflow.gds[i]._velocity_w.data = params["vw"][i]
        workflow.gds[i]._velocity_b.data = params["vb"][i]


def build_train_step(layer_spec, mesh=None, donate=True):
    """Compile the fused train step.

    Returns ``step(params, batch, labels, mask) -> (params, metrics)`` where
    metrics = (loss, n_err). With a mesh, the step is shard_map-ped over
    (data, model) with the collectives described in the module docstring.
    """
    n_layers = len(layer_spec)
    acts = [act_lib.ACTIVATIONS[s["activation"]] for s in layer_spec]
    hyper = [(s["learning_rate"], s["learning_rate_bias"],
              s["weights_decay"], s["l1_vs_l2"], s["gradient_moment"])
             for s in layer_spec]
    data_ax = mesh.shape.get("data", 1) if mesh is not None else 1
    model_ax = mesh.shape.get("model", 1) if mesh is not None else 1

    def local_step(params, batch, labels, mask):
        # ---- forward, saving activations ----
        x = batch.reshape(batch.shape[0], -1)
        saved = [x]
        for i in range(n_layers):
            w, b = params["w"][i], params["b"][i]
            y = matmul(x, w, out_dtype=jnp.float32)
            if model_ax > 1:  # columns sharded: assemble the full width
                y = jax.lax.all_gather(y, "model", axis=1, tiled=True)
            y = y + _full_bias(params["b"][i], model_ax)
            if i < n_layers - 1:
                y = acts[i][0](y)
            saved.append(y)
            x = y
        logits = saved[-1]

        # ---- evaluator: softmax xent on the global batch (shared op —
        # keeps fused mode numerically identical to EvaluatorSoftmax) ----
        valid = jnp.sum(mask)
        if data_ax > 1:
            valid = jax.lax.psum(valid, "data")
        valid = jnp.maximum(valid, 1.0)
        err, loss_sum, n_err, _ = losses.masked_softmax_xent(
            logits, labels, mask, valid)
        if data_ax > 1:
            loss_sum = jax.lax.psum(loss_sum, "data")
            n_err = jax.lax.psum(n_err, "data")
        loss = loss_sum / valid

        # ---- backward + update, deepest layer first ----
        new = {"w": list(params["w"]), "b": list(params["b"]),
               "vw": list(params["vw"]), "vb": list(params["vb"])}
        for i in reversed(range(n_layers)):
            lr, lr_b, l2, l1, moment = hyper[i]
            w, b = params["w"][i], params["b"][i]
            y = saved[i + 1]
            if i < n_layers - 1:
                err = err * acts[i][1](y)
            err_local = _model_shard(err, model_ax)  # this device's columns
            grad_w = matmul(saved[i].T, err_local, out_dtype=jnp.float32)
            grad_b = jnp.sum(err_local, axis=0)
            if data_ax > 1:
                grad_w = jax.lax.psum(grad_w, "data")
                grad_b = jax.lax.psum(grad_b, "data")
            grad_w = grad_w + l2 * w + l1 * jnp.sign(w)
            if i > 0:
                err = matmul(err_local, w.T, out_dtype=jnp.float32)
                if model_ax > 1:  # partial over column shards
                    err = jax.lax.psum(err, "model")
            vw = moment * new["vw"][i] - lr * grad_w
            vb = moment * new["vb"][i] - lr_b * grad_b
            new["w"][i] = w + vw
            new["b"][i] = b + vb
            new["vw"][i] = vw
            new["vb"][i] = vb
        return new, (loss, n_err)

    if mesh is None or (data_ax == 1 and model_ax == 1):
        fused = local_step
        jit_kwargs = {}
        if donate:
            jit_kwargs["donate_argnums"] = (0,)
        return jax.jit(fused, **jit_kwargs)

    wspec = P(None, "model")
    bspec = P("model")
    param_specs = {"w": [wspec] * n_layers, "b": [bspec] * n_layers,
                   "vw": [wspec] * n_layers, "vb": [bspec] * n_layers}
    in_specs = (param_specs, P("data"), P("data"), P("data"))
    out_specs = (param_specs, (P(), P()))
    fused = shard_map(local_step, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)
    jit_kwargs = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(fused, **jit_kwargs)


def _full_bias(b, model_ax):
    if model_ax > 1:
        return jax.lax.all_gather(b, "model", axis=0, tiled=True)
    return b


def _model_shard(err, model_ax):
    """Slice this device's column block out of a full-width error."""
    if model_ax == 1:
        return err
    from veles_tpu.parallel.mesh import axis_size
    cols = err.shape[1] // axis_size("model")
    idx = jax.lax.axis_index("model")
    return jax.lax.dynamic_slice_in_dim(err, idx * cols, cols, axis=1)


def shard_params(params, mesh):
    """Place a params pytree onto the mesh with the step's shardings."""
    wsh = NamedSharding(mesh, P(None, "model"))
    bsh = NamedSharding(mesh, P("model"))
    return {
        "w": [jax.device_put(w, wsh) for w in params["w"]],
        "b": [jax.device_put(b, bsh) for b in params["b"]],
        "vw": [jax.device_put(v, wsh) for v in params["vw"]],
        "vb": [jax.device_put(v, bsh) for v in params["vb"]],
    }


def shard_batch(arrays, mesh):
    """Place (batch, labels, mask) with data-axis sharding."""
    return [jax.device_put(a, NamedSharding(mesh, P("data")))
            for a in arrays]
