"""Sweep-tier partial fusion: ``lax.scan`` ANY JitUnit chain over whole
class sweeps.

The third fusion tier (VERDICT r3 #1). The full engine
(:mod:`veles_tpu.parallel.fused`) recognizes the standard forward/GD
topology and compiles hand-written sweep steps; the segment tier
(:mod:`veles_tpu.parallel.segments`) fuses runs of consecutive JitUnits
but still dispatches and serves per minibatch — which leaves any
workflow the full engine declines ~40x off the flagship path, because
per-tick host serving + dispatch dominates on a tunneled TPU (the
reference ran EVERY topology at full engine speed,
``veles/workflow.py:347-365``).

This tier closes that gap for any linear repeater cycle whose compute
units are JitUnits — including custom user layers the full engine has
never heard of — by composing the units' OWN ``compute()`` functions
into one per-minibatch body (dataflow derived from the shared Array
slots, exactly like the segment planner) and scanning that body over an
entire class sweep in ONE XLA dispatch per chunk:

- the loader switches to sweep serving (one index matrix per class per
  epoch — the fused engine's serving mode);
- the in-scan gather + normalize replicates the loader's jitted fill
  (``FullBatchLoader._fill_jit``) exactly;
- slots written by one iteration and read by the next (weights,
  velocities, Adam moments — anything the slot graph says) ride the
  scan carry; everything else stays intra-iteration dataflow;
- TRAIN sweeps include the units gated on ``decision.gd_skipped``; eval
  sweeps trace a variant without them — the same class-constant gate
  decision graph mode makes per tick;
- the Decision consumes sweep-aggregated metrics through its existing
  sweep-serving branch (the fused engine's contract).

Host units in the cycle still fire once per tick, between scanned runs:
the sweep executes in chunks (``root.common.engine.sweep_chunk``
minibatches per dispatch), and after each chunk is dispatched —
asynchronously, XLA computes while the host works — every mid-chain
host unit runs once per minibatch of that chunk, in chain order. This
is only observably identical to graph mode when those units do not read
or write device Array slots, so they must declare it:
``sweep_transparent = True`` (see :class:`veles_tpu.core.units.Unit`).
A non-transparent host unit makes the workflow fall back to the
per-tick segment tier — correctness beats speed.

Weight semantics match every other tier: the stopping epoch's last
TRAIN minibatch applies its update before the run finishes (graph mode
wires the EndPoint's AND-gate behind the gd chain for the same effect —
see StandardWorkflow.__init__). Metrics are bit-identical to graph mode
throughout — every metric sweep precedes the updates.
"""

import numpy

import jax
import jax.numpy as jnp
from jax import lax

from veles_tpu.core.config import root
from veles_tpu.core.units import Unit
from veles_tpu.loader.base import TRAIN
from veles_tpu.memory import Array
from veles_tpu.ops.gather import gather_minibatch
from veles_tpu.parallel.segments import (_default_block, _default_skip,
                                         _fusible, chain_of)

#: loader slot attr -> lane name produced inside the scan body
_LANES = (("minibatch_data", "data"), ("minibatch_labels", "labels"),
          ("minibatch_targets", "targets"), ("sample_mask", "mask"),
          ("minibatch_indices", "indices"))


def classify(workflow):
    """Sweep eligibility: returns ``(members, hosts)`` or None.

    ``members`` is the ordered list of ``(unit, train_only)`` compute
    steps (the Decision excluded — it is hoisted out of the cycle and
    fed sweep aggregates); ``hosts`` the ordered transparent host
    units. Gate rule: a member carries its birth gates, or the standard
    Decision wiring (``gate_skip is decision.gd_skipped`` => TRAIN-only,
    ``gate_block is decision.complete`` => stop-gated, which sweep mode
    subsumes by stopping the serving loop)."""
    from veles_tpu.loader.fullbatch import FullBatchLoader

    loader = getattr(workflow, "loader", None)
    decision = getattr(workflow, "decision", None)
    if decision is None or loader is None:
        return None
    if not isinstance(loader, FullBatchLoader) or not loader.on_device:
        return None
    if getattr(loader, "has_fill_transforms", False):
        # in-fill augmentation draws per-minibatch randomness the scan
        # does not replicate (the full engine special-cases "mirror")
        return None
    chain = chain_of(workflow)
    if not chain or decision not in chain:
        return None
    allowed = set(chain) | {loader, workflow.repeater, decision}
    members, hosts = [], []
    for unit in chain:
        if unit is decision:
            continue
        # the EndPoint hangs off the LAST chain unit (its AND-gate holds
        # the final update before finish — StandardWorkflow wiring); the
        # sweep splice subsumes that by stopping the serving loop, and
        # disable() restores exactly this link. An end_point link from
        # any OTHER unit is custom finish wiring the splice could not
        # restore — those chains stay on the segment tier.
        permitted = allowed | ({workflow.end_point}
                               if unit is chain[-1] else set())
        outside = [u for u in list(unit.links_from) + list(unit.links_to)
                   if u not in permitted]
        if outside:
            # a monitor/provider hangs off a cycle unit: per-sweep
            # execution would change when it fires — segment tier keeps
            # per-tick semantics for it
            return None
        if _fusible(unit):
            if any(not isinstance(getattr(unit, n), Array)
                   for n in unit.OUTPUTS):
                return None  # non-Array outputs: can't carry through scan
            train_only = False
            if not _default_skip(unit):
                if unit.gate_skip is decision.gd_skipped:
                    train_only = True
                else:
                    return None
            if not _default_block(unit) \
                    and unit.gate_block is not decision.complete:
                return None
            members.append((unit, train_only))
        elif getattr(unit, "sweep_transparent", False):
            if not (_default_skip(unit) and _default_block(unit)):
                return None
            hosts.append(unit)
        else:
            return None
    if not members:
        return None
    evaluator = getattr(workflow, "evaluator", None)
    if evaluator is None or evaluator not in (u for u, _ in members):
        return None  # the Decision's sweep branch needs the aggregates
    return members, hosts


def _lane_ids(loader):
    lanes = {}
    for attr, lane in _LANES:
        slot = getattr(loader, attr, None)
        if isinstance(slot, Array):
            lanes[id(slot)] = lane
    return lanes


class _Plan:
    """Static dataflow plan for one gate variant (train or eval).

    ``steps``: ``(unit, in_refs, outs)`` in chain order, where in_refs
    tag each compute argument as ``("env", pos)`` intra-iteration,
    ``("lane", name)`` loader-served, ``("carry", idx)`` previous
    iteration's write, or ``("const", idx)`` per-sweep constant.
    ``writes``: ordered ``(unit, attr)`` — every slot the body produces,
    deduped by Array identity (the scan carry and the post-sweep
    scatter). ``carry_reads``: positions in ``writes`` that seed
    cross-iteration reads. ``consts``: ``(unit, attr)`` read once per
    sweep dispatch (weights in the eval variant, hyper vectors, .)."""

    def __init__(self, members, lanes):
        written = {}  # id(Array) -> write index
        writes = []   # (unit, attr) representative
        for unit, _ in members:
            for name in unit.OUTPUTS:
                slot = getattr(unit, name)
                key = id(slot)
                if key not in written:
                    written[key] = len(writes)
                    writes.append((unit, name))
        consts, const_index = [], {}
        steps = []
        produced = {}  # id(Array) -> env position (this iteration)
        carry_read_set = {}
        n_values = 0
        for unit, _ in members:
            in_refs = []
            for name in unit.INPUTS:
                slot = getattr(unit, name)
                if isinstance(slot, Array):
                    key = id(slot)
                    if key in produced:
                        in_refs.append(("env", produced[key]))
                        continue
                    if key in lanes:
                        in_refs.append(("lane", lanes[key]))
                        continue
                    if key in written:
                        # read before this iteration's write: previous
                        # iteration's value rides the carry
                        idx = carry_read_set.setdefault(key, written[key])
                        in_refs.append(("carry", idx))
                        continue
                else:
                    key = (id(unit), name)
                if key not in const_index:
                    const_index[key] = len(consts)
                    consts.append((unit, name))
                in_refs.append(("const", const_index[key]))
            outs = []
            for name in unit.OUTPUTS:
                slot = getattr(unit, name)
                pos = n_values
                n_values += 1
                produced[id(slot)] = pos
                outs.append((pos, written[id(slot)]))
            steps.append((unit, in_refs, outs))
        self.steps = steps
        self.writes = writes
        self.written = written
        #: carry slots that must hold REAL values before iteration 0
        self.carry_reads = sorted(set(carry_read_set.values()))
        self.consts = consts
        self.n_values = n_values


class FusedSweep(Unit):
    """One class sweep of the whole repeater cycle as chunked
    ``lax.scan`` dispatches over the units' own computes.

    Spliced like the FusedTick: ``loader -> FusedSweep -> decision ->
    repeater``; the member units stay constructed (weights, exports,
    snapshots all read their Array slots — final values are scattered
    back after every sweep) but leave the control graph.
    """

    hide_from_registry = True
    VIEW_GROUP = "WORKER"
    #: execution strategy, not topology (see Workflow.checksum)
    EPHEMERAL = True

    def __init__(self, workflow, members, hosts, chain_units,
                 pipelined=False, **kwargs):
        kwargs.setdefault("name", "sweep[%d units]" % len(members))
        super().__init__(workflow, **kwargs)
        self.members = list(members)  # [(unit, train_only)]
        self.hosts = list(hosts)
        #: the original linear cycle order (incl. the Decision) — the
        #: exact restore recipe for disable()
        self.chain_units = list(chain_units)
        self.chunk = int(root.common.engine.get("sweep_chunk", 64))
        #: pipelined epochs (the FusedTick design): the Decision
        #: materializes metrics one epoch late so the per-epoch
        #: device->host sync overlaps the next epoch's compute; the
        #: sweep keeps a one-slot state history so the unit Arrays
        #: always hold the weights the currently-attributed metrics
        #: scored, and a lagged stop rolls back the one speculative
        #: epoch — outputs identical to the unpipelined run.
        self.pipelined = pipelined
        self.ticks = 0

    def initialize(self, **kwargs):
        wf = self.workflow
        loader = wf.loader
        if not loader.on_device:
            # the loader's HBM-OOM fallback kicked in during load_data:
            # in-scan gather from host originals would re-upload the
            # dataset every chunk — restore per-tick graph mode
            self.warning("dataset fell back to host: disabling the "
                         "sweep tier")
            self.disable()
            return
        if self.pipelined:
            from veles_tpu.loader.base import VALID
            if loader.effective_class_lengths[VALID] == 0:
                # lagged improvement tracking needs a VALID sweep
                self.warning("pipelined sweeps need a validation split:"
                             " disabling pipelining")
                self.pipelined = False
            wf.decision.pipeline_depth = 1 if self.pipelined else 0

    def disable(self):
        """Undo the splice: relink the original linear cycle (classify
        guaranteed the chain had no outside links beyond the EndPoint
        gate, so a sequential relink + the finish gate is a complete
        restoration)."""
        wf = self.workflow
        loader = wf.loader
        self.unlink_all()
        wf.repeater.unlink_from(wf.decision)  # the splice's loop-back
        prev = loader
        for unit in self.chain_units:
            unit.link_from(prev)
            prev = unit
        wf.repeater.link_from(prev)
        # restore the finish gate EXACTLY as it was at enable() time: a
        # StandardWorkflow chain had the EndPoint AND-gated on the last
        # gd (the completing tick's update lands before finish); a
        # custom chain gated on the decision alone must NOT gain a
        # second AND input it never fires
        if getattr(self, "restore_finish_link", True):
            wf.end_point.link_from(prev)
        saved_gate = getattr(self, "saved_loader_gate", None)
        # `is not None`, not truthiness: a saved Bool(False) is falsy
        # but is exactly what must come back
        loader.gate_block = (saved_gate if saved_gate is not None
                             else wf.decision.complete)
        loader.fill_data = True
        loader.sweep_serving = False
        if getattr(wf, "sweep_unit", None) is self:
            wf.sweep_unit = None
        wf.del_ref(self)

    def init_unpickled(self):
        super().init_unpickled()
        self._plans_ = None
        self._fns_ = {}
        self._norm_ = None
        if not hasattr(self, "pipelined"):
            self.pipelined = False
        #: the TRUE current value of every written slot, keyed by
        #: id(Array) — reads prefer it over slot.data so the Arrays can
        #: lag one epoch in pipelined mode; volatile, so a resumed
        #: snapshot falls back to the slots (which then hold the
        #: restored state)
        self._state_ = {}
        self._eval_stash_ = None
        self._stashed_this_epoch_ = False
        self._wrote_eval_params_ = False

    # -- plan + compile -------------------------------------------------------
    def _build(self):
        loader = self.workflow.loader
        lanes = _lane_ids(loader)
        train_plan = _Plan(self.members, lanes)
        eval_plan = _Plan([(u, t) for u, t in self.members if not t],
                          lanes)
        self._plans_ = {True: train_plan, False: eval_plan}
        self._norm_ = {k: jnp.asarray(v) for k, v in
                       loader.normalizer.jit_state().items()}
        evaluator = self.workflow.evaluator
        self._metric_slots_ = {
            name: id(getattr(evaluator, name))
            for name in evaluator.OUTPUTS
            if name in ("loss", "n_err", "confusion_matrix")
            and isinstance(getattr(evaluator, name), Array)}
        self._with_confusion_ = (
            "confusion_matrix" in self._metric_slots_
            and getattr(evaluator, "compute_confusion", True))

    def _chunk_fn(self, training):
        """The jitted chunk executor for one gate variant (built once;
        jax retraces per chunk length)."""
        fn = self._fns_.get(training)
        if fn is not None:
            return fn
        plan = self._plans_[training]
        loader = self.workflow.loader
        norm_cls = type(loader.normalizer)
        metric = self._metric_slots_
        with_cm = self._with_confusion_
        loss_w = plan.written.get(metric.get("loss"))
        err_w = plan.written.get(metric.get("n_err"))
        cm_w = plan.written.get(metric.get("confusion_matrix"))

        def body(reads, consts, data, labels, targets, norm, row, valid):
            # the loader's jitted fill, replicated in-scan (same
            # gather + normalizer.apply_state math => same numerics)
            batch, lab = gather_minibatch(data, row, labels)
            batch = norm_cls.apply_state(jnp, batch, norm)
            mask = (jnp.arange(row.shape[0]) < valid).astype(jnp.float32)
            lane_vals = {"data": batch, "labels": lab, "mask": mask,
                         "indices": row}
            if targets is not None:
                lane_vals["targets"] = jnp.take(targets, row, axis=0)
            env = [None] * plan.n_values
            writes = list(reads)
            for unit, in_refs, outs in plan.steps:
                args = []
                for tag, ref in in_refs:
                    if tag == "env":
                        args.append(env[ref])
                    elif tag == "lane":
                        args.append(lane_vals[ref])
                    elif tag == "carry":
                        args.append(writes[ref])
                    else:
                        args.append(consts[ref])
                res = unit.compute(*args)
                if len(outs) == 1:
                    res = (res,)
                for (pos, widx), val in zip(outs, res):
                    env[pos] = val
                    writes[widx] = val
            valid_f = valid.astype(jnp.float32)
            loss_sum = (writes[loss_w] * valid_f
                        if loss_w is not None else jnp.float32(0))
            n_err = (writes[err_w] if err_w is not None
                     else jnp.int32(0))
            cm = (writes[cm_w] if with_cm and cm_w is not None
                  else jnp.zeros((1, 1), jnp.int32))
            return tuple(writes), (loss_sum, n_err, cm)

        def chunk(init_reads, consts, data, labels, targets, norm, rows,
                  valids):
            """``init_reads`` seed only the cross-iteration carry slots;
            iteration 0 populates the full write set, which then carries
            through the scan (write-only slots never need a pre-value)."""
            writes0 = [None] * len(plan.writes)
            for i, idx in enumerate(plan.carry_reads):
                writes0[idx] = init_reads[i]
            writes0, met0 = body(writes0, consts, data, labels, targets,
                                 norm, rows[0], valids[0])
            if rows.shape[0] == 1:
                return writes0, met0

            def scan_body(carry, xs):
                row, valid = xs
                return body(carry, consts, data, labels, targets, norm,
                            row, valid)

            writes, mets = lax.scan(scan_body, writes0,
                                    (rows[1:], valids[1:]))
            loss = met0[0] + jnp.sum(mets[0])
            n_err = met0[1] + jnp.sum(mets[1])
            cm = met0[2] + jnp.sum(mets[2], axis=0)
            return writes, (loss, n_err, cm)

        fn = jax.jit(chunk, static_argnames=())
        self._fns_[training] = fn
        return fn

    # -- per-sweep execution --------------------------------------------------
    def _gates_mutated(self):
        for unit, _ in self.members:
            if (_default_skip(unit) and bool(unit.gate_skip)) or \
                    (_default_block(unit) and bool(unit.gate_block)):
                return True
        for unit in self.hosts:
            if bool(unit.gate_skip) or bool(unit.gate_block):
                return True
        return False

    def run(self):
        wf = self.workflow
        loader = wf.loader
        if self._plans_ is None:
            self._build()
        klass = loader.minibatch_class
        training = klass == TRAIN
        matrix = numpy.asarray(loader.minibatch_indices.data)
        valids = numpy.asarray(loader.sweep_valid_sizes, numpy.int32)
        total_valid = max(int(loader.minibatch_valid_size), 1)
        if self._gates_mutated():
            if not getattr(self, "_warned_slow_", False):
                self.warning("%s: a member's default gate was mutated "
                             "after the sweep splice; running per-unit",
                             self.name)
                self._warned_slow_ = True
            # the slow path runs the units against their SLOTS: flush
            # the (possibly lagging) state first, and drop pipelining
            # for good — the slots are always current from here on, and
            # a later advance/rollback must not scatter a stale stash
            self._scatter_state(self._state_)
            self._state_ = {}
            self._eval_stash_ = None
            self._stashed_this_epoch_ = False
            self._wrote_eval_params_ = False
            if self.pipelined:
                self.pipelined = False
                wf.decision.pipeline_depth = 0
            self._run_slow(matrix, valids, training, total_valid)
            self.ticks += 1
            return
        plan = self._plans_[training]
        data = loader.original_data.data
        labels = loader.labels_for_gather()
        targets = getattr(getattr(loader, "original_targets", None),
                          "data", None)
        state = self._state_
        consts = []
        for unit, name in plan.consts:
            slot = getattr(unit, name)
            if isinstance(slot, Array):
                value = state.get(id(slot), slot.data)
                if value is None:
                    raise ValueError("%s: const slot %s.%s is empty"
                                     % (self.name, unit.name, name))
                consts.append(value)
            else:
                consts.append(slot)
        consts = tuple(consts)
        reads = []
        for idx in plan.carry_reads:
            unit, name = plan.writes[idx]
            slot = getattr(unit, name)
            value = state.get(id(slot), slot.data)
            if value is None:
                raise ValueError(
                    "%s: carry slot %s.%s is uninitialized"
                    % (self.name, unit.name, name))
            reads.append(value)
        fn = self._chunk_fn(training)
        chunk = self.chunk if self.hosts else len(matrix)
        chunk = max(chunk, 1)
        loss_sum = n_err_sum = cm_sum = None
        writes = None
        for start in range(0, len(matrix), chunk):
            rows = matrix[start:start + chunk]
            vrow = valids[start:start + chunk]
            writes, (loss, err, cm) = fn(tuple(reads), consts, data,
                                         labels, targets, self._norm_,
                                         rows, vrow)
            reads = [writes[i] for i in plan.carry_reads]
            # lazy device adds: a handful per sweep, settled by the
            # Decision's batched epoch read
            loss_sum = loss if loss_sum is None else loss_sum + loss
            n_err_sum = err if n_err_sum is None else n_err_sum + err
            cm_sum = cm if cm_sum is None else cm_sum + cm
            # host units fire once per tick, between scanned runs — the
            # chunk dispatch above is asynchronous, so the device is
            # already computing while these run
            for _ in range(len(rows)):
                for host in self.hosts:
                    host.run()
        for (unit, name), value in zip(plan.writes, writes):
            state[id(getattr(unit, name))] = value
        if not self.pipelined:
            # scatter every written slot's final value back into the
            # unit Arrays (lazy assignments — snapshotter/export/
            # plotters see graph-mode state at every sweep boundary)
            for (unit, name), value in zip(plan.writes, writes):
                getattr(unit, name).data = value
        else:
            self._rotate_pipelined(loader, training)
        self._publish_metrics(loader, training, loss_sum, n_err_sum,
                              cm_sum, total_valid)
        self.ticks += 1

    def _rotate_pipelined(self, loader, training):
        """Pipelined Array semantics (the FusedTick one-slot history):
        the unit Arrays lag one epoch, holding the weights the
        CURRENTLY-ATTRIBUTED metrics scored, so a Snapshotter firing on
        the lagged ``improved`` captures exactly the scoring state."""
        from veles_tpu.loader.base import VALID
        if not training and loader.epoch_ended_for_class:
            if not self._stashed_this_epoch_:
                current = dict(self._state_)
                if self._eval_stash_ is not None:
                    self._scatter_state(self._eval_stash_)
                self._eval_stash_ = current
                self._stashed_this_epoch_ = True
            self._wrote_eval_params_ = True
        if loader.epoch_ended:
            eval_covers = (self._wrote_eval_params_ and
                           loader.effective_class_lengths[VALID] > 0)
            if training and not eval_covers:
                self._scatter_state(self._state_)
            self._wrote_eval_params_ = False
            self._stashed_this_epoch_ = False

    def _scatter_state(self, state):
        """Write a state snapshot into the unit Arrays (train-plan
        writes are the superset of all written slots)."""
        if not state:
            return
        plan = self._plans_[True] if self._plans_ else None
        if plan is None:
            return
        for unit, name in plan.writes:
            slot = getattr(unit, name)
            value = state.get(id(slot))
            if value is not None:
                slot.data = value

    def advance_eval_params(self):
        """Decision drain hook (see FusedTick.advance_eval_params): a
        multi-epoch drain is about to attribute an improvement to the
        NEWER epoch — advance the Arrays to the state its eval scored."""
        if self._eval_stash_ is not None:
            self._scatter_state(self._eval_stash_)
            self._eval_stash_ = None

    def rollback_speculative(self):
        """A lagged stop arrived after one more epoch was speculatively
        trained: restore the state to the stopping epoch's evaluated
        weights (the one-slot stash holds exactly them)."""
        if self._eval_stash_ is not None:
            self._state_ = self._eval_stash_
            self._eval_stash_ = None

    def sync_params(self):
        """Workflow finished: the final (post-train) state lands in the
        unit Arrays so exports/results/final snapshots see it."""
        self._scatter_state(self._state_)

    def _publish_metrics(self, loader, training, loss_sum, n_err_sum,
                         cm_sum, total_valid):
        """The Decision's sweep-serving contract (the fused engine's):
        ``loss`` holds the sweep AVERAGE, ``n_err``/confusion the sweep
        sums."""
        evaluator = self.workflow.evaluator
        if "loss" in self._metric_slots_:
            evaluator.loss.data = loss_sum / total_valid
        if "n_err" in self._metric_slots_:
            evaluator.n_err.data = n_err_sum
        if not training and self._with_confusion_ and cm_sum is not None:
            evaluator.confusion_matrix.data = cm_sum

    def _run_slow(self, matrix, valids, training, total_valid):
        """Per-row fallback honoring live gate state (a birth gate was
        mutated after the splice): graph-mode unit execution per
        minibatch, sweep-aggregated metrics for the Decision."""
        loader = self.workflow.loader
        evaluator = self.workflow.evaluator
        # the ORIGINAL cycle order saved at enable() time — chain_of
        # would walk the rewired (spliced) graph here
        host_set = set(self.hosts)
        order = [u for u in self.chain_units
                 if u is not self.workflow.decision]
        loss_sum = n_err_sum = cm_sum = None
        for row, valid in zip(matrix, valids):
            loader.fill_minibatch(numpy.asarray(row), int(valid))
            for unit in order:
                if bool(unit.gate_block):
                    break
                if bool(unit.gate_skip):
                    continue
                if unit in host_set:
                    unit.run()
                    continue
                train_only = next(t for u, t in self.members if u is unit)
                if train_only and not training:
                    continue
                unit.run()
            valid_f = float(valid)
            if "loss" in self._metric_slots_:
                part = evaluator.loss.data * valid_f
                loss_sum = part if loss_sum is None else loss_sum + part
            if "n_err" in self._metric_slots_:
                n_err_sum = (evaluator.n_err.data if n_err_sum is None
                             else n_err_sum + evaluator.n_err.data)
            if not training and self._with_confusion_:
                cm = evaluator.confusion_matrix.data
                cm_sum = cm if cm_sum is None else cm_sum + cm
        self._publish_metrics(loader, training, loss_sum, n_err_sum,
                              cm_sum, total_valid)


def enable(workflow, pipelined=False):
    """Splice a FusedSweep over the repeater cycle. Returns the unit, or
    None when the workflow is not sweep-eligible (the caller then tries
    the per-tick segment tier). Call between construction and
    ``initialize()``."""
    info = classify(workflow)
    if info is None:
        return None
    members, hosts = info
    loader = workflow.loader
    decision = workflow.decision
    chain = chain_of(workflow)
    sweep = FusedSweep(workflow, members, hosts, chain,
                       pipelined=pipelined)
    # record what disable() must put back EXACTLY: whether the last
    # chain unit held the EndPoint finish gate (StandardWorkflow wiring;
    # a custom chain may gate the EndPoint on the decision alone), and
    # the loader's original stop gate
    sweep.restore_finish_link = (
        workflow.end_point in chain[-1].links_to)
    sweep.saved_loader_gate = loader.gate_block
    # detaching every non-Decision chain unit also clears its links INTO
    # the repeater and the Decision (unlink_all is bidirectional); the
    # repeater keeps its start_point provider, the Decision keeps its
    # outward links (end_point gate, plotters)
    for unit in chain:
        if unit is not decision:
            unit.unlink_all()
    # the cycle becomes: start -> repeater -> loader -> sweep ->
    # decision -> repeater (end_point keeps its decision link + gate)
    sweep.link_from(loader)
    decision.link_from(sweep)
    workflow.repeater.link_from(decision)
    loader.gate_block = decision.complete
    loader.fill_data = False
    loader.sweep_serving = True
    workflow.sweep_unit = sweep
    return sweep
