"""Portable train↔serve resharding: collective schedules, not device_put.

The mesh gives one checkpoint two natural layouts — the fused train
step wants params replicated over ``data`` (gradients psum over ICI),
the slot-engine serving tier wants them tensor-parallel over ``model``
with the KV cache sharded by head. Moving between them with a naive
``jax.device_put`` round-trips every shard through a host-mediated
copy-and-rescatter; *Memory-efficient array redistribution through
portable collective communication* (arxiv 2112.01075) shows any
``PartitionSpec`` change decomposes into a short schedule of portable
collectives that stays on the interconnect. This module implements
that decomposition:

- a mesh axis that moves BETWEEN tensor dims (``P(None, "model")`` →
  ``P("model", None)``) is one ``all_to_all`` — each device keeps
  ``1/n`` of its shard and exchanges the rest, never materializing the
  full array (the paper's headline saving over gather-then-slice);
- an axis only in the SOURCE spec is an ``all_gather`` along its dim;
- an axis only in the DESTINATION spec is a local ``dynamic_slice`` at
  the device's axis index (zero bytes on the wire).

Steps run in that order (all-to-alls first keep peak memory at the
shard size for the transpose-resharding case); values are moved, never
recomputed, so a round trip is bit-exact. Every call is measured:
per-transition bytes-on-the-wire and wall seconds land in the metrics
registry (``veles_reshard_bytes_total`` / ``veles_reshard_seconds`` —
docs/sharded_serving.md) and ``bench.py``'s ``reshard`` section records
the train→serve / serve→train transitions against the naive
``device_put`` formulation.
"""

import threading
import time

import numpy

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from veles_tpu.parallel.mesh import shard_map

#: reshard-latency histogram buckets (seconds): intra-host CPU test
#: meshes through cross-pod transitions of multi-GiB param trees
RESHARD_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _axis_dims(spec, ndim):
    """{mesh axis name: tensor dim} of a PartitionSpec (tuple entries —
    several axes sharding one dim — map each axis to that dim)."""
    out = {}
    for dim, entry in enumerate(tuple(spec)[:ndim]):
        if entry is None:
            continue
        for name in (entry if isinstance(entry, tuple) else (entry,)):
            out[name] = dim
    return out


def _normalize_spec(spec):
    """Canonical PartitionSpec: unsharded entries become None and
    trailing Nones are stripped, so specs that SPELL the same layout
    differently (``P("model")`` vs ``P("model", None)``, ``P()`` vs
    ``P(None)``, a 1-tuple axis entry vs the bare name) compare equal —
    the keep/schedule decision below must see layouts, not spellings
    (jax reports live arrays' specs in any of these forms)."""
    if spec is None:
        return PartitionSpec()
    if isinstance(spec, NamedSharding):
        spec = spec.spec
    entries = []
    for entry in tuple(spec):
        if isinstance(entry, tuple):
            entry = entry[0] if len(entry) == 1 else (entry or None)
        entries.append(entry)
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def _divisible(shape, spec, sizes):
    for dim, entry in enumerate(tuple(spec)[:len(shape)]):
        if entry is None:
            continue
        total = 1
        for name in (entry if isinstance(entry, tuple) else (entry,)):
            total *= sizes[name]
        if shape[dim] % total:
            return False
    return True


def _dim_entries(spec, ndim):
    """Per-dim tuple of sharding axes (major → minor), length ndim."""
    out = []
    entries = tuple(spec)[:ndim]
    for dim in range(ndim):
        entry = entries[dim] if dim < len(entries) else None
        if entry is None:
            out.append(())
        elif isinstance(entry, tuple):
            out.append(tuple(entry))
        else:
            out.append((entry,))
    return out


class LeafPlan:
    """The collective schedule for ONE array's spec change.

    ``steps`` is a list of ``(kind, axis, src_dim, dst_dim)`` with kind
    in ``all_to_all`` / ``all_gather`` / ``slice`` / ``keep``.
    An axis moving between dims rides ONE all_to_all (the paper's
    memory-bounded transpose resharding) when the move is CLEAN — the
    axis is alone on both its source and destination dim, and the
    destination dim is unsharded in the source layout; any other
    transition lowers to the always-correct gather-then-slice form
    (gathers per dim minor-axis-first, slices major-axis-first, so
    nested tuple shardings reassemble in index order). ``bytes`` is the
    total crossing the interconnect, summed over devices (all-to-all:
    ``(n-1)/n`` of each device's shard; all-gather: ``n-1`` shards
    received per device; slice/keep: zero)."""

    __slots__ = ("shape", "dtype", "src", "dst", "steps", "bytes")

    def __init__(self, shape, dtype, src, dst, sizes, n_devices):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.src = src
        self.dst = dst
        self.steps = []
        self.bytes = 0
        nbytes = int(numpy.prod(shape, dtype=numpy.int64)
                     * numpy.dtype(dtype).itemsize) if shape else \
            numpy.dtype(dtype).itemsize
        if src == dst:
            self.steps.append(("keep", None, None, None))
            return
        for name, spec in (("source", src), ("destination", dst)):
            if not _divisible(shape, spec, sizes):
                raise ValueError(
                    "reshard: shape %s cannot shard as %s spec %s — "
                    "every sharded dim must divide by its mesh axis "
                    "size(s) %s" % (list(shape), name, spec,
                                    dict(sizes)))
        ndim = len(shape)
        s_dims = _dim_entries(src, ndim)
        d_dims = _dim_entries(dst, ndim)
        s = _axis_dims(src, ndim)
        d = _axis_dims(dst, ndim)
        live = {ax: sizes[ax] for ax in s}  # axes currently sharding

        def local_bytes():
            return nbytes // int(numpy.prod(
                list(live.values()) or [1], dtype=numpy.int64))

        # 1) clean single-axis moves: one all_to_all each. "Clean" =
        #    the axis is alone on its src and dst dims and the dst dim
        #    carries no src sharding, so the tiled split/concat IS the
        #    layout change. Each device exchanges (n-1)/n of its shard
        #    inside its axis group.
        a2a = []
        for ax in sorted(set(s) & set(d)):
            if s[ax] == d[ax]:
                continue
            if (s_dims[s[ax]] == (ax,) and d_dims[d[ax]] == (ax,)
                    and not s_dims[d[ax]]):
                n = sizes[ax]
                self.bytes += n_devices * local_bytes() * (n - 1) // n
                self.steps.append(("all_to_all", ax, s[ax], d[ax]))
                a2a.append(ax)
        # 2) everything else lowers to gather + slice, scheduled
        #    per-dim so nested tuple shardings reassemble in global
        #    index order: gathers must peel a dim's MINOR suffix
        #    (tiled all_gather concatenates group order along the
        #    dim), slices must add a MINOR suffix under the staying
        #    prefix. A dim whose change is not suffix-shaped (axis
        #    swaps inside a tuple, a major axis leaving under a
        #    staying minor one) escalates: the whole dim gathers to
        #    full and reslices — always correct, the paper's portable
        #    lower bound when no cheaper schedule applies.
        gathers, slices = [], []
        for dim in range(ndim):
            leaving = tuple(ax for ax in s_dims[dim]
                            if ax not in a2a
                            and (ax not in d or d[ax] != dim))
            arriving = tuple(ax for ax in d_dims[dim]
                             if ax not in a2a
                             and (ax not in s or s[ax] != dim))
            if not leaving and not arriving:
                continue
            src_stay = tuple(ax for ax in s_dims[dim]
                             if ax not in leaving and ax not in a2a)
            dst_stay = tuple(ax for ax in d_dims[dim]
                             if ax not in arriving and ax not in a2a)
            suffix_ok = (
                src_stay == dst_stay
                and s_dims[dim][:len(src_stay)] == src_stay
                and d_dims[dim][:len(dst_stay)] == dst_stay)
            if suffix_ok:
                gathers.append((dim, leaving))
                slices.append((dim, arriving))
            else:
                gathers.append((dim, tuple(
                    ax for ax in s_dims[dim] if ax not in a2a)))
                slices.append((dim, tuple(
                    ax for ax in d_dims[dim] if ax not in a2a)))
        for dim, leaving in gathers:
            # minor-axis-first: each gather concatenates its groups
            # back into global index order under the remaining prefix
            for ax in reversed(leaving):
                n = sizes[ax]
                self.bytes += n_devices * local_bytes() * (n - 1)
                self.steps.append(("all_gather", ax, dim, None))
                del live[ax]
        for dim, arriving in slices:
            # major-axis-first: sequential slices nest correctly
            for ax in arriving:
                self.steps.append(("slice", ax, None, dim))
        if not self.steps:
            # src != dst as objects but no axis moved — the layouts
            # were equal under a spelling _normalize_spec didn't fold;
            # an empty schedule IS a keep, never an indexing crash
            self.steps.append(("keep", None, None, None))

    def describe(self):
        return {"shape": list(self.shape),
                "dtype": str(numpy.dtype(self.dtype)),
                "src": str(self.src), "dst": str(self.dst),
                "bytes": self.bytes,
                "steps": [{"op": op, "axis": ax,
                           "src_dim": sd, "dst_dim": dd}
                          for op, ax, sd, dd in self.steps]}


class ReshardPlan:
    """The whole tree's transition: per-leaf :class:`LeafPlan` list in
    flatten order, total wire bytes, and the step-kind tally the tests
    pin (a transpose resharding must plan all-to-all, never
    gather+slice)."""

    def __init__(self, leaves):
        self.leaves = leaves
        self.bytes = sum(leaf.bytes for leaf in leaves)

    def counts(self):
        out = {}
        for leaf in self.leaves:
            for op, *_ in leaf.steps:
                out[op] = out.get(op, 0) + 1
        return out

    def describe(self):
        return {"bytes": self.bytes, "counts": self.counts(),
                "leaves": [leaf.describe() for leaf in self.leaves]}


def _build_plan(leaves, src_list, dst_list, mesh):
    sizes = dict(mesh.shape)
    return ReshardPlan([
        LeafPlan(leaf.shape, leaf.dtype, src, dst, sizes, mesh.size)
        for leaf, src, dst in zip(leaves, src_list, dst_list)])


def plan_reshard(tree, mesh, dst_specs, src_specs):
    """Build the :class:`ReshardPlan` for moving ``tree`` from
    ``src_specs`` to ``dst_specs`` over ``mesh`` (specs: a matching
    pytree of ``PartitionSpec``, or one spec broadcast to every leaf)."""
    leaves, treedef = jax.tree.flatten(tree)
    src_list = _spec_list(src_specs, leaves, treedef)
    dst_list = _spec_list(dst_specs, leaves, treedef)
    return _build_plan(leaves, src_list, dst_list, mesh)


def _spec_list(specs, leaves, treedef):
    if isinstance(specs, (PartitionSpec, NamedSharding)) or specs is None:
        return [_normalize_spec(specs)] * len(leaves)
    flat = treedef.flatten_up_to(specs)
    return [_normalize_spec(spec) for spec in flat]


def _leaf_body(plan, sizes):
    """shard_map-local function applying one leaf's schedule."""
    def body(x):
        for kind, ax, src_dim, dst_dim in plan.steps:
            if kind == "all_to_all":
                x = lax.all_to_all(x, ax, split_axis=dst_dim,
                                   concat_axis=src_dim, tiled=True)
            elif kind == "all_gather":
                x = lax.all_gather(x, ax, axis=src_dim, tiled=True)
            elif kind == "slice":
                chunk = x.shape[dst_dim] // sizes[ax]
                x = lax.dynamic_slice_in_dim(
                    x, lax.axis_index(ax) * chunk, chunk, axis=dst_dim)
        return x
    return body


#: (mesh, structure/shape/spec signature) -> compiled transition. ONE
#: program per distinct transition, so repeated train↔serve flips hit
#: the jit cache (and the instrument() compile counters see one
#: compile, not one per call). _PLAN_CACHE shares the key (sans
#: schedule subset): the pure-Python schedule is fully determined by
#: it, so repeated flips skip the O(leaves × ndim) planning too.
_FN_CACHE = {}
_PLAN_CACHE = {}
_FN_LOCK = threading.Lock()


def _cache_key(mesh, treedef, leaves, src_list, dst_list):
    return (mesh, treedef,
            tuple((leaf.shape, str(leaf.dtype)) for leaf in leaves),
            tuple(str(s) for s in src_list),
            tuple(str(d) for d in dst_list))


def reshard(tree, mesh, dst_specs, src_specs=None, label="reshard",
            registry=None):
    """Move ``tree`` from its current sharding to ``dst_specs`` via the
    collective schedule; returns ``(new_tree, stats)``.

    ``dst_specs`` / ``src_specs``: a pytree of ``PartitionSpec``
    matching ``tree``, or one spec broadcast to every leaf.
    ``src_specs=None`` reads each leaf's current ``NamedSharding`` spec
    (leaves not already sharded over ``mesh`` — fresh host arrays,
    single-device results — are treated as replicated and placed first).
    ``stats``: ``{"bytes", "seconds", "counts"}``; the same numbers
    land on the metrics registry as ``veles_reshard_bytes_total`` /
    ``veles_reshard_seconds`` labeled by ``label`` (the train→serve /
    serve→train transitions each carry their own label on /metrics).

    Bit-exactness: every step is a data movement (exchange, gather,
    slice) — no arithmetic — so ``reshard(reshard(x, serve), train)``
    returns ``x``'s values exactly, which ``tests/test_reshard.py``
    asserts for arbitrary spec pairs.
    """
    leaves, treedef = jax.tree.flatten(tree)
    dst_list = _spec_list(dst_specs, leaves, treedef)
    if src_specs is None:
        src_list = []
        for leaf in leaves:
            sharding = getattr(leaf, "sharding", None)
            if isinstance(sharding, NamedSharding) \
                    and sharding.mesh == mesh:
                src_list.append(_normalize_spec(sharding.spec))
            else:
                src_list.append(PartitionSpec())
    else:
        src_list = _spec_list(src_specs, leaves, treedef)
    plan_key = _cache_key(mesh, treedef, leaves, src_list, dst_list)
    with _FN_LOCK:
        plan = _PLAN_CACHE.get(plan_key)
    if plan is None:
        plan = _build_plan(leaves, src_list, dst_list, mesh)
        with _FN_LOCK:
            _PLAN_CACHE[plan_key] = plan

    sizes = dict(mesh.shape)
    # keep-leaves stay OUT of the compiled program: one already placed
    # in its dst layout passes through untouched; one not yet on the
    # mesh (host array, single-device result) is a plain placement.
    # Only leaves whose layout actually changes ride the shard_map —
    # smaller programs, no identity arguments.
    sched_idx, place_idx = [], []
    for i, leaf_plan in enumerate(plan.leaves):
        if leaf_plan.steps[0][0] != "keep":
            sched_idx.append(i)
            continue
        sharding = getattr(leaves[i], "sharding", None)
        if not (isinstance(sharding, NamedSharding)
                and sharding.mesh == mesh):
            place_idx.append(i)

    t0 = time.perf_counter()
    out_leaves = list(leaves)
    if sched_idx:
        # the schedule SET rides the key: the same (specs, shapes) tree
        # can arrive with different keep subsets placed vs scheduled
        key = plan_key + (tuple(sched_idx),)
        with _FN_LOCK:
            fn = _FN_CACHE.get(key)
        if fn is None:
            bodies = [_leaf_body(plan.leaves[i], sizes)
                      for i in sched_idx]

            def run(*args):
                return tuple(body(arg)
                             for body, arg in zip(bodies, args))

            fn = jax.jit(shard_map(
                run, mesh=mesh,
                in_specs=tuple(src_list[i] for i in sched_idx),
                out_specs=tuple(dst_list[i] for i in sched_idx)))
            with _FN_LOCK:
                _FN_CACHE[key] = fn
        # leaves not yet living on the mesh (host arrays, single-device
        # results) are placed into the src layout first — the schedule
        # itself then never leaves the interconnect
        args = []
        for i in sched_idx:
            leaf = leaves[i]
            sharding = getattr(leaf, "sharding", None)
            if not (isinstance(sharding, NamedSharding)
                    and sharding.mesh == mesh):
                leaf = jax.device_put(
                    jnp.asarray(leaf), NamedSharding(mesh, src_list[i]))
            args.append(leaf)
        moved = fn(*args)
        for i, arr in zip(sched_idx, moved):
            out_leaves[i] = arr
    for i in place_idx:
        out_leaves[i] = jax.device_put(
            jnp.asarray(leaves[i]), NamedSharding(mesh, dst_list[i]))
    out = jax.tree.unflatten(treedef, out_leaves)
    jax.block_until_ready(out)
    seconds = time.perf_counter() - t0

    stats = {"bytes": plan.bytes, "seconds": seconds,
             "counts": plan.counts()}
    if registry is None:
        from veles_tpu.observe.metrics import get_metrics_registry
        registry = get_metrics_registry()
    registry.incr("veles_reshard_bytes_total", plan.bytes,
                  labels={"transition": label},
                  help="interconnect bytes moved by reshard() schedules")
    registry.observe("veles_reshard_seconds", seconds,
                     labels={"transition": label},
                     buckets=RESHARD_BUCKETS,
                     help="wall seconds per reshard() transition")
    return out, stats


def naive_reshard(tree, mesh, dst_specs):
    """The baseline ``device_put`` formulation (what :func:`reshard`
    replaces) — kept callable so the bench can measure the schedule
    against it honestly on the same tree/mesh/specs."""
    leaves, treedef = jax.tree.flatten(tree)
    dst_list = _spec_list(dst_specs, leaves, treedef)
    t0 = time.perf_counter()
    out = jax.tree.unflatten(treedef, [
        jax.device_put(leaf, NamedSharding(mesh, spec))
        for leaf, spec in zip(leaves, dst_list)])
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0
