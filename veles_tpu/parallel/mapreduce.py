"""Compiler-visible fleet aggregation: mapreduce primitives in XLA.

The reference VELES merged data-parallel updates on the HOST: every
gradient rode an asyncio frame to the master and was applied under a
lock (``fleet/server.py``), so the chip idled through every reduce.
This module re-expresses that aggregation as *in-program* mapreduce
primitives per DrJAX (*Scalable and Differentiable MapReduce Primitives
in JAX*, PAPERS.md, arxiv 2403.07128): ``broadcast`` / ``map_fn`` /
``reduce_sum`` / ``reduce_mean`` over the named ``"data"`` mesh axis
under ``parallel/mesh.shard_map``, so the whole data-parallel train
step — forward, backward, gradient merge, update — is ONE compiled XLA
program with the reduce riding ICI collectives. Zero host round trips
per step; the fleet wire protocol shrinks to a control plane
(``docs/compiler_fleet.md``).

Reduce precision tiers (``root.common.fleet.reduce``):

- ``f32`` (default) — a plain ``lax.psum``; bit-identical to the
  pre-existing pod-mode gradient merge;
- ``bf16`` — gradients cast to bfloat16 for the wire, summed by the
  collective, widened back: half the bytes of f32;
- ``int8`` — two-stage quantized all-reduce with **per-leaf scales**
  (the ROADMAP item 3 follow-on): a global per-leaf scale (``pmax`` of
  the local amax) quantizes the gradient to int8, an ``all_to_all``
  exchanges chunk shards (each device exactly-sums its chunk in int32),
  and a second global-scale int8 ``all_gather`` replicates the reduced
  tensor — ~4x fewer wire bytes than f32, ~2x fewer than bf16, fully
  deterministic (every device runs the same program on the same bytes,
  so replicas stay in lockstep). Convergence differs from the exact sum
  by two bounded rounding stages; ``tests/test_mapreduce.py`` pins the
  error bound and the loss-curve parity vs the bf16 tier.

Byte accounting follows ``parallel/reshard.py``'s convention (total
bytes on the wire across ALL devices): a ring all-reduce of an
``E``-element tensor moves ``2*(n-1)*E*itemsize`` bytes; the int8 tier
moves ``(n-1)*E`` (all_to_all) + ``(n-1)*E`` (all_gather) int8 bytes
plus two scalar ``pmax`` rounds per leaf.

Observability: :func:`fleet_train_step` instruments the compiled steps
under ``observe/xla_stats`` (program ``mapreduce.fleet_*``) so
``veles_mfu_ratio`` during distributed training is a device-truth
number, and books per-step wire bytes / step cadence into
:class:`ReduceStats` — published on every ``/metrics`` mount as
``veles_fleet_reduce_bytes_total`` / ``veles_fleet_reduce_seconds`` /
``veles_fleet_chip_idle_fraction`` via the ``xla_stats`` collector.
"""

import threading
import time

import numpy

import jax
import jax.numpy as jnp
from jax import lax

from veles_tpu.parallel.mesh import axis_size, shard_map

#: valid in-program gradient-reduce precisions
REDUCE_PRECISIONS = ("f32", "bf16", "int8")

#: int8 quantization range (symmetric)
_Q_MAX = 127.0

#: a gap this long between steps re-arms the idle-fraction window (a
#: training lull must not be booked as chip idleness — same doctrine as
#: the MFU cadence reset in observe/xla_stats)
CADENCE_RESET = 60.0


def reduce_precision_of(value=None):
    """Validate/resolve the configured reduce tier
    (``root.common.fleet.reduce``); raises naming the knob."""
    if value is None:
        from veles_tpu.core.config import root
        value = root.common.fleet.get("reduce", "f32")
    if value not in REDUCE_PRECISIONS:
        raise ValueError(
            "root.common.fleet.reduce / --fleet-reduce must be one of "
            "%s, got %r" % ("/".join(REDUCE_PRECISIONS), value))
    return value


# -- primitives ---------------------------------------------------------------

def broadcast(tree):
    """DrJAX ``broadcast``: place a server (host) value on every client
    shard. Under the SPMD formulation replication is expressed by the
    ``P()`` in_spec at the :func:`map_fn` boundary, so inside the
    program this is the identity — kept as an explicit primitive so
    fleet step code reads as mapreduce, not as sharding trivia."""
    return tree


def map_fn(fn, mesh, in_specs, out_specs):
    """DrJAX ``map_fn``: run ``fn`` per shard of the ``"data"`` axis.
    A thin delegate to :func:`parallel.mesh.shard_map` (one shard_map
    implementation for the whole tree)."""
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)


def _int8_allreduce_leaf(x, axis):
    """Two-stage quantized all-reduce of one full-size leaf (see module
    docstring). Exact int32 accumulation between the two rounding
    stages; both scales are global (``pmax``), so every device computes
    identical bytes and the result is replicated by construction."""
    n = axis_size(axis)
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    size = flat.size
    pad = (-size) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    # stage 1: global per-leaf scale, int8 quantize, chunk exchange
    amax = lax.pmax(jnp.max(jnp.abs(flat)), axis)
    scale = jnp.maximum(amax, jnp.float32(1e-30)) / _Q_MAX
    quant = jnp.clip(jnp.round(flat / scale), -_Q_MAX, _Q_MAX) \
        .astype(jnp.int8)
    chunks = quant.reshape(n, -1)
    # device i ends with every peer's chunk i: (n, chunk) int8
    peers = lax.all_to_all(chunks, axis, split_axis=0, concat_axis=0,
                           tiled=False)
    # exact integer accumulation (int8 sums over n would overflow)
    reduced = peers.astype(jnp.int32).sum(axis=0).astype(jnp.float32) \
        * scale
    # stage 2: re-quantize the reduced chunk with a fresh global scale
    # and replicate it — (n-1)/n int8 bytes instead of f32's 4x
    amax2 = lax.pmax(jnp.max(jnp.abs(reduced)), axis)
    scale2 = jnp.maximum(amax2, jnp.float32(1e-30)) / _Q_MAX
    quant2 = jnp.clip(jnp.round(reduced / scale2), -_Q_MAX, _Q_MAX) \
        .astype(jnp.int8)
    gathered = lax.all_gather(quant2, axis, axis=0, tiled=True)
    out = gathered.astype(jnp.float32) * scale2
    if pad:
        out = out[:size]
    return out.reshape(orig_shape).astype(orig_dtype)


def _is_float(x):
    return jnp.issubdtype(getattr(x, "dtype", jnp.float32),
                          jnp.floating)


def reduce_sum(tree, axis="data", precision="f32"):
    """In-program all-reduce-sum of ``tree`` over the named mesh
    ``axis``. ``precision`` selects the wire tier (module docstring);
    ``f32`` IS ``lax.psum`` — bit-identical to the pre-existing pod
    gradient merge. Non-float leaves (error counts, confusion
    increments) always take the exact psum regardless of tier."""
    if precision not in REDUCE_PRECISIONS:
        raise ValueError("reduce precision must be one of %s, got %r"
                         % ("/".join(REDUCE_PRECISIONS), precision))
    if precision == "f32":
        return lax.psum(tree, axis)

    def leaf(x):
        if not _is_float(x):
            return lax.psum(x, axis)
        if precision == "bf16":
            return lax.psum(x.astype(jnp.bfloat16), axis) \
                .astype(x.dtype)
        return _int8_allreduce_leaf(x, axis)

    return jax.tree.map(leaf, tree)


def reduce_mean(tree, axis="data", precision="f32"):
    """In-program all-reduce-mean over ``axis`` (sum / static axis
    size)."""
    summed = reduce_sum(tree, axis=axis, precision=precision)
    n = None

    def leaf(x):
        nonlocal n
        if n is None:
            n = axis_size(axis)
        return x / n if _is_float(x) else x // n

    return jax.tree.map(leaf, summed)


# -- wire-byte accounting -----------------------------------------------------

def reduce_wire_bytes(tree, n_devices, precision="f32"):
    """Analytic bytes-on-the-wire (total across all devices, the
    reshard.py convention) of one :func:`reduce_sum` of ``tree`` over
    ``n_devices`` shards. Zero when nothing crosses the wire (n=1)."""
    n = int(n_devices)
    if n <= 1:
        return 0
    total = 0
    for leaf in jax.tree.leaves(tree):
        size = 1
        for dim in getattr(leaf, "shape", ()):
            size *= int(dim)
        dtype = numpy.dtype(getattr(leaf, "dtype", numpy.float32))
        itemsize = dtype.itemsize
        is_float = numpy.issubdtype(dtype, numpy.floating)
        if precision == "f32" or not is_float:
            total += 2 * (n - 1) * size * itemsize
        elif precision == "bf16":
            total += 2 * (n - 1) * size * 2
        else:  # int8: a2a + all_gather int8 payloads + 2 scalar pmaxes
            padded = size + ((-size) % n)
            total += 2 * (n - 1) * padded + 2 * 2 * (n - 1) * 4
    return total


# -- runtime stats (the /metrics plane) ---------------------------------------

class ReduceStats:
    """Per-precision in-program-reduce bookkeeping: steps, wire bytes,
    and the host-cadence idle fraction — the share of fleet-training
    wall time the driver spends OUTSIDE the compiled step (frames,
    protocol, bookkeeping). Host-aggregated training idles ~everything;
    the in-program path pushes this toward zero (the observable the
    compiler-visible refit exists to move). Thread-safe; fed by the
    :func:`fleet_train_step` wrappers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tiers = {}          # precision -> {"steps", "bytes"}
        self._busy = 0.0          # seconds inside the compiled step
        self._span_start = None   # cadence window start (monotonic)
        self._last_end = None

    def note(self, precision, wire_bytes=0, busy=0.0, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            tier = self._tiers.setdefault(precision,
                                          {"steps": 0, "bytes": 0})
            tier["steps"] += 1
            tier["bytes"] += int(wire_bytes)
            if self._last_end is None \
                    or now - self._last_end > CADENCE_RESET:
                # a lull re-arms the window: idle between runs is not
                # protocol overhead
                self._span_start = now - busy
                self._busy = 0.0
            self._busy += float(busy)
            self._last_end = now

    def idle_fraction(self):
        with self._lock:
            if self._span_start is None or self._last_end is None:
                return None
            span = self._last_end - self._span_start
            if span <= 0 or self._busy <= 0:
                return None
            return min(max(1.0 - self._busy / span, 0.0), 1.0)

    def snapshot(self):
        with self._lock:
            return {precision: dict(entry)
                    for precision, entry in self._tiers.items()}

    def reset(self):
        with self._lock:
            self._tiers.clear()
            self._busy = 0.0
            self._span_start = None
            self._last_end = None


_stats = ReduceStats()


def get_reduce_stats():
    return _stats


def publish_reduce_stats(registry):
    """Scrape-time re-publication (the bridge contract) — wired into
    ``observe/xla_stats.publish_xla_stats`` so every ``/metrics`` mount
    (serving, web-status, the fleet master sidecar) and every fleet
    slave's piggybacked snapshot carries the reduce plane."""
    snap = _stats.snapshot()
    for precision, entry in snap.items():
        registry.counter_set(
            "veles_fleet_reduce_steps_total", entry["steps"],
            labels={"precision": precision},
            help="in-program data-parallel reduce steps executed")
        registry.counter_set(
            "veles_fleet_reduce_bytes_total", entry["bytes"],
            labels={"precision": precision},
            help="analytic collective wire bytes moved by in-program "
                 "gradient reduces (reshard.py convention: total "
                 "across devices)")
    idle = _stats.idle_fraction()
    if idle is not None:
        registry.set(
            "veles_fleet_chip_idle_fraction", round(idle, 4),
            help="share of fleet-training wall time spent outside the "
                 "compiled step (host protocol/frames) — the quantity "
                 "in-program aggregation exists to minimize")


# -- the fleet train step -----------------------------------------------------

#: id(build_tick steps) + precision -> wrapped step tuple
_WRAP_CACHE = {}


def _grad_bytes(params, n, precision):
    """Wire bytes of one train-step gradient reduce: the grad tree
    mirrors the per-layer ``"p"`` leaves."""
    grads = [entry.get("p", {}) for entry in params
             if isinstance(entry, dict)]
    return reduce_wire_bytes(grads, n, precision)


def _wrap_step(name, fn, precision, bytes_of, sync_for_stats=False):
    """Instrument one compiled step: compiles/FLOPs via
    ``xla_stats.instrument``, per-call wire bytes + busy/cadence into
    :class:`ReduceStats`, cadence into the MFU tracker and the
    ``veles_fleet_reduce_seconds`` histogram. Disabled-tracker calls
    pay one attribute check (the observability fast-path contract).

    ``sync_for_stats``: block on the step's METRIC outputs before
    stamping the busy window — jax dispatch is asynchronous, so the
    raw call wall is microseconds of enqueueing and would book a fully
    chip-bound run as ~100% idle. Enabled for the per-minibatch step
    programs (the fleet-slave path, where the metric scalars get
    host-read microseconds later anyway — the Decision payload — so
    the sync costs ~nothing); the SWEEP programs stay unsynced (the
    pipelined standalone engine hides that sync by design; they book
    steps/bytes only, never busy, so they cannot skew the gauge)."""
    from veles_tpu.observe.xla_stats import (get_compile_tracker,
                                             instrument)

    inst = instrument(name, fn)
    tracker = get_compile_tracker()
    state = {"last": None}

    def call(*args, **kwargs):
        if not tracker.enabled:
            return inst(*args, **kwargs)
        t0 = time.perf_counter()
        out = inst(*args, **kwargs)
        busy = 0.0
        if sync_for_stats:
            # metrics only — the params leaf stays in flight
            jax.block_until_ready(out[1] if isinstance(out, tuple)
                                  and len(out) == 2 else out)
            busy = time.perf_counter() - t0
        t1 = time.perf_counter()
        last = state["last"]
        state["last"] = t1
        _stats.note(precision, wire_bytes=bytes_of(args), busy=busy)
        if last is not None and t1 - last <= CADENCE_RESET:
            # cadence (time per step incl. host gaps) is the honest
            # step denominator for distributed MFU — the PR 5 serving
            # doctrine (collect_chunk cadence) applied to training
            cadence = t1 - last
            tracker.observe_step(name, cadence)
            from veles_tpu.observe.metrics import get_metrics_registry
            get_metrics_registry().observe(
                "veles_fleet_reduce_seconds", cadence,
                labels={"program": name, "precision": precision},
                help="wall seconds per in-program-reduced fleet step "
                     "(the reduce is fused into the step program)")
        return out

    call.program_name = name
    call.__wrapped__ = fn
    return call


def fleet_train_step(mesh, specs, norm_type="none", with_confusion=True,
                     augment="none", loss_kind="softmax",
                     reduce_precision=None):
    """The in-program data-parallel fleet step (ROADMAP item 3): the
    existing fused train step (``parallel/fused.py``) run per-shard of
    ``mesh``'s ``"data"`` axis with gradients merged by an in-program
    :func:`reduce_sum` at ``reduce_precision`` (default: the configured
    ``root.common.fleet.reduce`` tier) — ONE compiled program, zero
    host round trips per step, instrumented under ``observe/xla_stats``
    (programs ``mapreduce.fleet_{train,eval}_{step,sweep}``).

    Returns the same ``(train_step, eval_step, train_sweep,
    eval_sweep)`` tuple as ``fused.build_tick``; ``f32`` results are
    bit-identical to the raw ``build_tick(mesh=...)`` programs (the
    tick itself routes its psums through :func:`reduce_sum`)."""
    from veles_tpu.parallel import fused

    precision = reduce_precision_of(reduce_precision)
    steps = fused.build_tick(specs, norm_type, mesh=mesh,
                             with_confusion=with_confusion,
                             augment=augment, loss_kind=loss_kind,
                             grad_reduce=precision)
    key = (id(steps), precision)
    cached = _WRAP_CACHE.get(key)
    if cached is not None:
        return cached
    n = int(mesh.shape.get("data", 1)) if mesh is not None else 1
    train_step, eval_step, train_sweep, eval_sweep = steps

    def train_bytes(args):
        return _grad_bytes(args[0], n, precision)

    def sweep_bytes(args):
        rows = int(getattr(args[5], "shape", (1,))[0])
        return rows * _grad_bytes(args[0], n, precision)

    # eval reduces scalars (+ the confusion increment) — book the
    # scalar pair; the tier never compresses ints anyway
    scalar_wire = reduce_wire_bytes(
        (numpy.zeros((), numpy.float32), numpy.zeros((), numpy.int32)),
        n, "f32")

    def metric_bytes(args):
        return scalar_wire

    wrapped = (
        _wrap_step("mapreduce.fleet_train_step", train_step, precision,
                   train_bytes, sync_for_stats=True),
        _wrap_step("mapreduce.fleet_eval_step", eval_step, precision,
                   metric_bytes, sync_for_stats=True),
        _wrap_step("mapreduce.fleet_train_sweep", train_sweep,
                   precision, sweep_bytes),
        _wrap_step("mapreduce.fleet_eval_sweep", eval_sweep, precision,
                   metric_bytes),
    )
    _WRAP_CACHE[key] = wrapped
    return wrapped
