"""Pipeline parallelism over the ``pipe`` mesh axis.

Additive beyond the reference (which had no model sharding of any kind,
SURVEY §2.5): a GPipe-style microbatch pipeline expressed the TPU way —
one ``shard_map`` over the ``pipe`` axis in ONE jitted computation, with
``lax.ppermute`` moving activations between neighbouring stages and a
``lax.fori_loop`` running the classic ``n_micro + n_stages - 1`` fill +
drain schedule. Stage weights live only on their stage's devices.

The stage function is uniform (same shapes per stage — the standard
pipelined-transformer setup); stage identity selects the local weight
shard automatically because each device only holds its own stage's
parameters.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def make_pipeline(mesh, stage_fn, n_microbatches):
    """Compile a pipelined forward.

    ``stage_fn(w, x) -> y`` is one stage's computation with ``x``/``y``
    of identical shape (microbatch, ...). Returns
    ``pipeline(stage_weights, batch)`` where ``stage_weights`` has a
    leading stage axis sharded over ``pipe`` and ``batch`` splits into
    ``n_microbatches`` along axis 0.

    Wall-clock per batch is ``(n_micro + n_stages - 1)`` stage steps
    instead of ``n_micro * n_stages`` — the pipeline overlap.
    """
    n_stages = mesh.shape["pipe"]

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P("pipe"), P()), out_specs=P(),
             check_vma=False)
    def _pipeline(w_local, batch):
        stage = lax.axis_index("pipe")
        w = jax.tree.map(lambda a: a[0], w_local)  # this stage's weights
        micro = batch.reshape((n_microbatches, -1) + batch.shape[1:])
        n_steps = n_microbatches + n_stages - 1
        zero = jnp.zeros_like(micro[0])
        outputs = jnp.zeros_like(micro)

        def step(t, carry):
            incoming, outputs = carry
            # stage 0 feeds itself from the microbatch queue; others use
            # the activation handed over by the previous stage
            feed = lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, n_microbatches - 1), 0,
                keepdims=False)
            x = jnp.where(stage == 0, feed, incoming)
            y = stage_fn(w, x)
            # the LAST stage writes its finished microbatch (index t -
            # (n_stages-1)); earlier stages pass y to the next stage
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
            write = jnp.logical_and(stage == n_stages - 1,
                                    t >= n_stages - 1)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, out_idx, 0),
                lambda o: o, outputs)
            nxt = lax.ppermute(
                y, "pipe",
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return nxt, outputs

        _, outputs = lax.fori_loop(0, n_steps, step, (zero, outputs))
        # only the last stage holds real outputs; psum of the masked
        # buffers broadcasts them to every stage in one collective
        outputs = lax.psum(
            jnp.where(stage == n_stages - 1, outputs,
                      jnp.zeros_like(outputs)), "pipe")
        return outputs.reshape(batch.shape[:1] + outputs.shape[2:])

    def pipeline(stage_weights, batch):
        # fail HERE with the real constraint names, not deep inside the
        # shard_map trace: (a) exactly one weight row per stage — a
        # multiple would shard cleanly but silently run every k-th
        # stage's weights; (b) the batch must split into microbatches
        for leaf in jax.tree.leaves(stage_weights):
            if leaf.shape[0] != n_stages:
                raise ValueError(
                    "stage weights leading dim %d != pipe axis %d"
                    % (leaf.shape[0], n_stages))
        if batch.shape[0] % n_microbatches:
            raise ValueError(
                "batch size %d does not divide into %d microbatches"
                % (batch.shape[0], n_microbatches))
        return _pipeline(stage_weights, batch)

    return pipeline


def shard_stage_weights(weights, mesh):
    """Place stage-major weight pytrees on the pipe axis."""
    spec = jax.sharding.NamedSharding(mesh, P("pipe"))
    return jax.tree.map(lambda a: jax.device_put(a, spec), weights)
