"""Pipeline parallelism over the ``pipe`` mesh axis.

Additive beyond the reference (which had no model sharding of any kind,
SURVEY §2.5): a GPipe-style microbatch pipeline expressed the TPU way —
one ``shard_map`` over the ``pipe`` axis in ONE jitted computation, with
``lax.ppermute`` moving activations between neighbouring stages and a
``lax.scan`` running the classic ``n_micro + n_stages - 1`` fill + drain
schedule. Stage weights live only on their stage's devices.

The schedule is a ``scan`` (not ``fori_loop``) so the WHOLE pipeline is
reverse-differentiable: ``jax.grad`` through it yields the backward
microbatch schedule automatically — the cotangent of each ``ppermute``
is the reverse ``ppermute``, so gradients flow stage N → stage 0 in the
mirrored fill/drain order, with ``jax.checkpoint`` on the stage function
bounding the stored residuals (GPipe's rematerialization). The train
step (:func:`make_pipeline_train_step`) builds on exactly this; an
optional ``data`` mesh axis composes pp x dp (batch rows sharded,
gradients psum-merged).

The stage function is uniform (same shapes per stage — the standard
pipelined-transformer setup); stage identity selects the local weight
shard automatically because each device only holds its own stage's
parameters.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from veles_tpu.parallel.mesh import shard_map


def _pipeline_body(stage_fn, n_stages, n_microbatches, remat):
    """The shared shard_map-local forward: returns the full pipelined
    output of this device's batch shard."""
    staged = jax.checkpoint(stage_fn) if remat else stage_fn

    def forward(w_local, batch):
        stage = lax.axis_index("pipe")
        w = jax.tree.map(lambda a: a[0], w_local)  # this stage's weights
        micro = batch.reshape((n_microbatches, -1) + batch.shape[1:])
        n_steps = n_microbatches + n_stages - 1
        zero = jnp.zeros_like(micro[0])

        def step(incoming, t):
            # stage 0 feeds itself from the microbatch queue; others use
            # the activation handed over by the previous stage
            feed = lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, n_microbatches - 1), 0,
                keepdims=False)
            x = jnp.where(stage == 0, feed, incoming)
            y = staged(w, x)
            nxt = lax.ppermute(
                y, "pipe",
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            # the LAST stage's y at t >= n_stages-1 is microbatch
            # t-(n_stages-1) finished; stack every step's y and slice
            # the drain window after the scan (cheaper than an in-loop
            # masked dynamic update, and scan stacks for free)
            return nxt, y

        _, ys = lax.scan(step, zero, jnp.arange(n_steps))
        outputs = ys[n_stages - 1:]  # (n_micro, mb, ...) on last stage
        # only the last stage holds real outputs; psum of the masked
        # buffers broadcasts them to every stage in one collective
        outputs = lax.psum(
            jnp.where(stage == n_stages - 1, outputs,
                      jnp.zeros_like(outputs)), "pipe")
        return outputs.reshape((batch.shape[0],) + outputs.shape[2:])

    return forward


def make_pipeline(mesh, stage_fn, n_microbatches, remat=False):
    """Compile a pipelined forward.

    ``stage_fn(w, x) -> y`` is one stage's computation with ``x``/``y``
    of identical shape (microbatch, ...). Returns
    ``pipeline(stage_weights, batch)`` where ``stage_weights`` has a
    leading stage axis sharded over ``pipe`` and ``batch`` splits into
    ``n_microbatches`` along axis 0.

    Wall-clock per batch is ``(n_micro + n_stages - 1)`` stage steps
    instead of ``n_micro * n_stages`` — the pipeline overlap.
    """
    n_stages = mesh.shape["pipe"]

    _pipeline = shard_map(
        _pipeline_body(stage_fn, n_stages, n_microbatches, remat),
        mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P())

    def pipeline(stage_weights, batch):
        _validate(stage_weights, batch, n_stages, n_microbatches)
        return _pipeline(stage_weights, batch)

    return pipeline


def _validate(stage_weights, batch, n_stages, n_microbatches, data_ax=1):
    """Fail HERE with the real constraint names, not deep inside the
    shard_map trace: (a) exactly one weight row per stage — a multiple
    would shard cleanly but silently run every k-th stage's weights;
    (b) the (per-data-shard) batch must split into microbatches."""
    for leaf in jax.tree.leaves(stage_weights):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                "stage weights leading dim %d != pipe axis %d"
                % (leaf.shape[0], n_stages))
    rows = batch.shape[0]
    if rows % data_ax:
        raise ValueError(
            "batch size %d does not shard over data axis %d"
            % (rows, data_ax))
    if (rows // data_ax) % n_microbatches:
        raise ValueError(
            "batch size %d (per data shard: %d) does not divide into "
            "%d microbatches" % (rows, rows // data_ax, n_microbatches))


def shard_stage_weights(weights, mesh):
    """Place stage-major weight pytrees on the pipe axis."""
    spec = jax.sharding.NamedSharding(mesh, P("pipe"))
    return jax.tree.map(lambda a: jax.device_put(a, spec), weights)


def make_pipeline_train_step(mesh, stage_fn, n_microbatches, loss_fn,
                             learning_rate=0.01, remat=True):
    """Compile a pipelined TRAIN step — forward fill/drain, backward
    microbatch schedule (the reverse ppermute chain ``jax.grad`` derives
    from the scanned forward, with per-stage rematerialization), SGD
    update — as ONE jitted computation.

    ``loss_fn(outputs, targets) -> scalar`` consumes the last stage's
    assembled batch outputs. With a ``data`` axis of size > 1 in the
    mesh, batch/targets rows are sharded over it and gradients are
    psum-merged — pp x dp composition.

    Returns ``step(stage_weights, batch, targets) -> (new_weights,
    loss)``.
    """
    n_stages = mesh.shape["pipe"]
    data_ax = mesh.shape.get("data", 1)
    forward = _pipeline_body(stage_fn, n_stages, n_microbatches, remat)

    def local_step(w_local, batch, targets):
        def local_loss(w_local):
            outputs = forward(w_local, batch)
            loss = loss_fn(outputs, targets)
            if data_ax > 1:
                loss = lax.pmean(loss, "data")
            return loss

        loss, grads = jax.value_and_grad(local_loss)(w_local)
        # the loss is REPLICATED over pipe by the masked-psum broadcast,
        # so under grad every pipe device seeds its own copy and the
        # psum transpose sums the n_stages seeds — normalize back
        grads = jax.tree.map(lambda g: g / n_stages, grads)
        if data_ax > 1:
            # each data-shard computed grads for ITS rows: merge
            # (pmean(loss, "data") above makes the data-axis seeds net
            # out to 1; only the row-shard averaging remains)
            grads = lax.pmean(grads, "data")
        new = jax.tree.map(lambda w, g: w - learning_rate * g,
                           w_local, grads)
        return new, loss

    batch_spec = P("data") if data_ax > 1 else P()
    step = jax.jit(shard_map(
        local_step, mesh=mesh,
        in_specs=(P("pipe"), batch_spec, batch_spec),
        out_specs=(P("pipe"), P())))

    def train_step(stage_weights, batch, targets):
        _validate(stage_weights, batch, n_stages, n_microbatches,
                  data_ax)
        return step(stage_weights, batch, targets)

    return train_step


def sequential_reference(stage_fn, stage_weights, batch):
    """Single-device reference of the same pipeline: apply the stages in
    order (parity oracle for the train-step tests)."""
    x = batch
    n_stages = jax.tree.leaves(stage_weights)[0].shape[0]
    for i in range(n_stages):
        w = jax.tree.map(lambda a: a[i], stage_weights)
        x = stage_fn(w, x)
    return x
