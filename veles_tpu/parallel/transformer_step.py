"""Sequence-parallel transformer training: one fused step under a
``data`` x ``seq`` mesh.

The long-context training integration: activations are sharded over BOTH
the batch (``data``) and the sequence (``seq``) axes; attention runs
sequence-parallel via either SP strategy — Ulysses all-to-all (default;
plain differentiable composition) or ring attention (``lax.scan``-based
online softmax, reverse-differentiable, HBM per device scales with T/n);
every other sublayer (layer norm, MLP, residuals, the per-token head) is
token-local, so only the attention pays collectives. Gradients ``psum``
over both axes.

No reference counterpart (VELES predates attention; SURVEY §5
"Long-context: absent") — this is the additive tier the build brief makes
first-class. The causal-LM toy model here (pre-LN blocks, GELU MLP,
per-token softmax head) is the standard shape scaling recipes assume.
"""

import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.ops.quant import matmul_any
from veles_tpu.parallel.mesh import shard_map
from veles_tpu.ops.attention import (attention, ring_attention,
                                     ulysses_attention)


def init_transformer_params(rng, n_blocks, embed, heads, vocab,
                            mlp_ratio=4):
    """Plain float32 pytree; ``rng`` is a numpy RandomState."""
    def mat(a, b):
        return jnp.asarray(rng.randn(a, b).astype("float32")
                           / math.sqrt(a))

    hidden = embed * mlp_ratio
    blocks = []
    for _ in range(n_blocks):
        blocks.append({
            "ln1_w": jnp.ones(embed), "ln1_b": jnp.zeros(embed),
            "wqkv": mat(embed, 3 * embed), "bqkv": jnp.zeros(3 * embed),
            "wout": mat(embed, embed), "bout": jnp.zeros(embed),
            "ln2_w": jnp.ones(embed), "ln2_b": jnp.zeros(embed),
            "w1": mat(embed, hidden), "b1": jnp.zeros(hidden),
            "w2": mat(hidden, embed), "b2": jnp.zeros(embed),
        })
    return {"blocks": blocks,
            "lnf_w": jnp.ones(embed), "lnf_b": jnp.zeros(embed),
            "head": mat(embed, vocab)}


def _ln(x, w, b, eps=1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * w + b


# The sublayer helpers are shared with the KV-cache decode path
# (parallel/decode.py) — ONE copy of the block math keeps the cached
# and full-recompute forwards numerically equivalent by construction.

def _block_qkv(blk, x, heads):
    """Pre-LN qkv projection: (B, T, E) -> three (B, T, H, D)."""
    batch, t, embed = x.shape
    h = _ln(x, blk["ln1_w"], blk["ln1_b"])
    qkv = matmul_any(h, blk["wqkv"]) + blk["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (batch, t, heads, embed // heads)
    return q.reshape(shape), k.reshape(shape), v.reshape(shape)


def _mlp(blk, x, reduce=None):
    """Pre-LN residual gelu MLP. ``reduce`` completes a sharded
    contraction (tensor-parallel decode passes a psum; ``b2`` is added
    AFTER it, so it stays replicated) — one copy of the math for the
    single-device and TP paths alike. The products route through
    ``matmul_any`` so the int8 serving tier (``ops/quant.py``) shares
    this exact sublayer math."""
    h = _ln(x, blk["ln2_w"], blk["ln2_b"])
    y = matmul_any(jax.nn.gelu(matmul_any(h, blk["w1"]) + blk["b1"]),
                   blk["w2"])
    if reduce is not None:
        y = reduce(y)
    return x + y + blk["b2"]


def _head(params, x):
    """Final layer norm + vocab projection."""
    return matmul_any(_ln(x, params["lnf_w"], params["lnf_b"]),
                      params["head"])


def _forward(params, x, heads, seq_ax, sp_strategy):
    batch, t, embed = x.shape
    for blk in params["blocks"]:
        q, k, v = _block_qkv(blk, x, heads)
        if seq_ax > 1 and sp_strategy == "ring":
            att = ring_attention(q, k, v, "seq", causal=True)
        elif seq_ax > 1:
            att = ulysses_attention(q, k, v, "seq", causal=True)
        else:
            att = attention(q, k, v, causal=True)
        x = x + matmul_any(att.reshape(batch, t, embed),
                           blk["wout"]) + blk["bout"]
        x = _mlp(blk, x)
    return _head(params, x)


def build_transformer_train_step(heads, mesh=None, learning_rate=0.1,
                                 sp_strategy="ulysses"):
    """Compile ``step(params, x, labels) -> (params, (loss, n_err))``:
    per-token causal-LM softmax xent, SGD update. With a mesh, ``x`` and
    ``labels`` shard over (data, seq) and gradients psum over both;
    ``sp_strategy`` picks "ulysses" (all-to-all) or "ring" attention."""
    if sp_strategy not in ("ulysses", "ring"):
        raise ValueError("sp_strategy must be 'ulysses' or 'ring', got %r"
                         % (sp_strategy,))
    data_ax = mesh.shape.get("data", 1) if mesh is not None else 1
    seq_ax = mesh.shape.get("seq", 1) if mesh is not None else 1

    def local_step(params, x, labels):
        # static: shard shapes are known at trace time — no collective
        n_tokens = jnp.float32(
            x.shape[0] * x.shape[1] * data_ax * seq_ax)

        def loss_fn(params):
            logits = _forward(params, x, heads, seq_ax, sp_strategy)
            logp = jax.nn.log_softmax(logits, axis=-1)
            picked = jnp.take_along_axis(
                logp, labels[..., None], axis=-1)[..., 0]
            n_err = jnp.sum(jnp.argmax(logits, -1) != labels)
            return -jnp.sum(picked) / n_tokens, n_err

        (loss, n_err), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        for axis, size in (("data", data_ax), ("seq", seq_ax)):
            if size > 1:
                grads = jax.lax.psum(grads, axis)
                loss = jax.lax.psum(loss, axis)
                n_err = jax.lax.psum(n_err, axis)
        new = jax.tree.map(lambda p, g: p - learning_rate * g, params,
                           grads)
        return new, (loss, n_err)

    if mesh is None or (data_ax == 1 and seq_ax == 1):
        return jax.jit(local_step)
    xspec = P("data", "seq", None)
    in_specs = (P(), xspec, P("data", "seq"))
    out_specs = (P(), (P(), P()))
    return jax.jit(shard_map(local_step, mesh=mesh,
                             in_specs=in_specs, out_specs=out_specs))


def shard_tokens(arrays, mesh):
    """Place (x, labels) with (data, seq) sharding."""
    specs = (P("data", "seq", None), P("data", "seq"))
    return [jax.device_put(a, NamedSharding(mesh, s))
            for a, s in zip(arrays, specs)]
