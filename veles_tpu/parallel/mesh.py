"""Logical device mesh construction.

Axes (sized from ``root.common.mesh.axes``, -1 = absorb remaining devices):

- ``data``  — batch (DP); gradient psum rides ICI
- ``model`` — tensor parallel (TP): weight column/row shards
- ``seq``   — sequence/context parallel (ring attention neighborhoods)
- ``pipe``  — pipeline stages
- ``expert``— MoE expert parallel

The reference has no analogue (its DP is host-level); this is the
scaling-book-style mesh the whole pod-mode design hangs off.
"""

import threading

import numpy

import jax
from jax.sharding import Mesh

from veles_tpu.core.config import root

AXIS_ORDER = ("pipe", "data", "expert", "seq", "model")


#: resolved once: (implementation, name of its replication-check
#: kwarg). Feature-detected by SIGNATURE, not try/except — a genuine
#: TypeError from a caller's bad mesh/specs must surface as itself,
#: never as a bogus "unexpected keyword" retry artifact.
_SHARD_MAP_IMPL = None


def _shard_map_impl():
    global _SHARD_MAP_IMPL
    if _SHARD_MAP_IMPL is None:
        import inspect
        impl = getattr(jax, "shard_map", None)
        if impl is None:
            from jax.experimental.shard_map import shard_map as impl
        try:
            params = inspect.signature(impl).parameters
        except (TypeError, ValueError):
            params = {}
        kwarg = "check_vma" if "check_vma" in params else (
            "check_rep" if "check_rep" in params else None)
        _SHARD_MAP_IMPL = (impl, kwarg)
    return _SHARD_MAP_IMPL


def shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: newer jax exposes it at
    the top level (replication checking via ``check_vma``), older jax
    under ``jax.experimental.shard_map`` (``check_rep``). Every
    shard_map in the tree routes through here so a jax upgrade is one
    edit, not eight."""
    impl, kwarg = _shard_map_impl()
    kwargs = {kwarg: False} if kwarg else {}
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **kwargs)


def axis_size(axis_name):
    """Static mesh-axis size from inside a shard_map body, across jax
    versions (``lax.axis_size`` is newer jax; older jax reads the axis
    environment)."""
    impl = getattr(jax.lax, "axis_size", None)
    if impl is not None:
        return impl(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def mesh_axes():
    cfg = root.common.mesh.axes
    if hasattr(cfg, "__content__"):
        cfg = cfg.__content__()
    return {name: int(cfg.get(name, 1)) for name in AXIS_ORDER}


def mesh_configured():
    """True when the config asks for a non-trivial mesh (any axis != 1,
    including a -1 absorb-the-devices wildcard). This is what makes pod
    mode CLI-reachable: ``--mesh data=8`` / ``root.common.mesh.axes``
    sets it, and the launcher then builds the mesh into the workflow."""
    return any(v != 1 for v in mesh_axes().values())


def initialize_distributed(coordinator, num_processes, process_id,
                           local_device_count=None):
    """Multi-host pod bring-up: ``jax.distributed.initialize`` so every
    process sees the GLOBAL device list and ``build_mesh`` spans hosts.

    The reference reached across hosts by SSH-spawning slaves and
    selecting per-host endpoints (``launcher.py:617-660``,
    ``server.py:721-732``); the TPU-idiomatic equivalent is one SPMD
    program per host joined through the JAX coordination service, with
    XLA collectives riding ICI/DCN. Must run before any jax backend
    initializes (i.e. before the first ``jax.devices()`` call).

    ``local_device_count`` (CPU testing only) forces this process's
    virtual device count via XLA_FLAGS — on real TPU hosts leave unset.
    """
    import os
    if local_device_count:
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=%d"
                     % local_device_count)
        os.environ["XLA_FLAGS"] = " ".join(flags)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=int(num_processes),
                               process_id=int(process_id))


def is_primary():
    """True on the process that owns singleton side effects (snapshots,
    plots, web status, result files) in a multi-process pod. Single
    process → trivially True; does not force jax backend init order
    beyond what any device query would."""
    try:
        return jax.process_index() == 0
    except RuntimeError:
        return True


def parse_axes(spec, flag="--mesh"):
    """Parse an ``AXIS=N[,AXIS=N...]`` mesh string into an override
    dict — ONE parser for ``--mesh`` and ``--serve-mesh`` (and their
    config twins), so the syntax cannot drift between flags. Raises
    ``ValueError`` naming ``flag``; sizes stay unvalidated here —
    :func:`build_mesh` owns the integer/positivity checks."""
    overrides = {}
    for part in str(spec).split(","):
        axis, eq, size = part.partition("=")
        axis = axis.strip()
        if not eq or axis not in AXIS_ORDER:
            raise ValueError(
                "%s expects AXIS=N[,AXIS=N...] with axes from %s, "
                "got %r" % (flag, ", ".join(AXIS_ORDER), spec))
        try:
            overrides[axis] = int(size)
        except ValueError:
            raise ValueError("%s: size %r of axis %s is not an integer"
                             % (flag, size, axis))
    return overrides


def build_mesh(devices=None, flag="root.common.mesh.axes / --mesh",
               **overrides):
    """Build a Mesh over ``devices`` with configured axis sizes.

    Axis sizes multiply to the device count; a single -1 axis absorbs the
    remainder (like a reshape). Axes of size 1 are kept (they cost nothing
    and make in/out specs uniform).

    Every size is validated here with an error naming the config knob —
    ``flag`` (the training default, or ``--serve-mesh``'s twin via
    :func:`veles_tpu.serving.build_serve_mesh`) — a bad value must fail
    as "axis data=0 is invalid", never as an opaque numpy reshape
    exception three layers down.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sizes = mesh_axes()
    for key, value in overrides.items():
        if key not in sizes:
            raise ValueError(
                "unknown mesh axis %r (valid: %s) — check %s"
                % (key, ", ".join(AXIS_ORDER), flag))
        sizes[key] = value
    for key, value in sizes.items():
        try:
            as_int = int(value)
        except (TypeError, ValueError):
            as_int = None
        if as_int is None or as_int != value or (
                as_int < 1 and as_int != -1):
            raise ValueError(
                "mesh axis %s=%r is invalid: sizes must be positive "
                "integers (or -1 to absorb the remaining devices) — "
                "check %s" % (key, value, flag))
        sizes[key] = as_int
    wildcard = [k for k, v in sizes.items() if v == -1]
    fixed = int(numpy.prod([v for v in sizes.values() if v != -1]))
    if len(wildcard) > 1:
        raise ValueError("only one mesh axis may be -1, got %s" % wildcard)
    if wildcard:
        if n % fixed:
            raise ValueError(
                "mesh axes %s: the fixed sizes multiply to %d, which "
                "does not divide the %d available devices — check %s"
                % (sizes, fixed, n, flag))
        sizes[wildcard[0]] = n // fixed
    elif fixed != n:
        raise ValueError(
            "mesh axes %s multiply to %d but %d devices present — "
            "check %s" % (sizes, fixed, n, flag))
    shape = tuple(sizes[name] for name in AXIS_ORDER)
    dev_array = numpy.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, AXIS_ORDER)
    note_active_mesh(mesh)
    return mesh


# -- active-mesh registry ----------------------------------------------------
#
# The LAST mesh built in this process, kept as plain data (no Device
# refs): the /metrics mesh gauges, the web-status device column and the
# fleet slaves' metric-row coordinates all read it (a master scrape must
# be able to tell WHICH shard a process is, not just which slave).

_active_lock = threading.Lock()
_active_mesh = None


def note_active_mesh(mesh):
    """Record ``mesh`` as the process's active mesh (called by
    :func:`build_mesh`; callers constructing a Mesh by hand can call it
    directly)."""
    global _active_mesh
    info = {"axes": {name: int(size)
                     for name, size in dict(mesh.shape).items()},
            "devices": int(mesh.size)}
    with _active_lock:
        _active_mesh = info


def active_mesh_info():
    """``{"axes": {name: size}, "devices": n}`` of the last mesh built
    in this process, or None when nothing meshed yet."""
    with _active_lock:
        return None if _active_mesh is None else {
            "axes": dict(_active_mesh["axes"]),
            "devices": _active_mesh["devices"]}


def mesh_shape_label(info=None):
    """Compact ``data2.model4`` string of the non-trivial axes (label
    value for /metrics rows and the dashboard cell); None when no mesh
    is active or every axis is 1."""
    if info is None:
        info = active_mesh_info()
    if not info:
        return None
    parts = ["%s%d" % (name, size)
             for name in AXIS_ORDER
             for size in [info["axes"].get(name, 1)] if size != 1]
    return ".".join(parts) or None


def mesh_coordinate_labels():
    """Label dict identifying this process's place in the pod:
    ``{"process": i, "mesh": "data2.model4"}`` — merged into the
    metric rows a fleet slave piggybacks on update frames so a master
    scrape distinguishes shards, not just slaves. Empty when no mesh
    is active (single-chip slaves keep their old label set)."""
    label = mesh_shape_label()
    if label is None:
        return {}
    try:
        process = jax.process_index()
    except RuntimeError:
        process = 0
    return {"process": str(process), "mesh": label}
