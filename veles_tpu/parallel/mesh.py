"""Logical device mesh construction.

Axes (sized from ``root.common.mesh.axes``, -1 = absorb remaining devices):

- ``data``  — batch (DP); gradient psum rides ICI
- ``model`` — tensor parallel (TP): weight column/row shards
- ``seq``   — sequence/context parallel (ring attention neighborhoods)
- ``pipe``  — pipeline stages
- ``expert``— MoE expert parallel

The reference has no analogue (its DP is host-level); this is the
scaling-book-style mesh the whole pod-mode design hangs off.
"""

import numpy

import jax
from jax.sharding import Mesh

from veles_tpu.core.config import root

AXIS_ORDER = ("pipe", "data", "expert", "seq", "model")


def mesh_axes():
    cfg = root.common.mesh.axes
    if hasattr(cfg, "__content__"):
        cfg = cfg.__content__()
    return {name: int(cfg.get(name, 1)) for name in AXIS_ORDER}


def build_mesh(devices=None, **overrides):
    """Build a Mesh over ``devices`` with configured axis sizes.

    Axis sizes multiply to the device count; a single -1 axis absorbs the
    remainder (like a reshape). Axes of size 1 are kept (they cost nothing
    and make in/out specs uniform).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sizes = mesh_axes()
    sizes.update({k: int(v) for k, v in overrides.items()})
    wildcard = [k for k, v in sizes.items() if v == -1]
    fixed = int(numpy.prod([v for v in sizes.values() if v != -1]))
    if len(wildcard) > 1:
        raise ValueError("only one mesh axis may be -1, got %s" % wildcard)
    if wildcard:
        if n % fixed:
            raise ValueError(
                "%d devices not divisible by fixed axes %s" % (n, sizes))
        sizes[wildcard[0]] = n // fixed
    elif fixed != n:
        raise ValueError(
            "mesh axes %s multiply to %d but %d devices present"
            % (sizes, fixed, n))
    shape = tuple(sizes[name] for name in AXIS_ORDER)
    dev_array = numpy.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)
