"""Logical device mesh construction.

Axes (sized from ``root.common.mesh.axes``, -1 = absorb remaining devices):

- ``data``  — batch (DP); gradient psum rides ICI
- ``model`` — tensor parallel (TP): weight column/row shards
- ``seq``   — sequence/context parallel (ring attention neighborhoods)
- ``pipe``  — pipeline stages
- ``expert``— MoE expert parallel

The reference has no analogue (its DP is host-level); this is the
scaling-book-style mesh the whole pod-mode design hangs off.
"""

import numpy

import jax
from jax.sharding import Mesh

from veles_tpu.core.config import root

AXIS_ORDER = ("pipe", "data", "expert", "seq", "model")


def mesh_axes():
    cfg = root.common.mesh.axes
    if hasattr(cfg, "__content__"):
        cfg = cfg.__content__()
    return {name: int(cfg.get(name, 1)) for name in AXIS_ORDER}


def mesh_configured():
    """True when the config asks for a non-trivial mesh (any axis != 1,
    including a -1 absorb-the-devices wildcard). This is what makes pod
    mode CLI-reachable: ``--mesh data=8`` / ``root.common.mesh.axes``
    sets it, and the launcher then builds the mesh into the workflow."""
    return any(v != 1 for v in mesh_axes().values())


def initialize_distributed(coordinator, num_processes, process_id,
                           local_device_count=None):
    """Multi-host pod bring-up: ``jax.distributed.initialize`` so every
    process sees the GLOBAL device list and ``build_mesh`` spans hosts.

    The reference reached across hosts by SSH-spawning slaves and
    selecting per-host endpoints (``launcher.py:617-660``,
    ``server.py:721-732``); the TPU-idiomatic equivalent is one SPMD
    program per host joined through the JAX coordination service, with
    XLA collectives riding ICI/DCN. Must run before any jax backend
    initializes (i.e. before the first ``jax.devices()`` call).

    ``local_device_count`` (CPU testing only) forces this process's
    virtual device count via XLA_FLAGS — on real TPU hosts leave unset.
    """
    import os
    if local_device_count:
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=%d"
                     % local_device_count)
        os.environ["XLA_FLAGS"] = " ".join(flags)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=int(num_processes),
                               process_id=int(process_id))


def is_primary():
    """True on the process that owns singleton side effects (snapshots,
    plots, web status, result files) in a multi-process pod. Single
    process → trivially True; does not force jax backend init order
    beyond what any device query would."""
    try:
        return jax.process_index() == 0
    except RuntimeError:
        return True


def build_mesh(devices=None, **overrides):
    """Build a Mesh over ``devices`` with configured axis sizes.

    Axis sizes multiply to the device count; a single -1 axis absorbs the
    remainder (like a reshape). Axes of size 1 are kept (they cost nothing
    and make in/out specs uniform).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sizes = mesh_axes()
    sizes.update({k: int(v) for k, v in overrides.items()})
    wildcard = [k for k, v in sizes.items() if v == -1]
    fixed = int(numpy.prod([v for v in sizes.values() if v != -1]))
    if len(wildcard) > 1:
        raise ValueError("only one mesh axis may be -1, got %s" % wildcard)
    if wildcard:
        if n % fixed:
            raise ValueError(
                "%d devices not divisible by fixed axes %s" % (n, sizes))
        sizes[wildcard[0]] = n // fixed
    elif fixed != n:
        raise ValueError(
            "mesh axes %s multiply to %d but %d devices present"
            % (sizes, fixed, n))
    shape = tuple(sizes[name] for name in AXIS_ORDER)
    dev_array = numpy.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)
