"""KV-cache autoregressive decoding for the causal-LM tier.

Training-side long context is covered by ring/Ulysses sequence
parallelism (``transformer_step.py``); this module is the SERVING side:
generate tokens from the same pre-LN causal model without recomputing
the prompt every step. TPU-native shape: the whole generation loop is
ONE ``lax.scan`` inside one jit — per-step K/V appends are
``lax.dynamic_update_slice`` into a static-shape cache (XLA keeps it
in-place via donation), the attention against the cache prefix masks by
position, and the sampled token feeds back through the scan carry. No
reference counterpart (VELES predates transformers) — additive tier.

Numerical contract: decode produces the same logits as running
``transformer_step._forward`` over the growing full sequence to within
fp-reassociation tolerance (``tests/test_decode.py`` asserts
rtol 2e-4 — the cached path computes attention in a different order
and ``_forward``'s core may take the engine's reduced-precision
policy, so equality is numerical, not bitwise), because both use the
identical parameter pytree and sublayer math.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from veles_tpu.ops.attention import attention
# ONE copy of the sublayer math, shared with the training-side full
# forward — the equivalence the module contract promises is structural
from veles_tpu.parallel.transformer_step import _block_qkv, _head, _mlp


def init_kv_cache(n_blocks, batch, max_len, heads, head_dim,
                  dtype=jnp.float32):
    """Static-shape cache: K/V per block, plus the filled length."""
    shape = (n_blocks, batch, max_len, heads, head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "length": jnp.zeros((), jnp.int32)}


def prefill(params, x, heads, cache):
    """Run the prompt (B, T, E) once, filling ``cache`` positions
    [0, T); returns ``(last_logits, cache)`` with ``last_logits``
    (B, vocab) for the first generated token."""
    batch, t, embed = x.shape
    ks, vs = [], []
    for blk in params["blocks"]:
        q, k, v = _block_qkv(blk, x, heads)
        ks.append(k)
        vs.append(v)
        # full causal attention over the prompt — the SAME gated op the
        # training forward uses (flash kernel for prompts >= 4096)
        att = attention(q, k, v, causal=True)
        x = x + att.reshape(batch, t, embed) @ blk["wout"] + blk["bout"]
        x = _mlp(blk, x)
    logits = _head(params, x[:, -1])
    cache = {
        "k": lax.dynamic_update_slice(
            cache["k"], jnp.stack(ks).astype(cache["k"].dtype),
            (0, 0, 0, 0, 0)),
        "v": lax.dynamic_update_slice(
            cache["v"], jnp.stack(vs).astype(cache["v"].dtype),
            (0, 0, 0, 0, 0)),
        "length": jnp.int32(t),
    }
    return logits, cache


def decode_step(params, x_tok, heads, cache):
    """One token (B, 1, E) through every block against the cache;
    returns ``(logits, cache)`` with the token's K/V appended."""
    batch, _, embed = x_tok.shape
    length = cache["length"]
    max_len = cache["k"].shape[2]
    # positions [0, length] are valid (the new token attends to itself)
    mask = (jnp.arange(max_len) <= length)[None, None, None, :]
    x = x_tok
    new_k, new_v = cache["k"], cache["v"]
    for i, blk in enumerate(params["blocks"]):
        q, k, v = _block_qkv(blk, x, heads)
        new_k = lax.dynamic_update_slice(
            new_k, k[None].astype(new_k.dtype), (i, 0, length, 0, 0))
        new_v = lax.dynamic_update_slice(
            new_v, v[None].astype(new_v.dtype), (i, 0, length, 0, 0))
        scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
        # q (B,1,H,D) x cache K (B,L,H,D) -> (B,H,1,L), f32 softmax
        s = jnp.einsum("bqhd,bkhd->bhqk", q,
                       new_k[i].astype(q.dtype),
                       preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        att = jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype),
                         new_v[i].astype(q.dtype),
                         preferred_element_type=jnp.float32
                         ).astype(x.dtype)
        x = x + att.reshape(batch, 1, embed) @ blk["wout"] + blk["bout"]
        x = _mlp(blk, x)
    logits = _head(params, x[:, 0])
    return logits, {"k": new_k, "v": new_v, "length": length + 1}


def _pick_token(logits, key, temperature, sample, top_k):
    """Greedy (``sample=False``) or temperature sampling, optionally
    truncated to the top-k logits. Pure — runs inside the scan.
    ``sample``/``top_k`` are trace-time constants; ``temperature`` is a
    traced operand (a new value must NOT recompile the decode loop)."""
    if not sample:
        return jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) / temperature
    if top_k:
        # lax.top_k, not a full vocab sort — this runs per token inside
        # the hot decode scan
        kth = lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
    return jax.random.categorical(key, scaled, axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("heads", "n_tokens", "sample",
                                    "top_k"),
                   donate_argnames=("cache",))
def _generate_jit(params, embed_table, prompt_x, heads, n_tokens, cache,
                  key, temperature, sample, top_k):
    logits, cache = prefill(params, prompt_x, heads, cache)

    def body(carry, step_key):
        cache, logits = carry
        tok = _pick_token(logits, step_key, temperature, sample,
                          top_k)                                 # (B,)
        x_tok = embed_table[tok][:, None, :]                     # (B,1,E)
        logits, cache = decode_step(params, x_tok, heads, cache)
        return (cache, logits), tok

    (cache, logits), toks = lax.scan(body, (cache, logits),
                                     jax.random.split(key, n_tokens))
    return jnp.swapaxes(toks, 0, 1), logits, cache


def generate(params, embed_table, prompt_tokens, heads, n_tokens,
             max_len=None, temperature=0.0, top_k=0, key=None):
    """Decode ``n_tokens`` after ``prompt_tokens`` (B, T) int32 —
    greedy by default; ``temperature > 0`` samples (optionally truncated
    to the ``top_k`` highest logits) from the reproducible ``key``
    (defaults to the framework's named "decode" PRNG stream).

    ``embed_table`` (vocab, E) maps tokens to the model's input
    embeddings (the toy model trains on pre-embedded x, so the table is
    the caller's). The prompt prefills the cache in one pass; the whole
    decode loop is one scan inside one jit with the cache donated.
    Returns ``(tokens (B, n_tokens), cache)``."""
    batch, t = prompt_tokens.shape
    n_blocks = len(params["blocks"])
    embed = embed_table.shape[1]
    head_dim = embed // heads
    if max_len is None:
        max_len = t + n_tokens
    if max_len < t + n_tokens:
        raise ValueError("max_len %d < prompt %d + n_tokens %d"
                         % (max_len, t, n_tokens))
    if top_k < 0:
        raise ValueError("top_k must be >= 0, got %d" % top_k)
    top_k = min(int(top_k), embed_table.shape[0])  # clamp to the vocab
    if key is None:
        if temperature:
            from veles_tpu.core.prng import get as get_rng
            key = get_rng("decode").next_key()
        else:
            key = jax.random.key(0)  # unused by greedy, jit wants one
    # the cache follows the serving dtype: with bf16 params/table the
    # K/V traffic (comparable to the weight traffic at long context)
    # halves too — measured +~50% tokens/sec on the memory-bound loop
    cache = init_kv_cache(n_blocks, batch, max_len, heads, head_dim,
                          dtype=embed_table.dtype)
    prompt_x = embed_table[prompt_tokens]
    toks, _, cache = _generate_jit(params, embed_table, prompt_x, heads,
                                   n_tokens, cache, key,
                                   jnp.float32(temperature or 1.0),
                                   bool(temperature), int(top_k))
    return toks, cache
