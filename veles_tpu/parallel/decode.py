"""KV-cache autoregressive decoding for the causal-LM tier.

Training-side long context is covered by ring/Ulysses sequence
parallelism (``transformer_step.py``); this module is the SERVING side:
generate tokens from the same pre-LN causal model without recomputing
the prompt every step. TPU-native shape: the whole generation loop is
ONE ``lax.scan`` inside one jit — per-step K/V appends are
``lax.dynamic_update_slice`` into a static-shape cache (XLA keeps it
in-place via donation), the attention against the cache prefix masks by
position, and the sampled token feeds back through the scan carry. No
reference counterpart (VELES predates transformers) — additive tier.

Numerical contract: decode produces the same logits as running
``transformer_step._forward`` over the growing full sequence to within
fp-reassociation tolerance (``tests/test_decode.py`` asserts
rtol 2e-4 — the cached path computes attention in a different order
and ``_forward``'s core may take the engine's reduced-precision
policy, so equality is numerical, not bitwise), because both use the
identical parameter pytree and sublayer math.
"""

import functools
import threading

import jax
import jax.numpy as jnp
from jax import lax

from veles_tpu.ops.attention import attention
from veles_tpu.ops.quant import (int8_cache_attend, matmul_any,
                                 quantize_int8)
from veles_tpu.observe.xla_stats import instrument
from veles_tpu.parallel.mesh import shard_map
# ONE copy of the sublayer math, shared with the training-side full
# forward — the equivalence the module contract promises is structural
from veles_tpu.parallel.transformer_step import _block_qkv, _head, _mlp


def init_kv_cache(n_blocks, batch, max_len, heads, head_dim,
                  dtype=jnp.float32, quantized=False):
    """Static-shape cache: K/V per block, plus the filled length.

    ``quantized=True`` stores K/V as int8 with one f32 absmax scale per
    (block, batch, position, head) — the KV half of the int8 serving
    tier. At decode lengths the cache read rivals the weight read, so
    this halves the OTHER half of the memory-bound loop's traffic.
    Layout is (L, B, H, D, T) — head-major, positions minor: the
    dequant-fused attend kernel's dots then tile the MXU natively
    (q x K contracts D with T on lanes; V x p contracts T), and XLA
    cannot sneak a materialized bf16 widening of the cache in between
    (measured 4-8x slower in every positions-major layout)."""
    shape = (n_blocks, batch, max_len, heads, head_dim)
    if quantized:
        qshape = (n_blocks, batch, heads, head_dim, max_len)
        sshape = (n_blocks, batch, heads, max_len)
        return {"k": jnp.zeros(qshape, jnp.int8),
                "v": jnp.zeros(qshape, jnp.int8),
                "k_scale": jnp.zeros(sshape, jnp.float32),
                "v_scale": jnp.zeros(sshape, jnp.float32),
                "length": jnp.zeros((), jnp.int32)}
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "length": jnp.zeros((), jnp.int32)}


def _quantize_kv(x):
    """Per-(batch, position, head) symmetric int8: (..., D) ->
    (int8 (..., D), f32 scale (...,)). The quantization the cache
    stores; one copy for prefill and decode appends."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale[..., 0]


def _prefill_forward(params, x, heads, length=None):
    """The prompt forward pass shared by every prefill surface: run
    ``x`` (B, T, E) through all blocks once and return
    ``(last_logits, k_all, v_all, cache_len)`` with ``k_all``/``v_all``
    stacked (L, B, T, H, D) — the caller decides how to store them
    (full-cache write for :func:`prefill`, bucket-shaped slot slab for
    :func:`slot_admit_many`).

    ``length`` may be ``None`` (use T), a traced scalar (one shared
    right-padded length), or a traced (B,) vector (per-row true lengths
    — the batched same-bucket admission path); the logits always read
    from each row's position ``length - 1``."""
    batch, t, embed = x.shape
    ks, vs = [], []
    for blk in params["blocks"]:
        q, k, v = _block_qkv(blk, x, heads)
        ks.append(k)
        vs.append(v)
        # full causal attention over the prompt — the SAME gated op the
        # training forward uses (flash kernel for prompts >= 4096).
        # With a quantized cache the prompt attention still runs on the
        # exact K/V; only the CACHED copies are rounded (decode steps
        # then attend against what was stored, like every later token).
        att = attention(q, k, v, causal=True)
        x = x + matmul_any(att.reshape(batch, t, embed),
                           blk["wout"]) + blk["bout"]
        x = _mlp(blk, x)
    if length is None:
        last = x[:, -1]
        cache_len = jnp.int32(t)
    else:
        cache_len = jnp.asarray(length, jnp.int32)
        if cache_len.ndim == 0:
            last = lax.dynamic_slice_in_dim(x, cache_len - 1, 1,
                                            axis=1)[:, 0]
        else:
            last = jnp.take_along_axis(
                x, (cache_len - 1)[:, None, None], axis=1)[:, 0]
    logits = _head(params, last)
    return logits, jnp.stack(ks), jnp.stack(vs), cache_len


def prefill(params, x, heads, cache, length=None):
    """Run the prompt (B, T, E) once, filling ``cache`` positions
    [0, T); returns ``(last_logits, cache)`` with ``last_logits``
    (B, vocab) for the first generated token.

    ``length`` (traced scalar, default T) supports right-PADDED
    prompts: the causal mask means pad positions past ``length`` never
    influence the real positions' K/V, the logits read from position
    ``length - 1``, and the cache length is ``length`` — so one
    compiled program serves a whole bucket of prompt lengths (the
    continuous-batching admission path)."""
    logits, k_all, v_all, cache_len = _prefill_forward(params, x, heads,
                                                       length)
    new = {"length": cache_len}
    if "k_scale" in cache:
        for name, val in (("k", k_all), ("v", v_all)):
            q8, scale = _quantize_kv(val)        # (L,B,T,H,D),(L,B,T,H)
            # head-major, positions-minor cache layout (see
            # init_kv_cache): (L,B,H,D,T) / (L,B,H,T)
            new[name] = lax.dynamic_update_slice(
                cache[name], jnp.transpose(q8, (0, 1, 3, 4, 2)),
                (0, 0, 0, 0, 0))
            new[name + "_scale"] = lax.dynamic_update_slice(
                cache[name + "_scale"],
                jnp.transpose(scale, (0, 1, 3, 2)), (0, 0, 0, 0))
    else:
        new["k"] = lax.dynamic_update_slice(
            cache["k"], k_all.astype(cache["k"].dtype), (0, 0, 0, 0, 0))
        new["v"] = lax.dynamic_update_slice(
            cache["v"], v_all.astype(cache["v"].dtype), (0, 0, 0, 0, 0))
    return logits, new


def _cache_attend(q, k_all, v_all, mask):
    """Attention of query tokens against the cache prefix, f32 softmax:
    ONE copy of the math for the single-device and tensor-parallel
    decode paths (the TP guarantee of token-identity depends on it).
    The int8-cache variant lives in ``ops/quant.int8_cache_attend``
    (head-major layout + the dequant-fused Pallas kernel)."""
    scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
    # q (B,1,H,D) x cache K (B,L,H,D) -> (B,H,1,L)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_all.astype(q.dtype),
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype),
                      v_all.astype(q.dtype),
                      preferred_element_type=jnp.float32)


def decode_step(params, x_tok, heads, cache):
    """One token (B, 1, E) through every block against the cache;
    returns ``(logits, cache)`` with the token's K/V appended."""
    batch, _, embed = x_tok.shape
    length = cache["length"]
    quantized = "k_scale" in cache
    # positions [0, length] are valid (the new token attends to itself)
    if quantized:
        max_len = cache["k"].shape[-1]  # head-major layout: T is minor
        mask_addend = jnp.where(jnp.arange(max_len) <= length, 0.0,
                                -1e30).astype(jnp.float32)
        # python float (weak type): `q * inv_sqrt` must NOT promote a
        # bf16 q to f32 — that would kill the fallback path's bf16
        # compute branch and widen the int8 cache to f32
        inv_sqrt = (embed // heads) ** -0.5
    else:
        max_len = cache["k"].shape[2]
        mask = (jnp.arange(max_len) <= length)[None, None, None, :]
    x = x_tok
    new_k, new_v = cache["k"], cache["v"]
    new_ks = cache.get("k_scale")
    new_vs = cache.get("v_scale")
    for i, blk in enumerate(params["blocks"]):
        q, k, v = _block_qkv(blk, x, heads)
        if quantized:
            kq, ks = _quantize_kv(k)        # (B,1,H,D), (B,1,H)
            vq, vs = _quantize_kv(v)
            # head-major column write at position `length`
            new_k = lax.dynamic_update_slice(
                new_k, jnp.transpose(kq, (0, 2, 3, 1))[None],
                (i, 0, 0, 0, length))
            new_v = lax.dynamic_update_slice(
                new_v, jnp.transpose(vq, (0, 2, 3, 1))[None],
                (i, 0, 0, 0, length))
            new_ks = lax.dynamic_update_slice(
                new_ks, jnp.transpose(ks, (0, 2, 1))[None],
                (i, 0, 0, length))
            new_vs = lax.dynamic_update_slice(
                new_vs, jnp.transpose(vs, (0, 2, 1))[None],
                (i, 0, 0, length))
            att = int8_cache_attend(q * inv_sqrt, new_k[i], new_ks[i],
                                    new_v[i], new_vs[i], mask_addend)
        else:
            new_k = lax.dynamic_update_slice(
                new_k, k[None].astype(new_k.dtype), (i, 0, length, 0, 0))
            new_v = lax.dynamic_update_slice(
                new_v, v[None].astype(new_v.dtype), (i, 0, length, 0, 0))
            att = _cache_attend(q, new_k[i], new_v[i], mask)
        att = att.astype(x.dtype)
        x = x + matmul_any(att.reshape(batch, 1, embed),
                           blk["wout"]) + blk["bout"]
        x = _mlp(blk, x)
    logits = _head(params, x[:, 0])
    new = {"k": new_k, "v": new_v, "length": length + 1}
    if quantized:
        new["k_scale"] = new_ks
        new["v_scale"] = new_vs
    return logits, new


#: the decode-path weight matrices the int8 tier quantizes (everything
#: the per-token loop reads in bulk; norms and biases stay fp)
_QUANT_BLOCK_MATS = ("wqkv", "wout", "w1", "w2")


def quantize_params(params):
    """Weight-only int8 quantization of the decode-path matmuls
    (``ops/quant.py`` W8A16 recipe): every block projection and the
    vocab head become ``{"q8": int8, "scale": f32}`` leaves that
    ``matmul_any`` dequantizes inside the product. Norms, biases and
    the caller's embed table stay in the serving float dtype."""
    qblocks = []
    for blk in params["blocks"]:
        qblk = dict(blk)
        for name in _QUANT_BLOCK_MATS:
            q, s = quantize_int8(blk[name])
            qblk[name] = {"q8": q, "scale": s}
        qblocks.append(qblk)
    q, s = quantize_int8(params["head"])
    return dict(params, blocks=qblocks, head={"q8": q, "scale": s})


def _pick_token(logits, key, temperature, sample, top_k):
    """Greedy (``sample=False``) or temperature sampling, optionally
    truncated to the top-k logits. Pure — runs inside the scan.
    ``sample``/``top_k`` are trace-time constants; ``temperature`` is a
    traced operand (a new value must NOT recompile the decode loop)."""
    if not sample:
        return jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) / temperature
    if top_k:
        # lax.top_k, not a full vocab sort — this runs per token inside
        # the hot decode scan
        kth = lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
    return jax.random.categorical(key, scaled, axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("heads", "n_tokens", "sample",
                                    "top_k"),
                   donate_argnames=("cache",))
def _generate_jit(params, embed_table, prompt_x, heads, n_tokens, cache,
                  key, temperature, sample, top_k):
    logits, cache = prefill(params, prompt_x, heads, cache)

    def body(carry, step_key):
        cache, logits = carry
        tok = _pick_token(logits, step_key, temperature, sample,
                          top_k)                                 # (B,)
        x_tok = embed_table[tok][:, None, :]                     # (B,1,E)
        logits, cache = decode_step(params, x_tok, heads, cache)
        return (cache, logits), tok

    # per-step keys by fold_in(key, step) — the SAME derivation the
    # continuous-batching slot engine uses per (request key, step), so
    # a slot's sampled stream reproduces generate(batch=1) exactly
    step_keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        key, jnp.arange(n_tokens))
    (cache, logits), toks = lax.scan(body, (cache, logits), step_keys)
    return jnp.swapaxes(toks, 0, 1), logits, cache


def generate(params, embed_table, prompt_tokens, heads, n_tokens,
             max_len=None, temperature=0.0, top_k=0, key=None,
             quantize=None):
    """Decode ``n_tokens`` after ``prompt_tokens`` (B, T) int32 —
    greedy by default; ``temperature > 0`` samples (optionally truncated
    to the ``top_k`` highest logits) from the reproducible ``key``
    (defaults to the framework's named "decode" PRNG stream).

    ``quantize="int8"`` runs the W8A16 serving tier: the weight
    matrices are absmax-quantized once up front and the per-token loop
    reads them as int8 through the dequant-fused Pallas matvec
    (``ops/quant.py``) — half the bf16 tier's HBM traffic on the
    memory-bound loop. ``quantize="int8-kv"`` additionally stores the
    KV cache as int8 with per-(position, head) scales — at decode
    lengths the cache read rivals the weight read, so this halves the
    other half too. Pass an already-``quantize_params``-ed pytree to
    skip the requantization cost across calls.

    ``embed_table`` (vocab, E) maps tokens to the model's input
    embeddings (the toy model trains on pre-embedded x, so the table is
    the caller's). The prompt prefills the cache in one pass; the whole
    decode loop is one scan inside one jit with the cache donated.
    Returns ``(tokens (B, n_tokens), cache)``."""
    if quantize not in (None, "none", "int8", "int8-kv"):
        raise ValueError("quantize must be None, 'int8' or 'int8-kv', "
                         "got %r" % (quantize,))
    if quantize in ("int8", "int8-kv") \
            and not isinstance(params["head"], dict):
        params = quantize_params(params)
    batch, t = prompt_tokens.shape
    n_blocks = len(params["blocks"])
    embed = embed_table.shape[1]
    head_dim = embed // heads
    if max_len is None:
        max_len = t + n_tokens
    if max_len < t + n_tokens:
        raise ValueError("max_len %d < prompt %d + n_tokens %d"
                         % (max_len, t, n_tokens))
    if top_k < 0:
        raise ValueError("top_k must be >= 0, got %d" % top_k)
    top_k = min(int(top_k), embed_table.shape[0])  # clamp to the vocab
    if key is None:
        if temperature:
            from veles_tpu.core.prng import get as get_rng
            key = get_rng("decode").next_key()
        else:
            key = jax.random.key(0)  # unused by greedy, jit wants one
    if quantize == "int8-kv":
        # round the quantized cache up to whole 128-lane tiles so the
        # dequant-fused attend kernel's T gate engages (masking makes
        # the extra positions inert)
        max_len = -(-max_len // 128) * 128
    # the cache follows the serving dtype: with bf16 params/table the
    # K/V traffic (comparable to the weight traffic at long context)
    # halves too — measured +~50% tokens/sec on the memory-bound loop
    cache = init_kv_cache(n_blocks, batch, max_len, heads, head_dim,
                          dtype=embed_table.dtype,
                          quantized=quantize == "int8-kv")
    prompt_x = embed_table[prompt_tokens]
    toks, _, cache = _generate_jit(params, embed_table, prompt_x, heads,
                                   n_tokens, cache, key,
                                   jnp.float32(temperature or 1.0),
                                   bool(temperature), int(top_k))
    return toks, cache


# -- continuous batching (slot engine) ----------------------------------------
#
# The serving tier's per-request loop: a fixed pool of cache SLOTS, each
# holding one in-flight sequence at its own length. New requests prefill
# into a free slot while other slots keep decoding — the "continuous
# batching" serving recipe (beyond-reference; VELES's serving analogue
# batches per tick, ``restful_api.py:78-215``). The math per slot is
# decode_step's exactly (same _block_qkv/_cache_attend/_head), with the
# scalar cache length generalized to a per-slot vector, the appends
# generalized to per-slot dynamic_update_slice at each slot's own
# length, and the attended span tiled to the longest live sequence
# (docs/serving_performance.md).


#: default attended-span tile (positions). The slot engine's per-step
#: attention and append traffic scale with
#: ``ceil((longest live sequence + chunk) / TILE) * TILE`` instead of
#: ``max_len`` — one compiled program per tile count, the same
#: compile-bounding trick as the prompt buckets. 128 = the TPU lane
#: width, and the granule the int8-KV attend kernel's T gate wants.
SLOT_SPAN_TILE = 128


def init_slot_state(n_blocks, slots, max_len, heads, head_dim, vocab,
                    dtype=jnp.float32, quantized=False, mesh=None,
                    mesh_axis="model", paged=False, pages=None,
                    page_size=None):
    """Cache + control state for ``slots`` concurrent sequences.

    ``quantized=True`` stores the slot K/V as int8 with per-(slot,
    position, head) f32 scales in the head-major (L, S, H, D, T)
    layout — ``init_kv_cache``'s int8-KV recipe generalized to the
    slot pool, so continuous serving gets the same halved cache
    traffic as raw ``generate(quantize="int8-kv")``.

    ``mesh`` creates the state already in the serving layout: the KV
    slab (and the int8 tier's scales) sharded over their heads dim on
    ``mesh_axis``, control leaves replicated — per-device slot-cache
    HBM then scales with H/n (:func:`slot_state_specs`).

    ``paged=True`` swaps the dense per-slot slab for the page-pool
    layout (``parallel/kv_pool.py``): one ``pages`` x ``page_size``
    pool (default: the slab-equivalent ``slots x ceil((max_len + 2) /
    page_size)`` plus the scratch page; the serving decoder sizes its
    own default with ``chunk=n_tokens`` dispatch slack) shared by
    every slot through a
    host page table, created in-layout under ``mesh`` exactly like the
    slab (pool pages shard over HEADS)."""
    if paged:
        from veles_tpu.parallel.kv_pool import (default_pool_pages,
                                                init_paged_state)

        if page_size is None:
            page_size = SLOT_SPAN_TILE
        if pages is None:
            pages = default_pool_pages(slots, max_len, page_size)
        return init_paged_state(
            n_blocks, pages, page_size, heads, head_dim, vocab, slots,
            dtype=dtype, quantized=quantized, mesh=mesh,
            mesh_axis=mesh_axis)
    base = {
        "lengths": jnp.zeros((slots,), jnp.int32),
        "logits": jnp.zeros((slots, vocab), jnp.float32),
        # per-slot sampling stream: the request's key + how many tokens
        # it has generated (step key = fold_in(req_key, step) — the
        # derivation generate() shares, so sampled streams match)
        "req_key": jax.random.split(jax.random.key(0), slots),
        "step": jnp.zeros((slots,), jnp.int32),
    }
    if quantized:
        qshape = (n_blocks, slots, heads, head_dim, max_len)
        sshape = (n_blocks, slots, heads, max_len)
        state = dict(base,
                     k=jnp.zeros(qshape, jnp.int8),
                     v=jnp.zeros(qshape, jnp.int8),
                     k_scale=jnp.zeros(sshape, jnp.float32),
                     v_scale=jnp.zeros(sshape, jnp.float32))
    else:
        shape = (n_blocks, slots, max_len, heads, head_dim)
        state = dict(base, k=jnp.zeros(shape, dtype),
                     v=jnp.zeros(shape, dtype))
    if mesh is not None:
        state = shard_slot_tree(
            state, mesh, slot_state_specs(quantized, axis=mesh_axis))
    return state


def slot_state_bytes(state):
    """Device bytes of a slot/paged decode state pytree — the
    ``decode_state`` memscope accountant's sizing primitive. For the
    paged layout the PAGE leaves are charged to the ``kv_pool`` owner
    instead (``kv_pool.paged_kv_bytes``), so callers subtract."""
    from veles_tpu.observe.memscope import pytree_nbytes
    return pytree_nbytes(state)


def param_tree_bytes(params, embed_table=None):
    """Device bytes of a parameter tree (plus the tied embedding table
    when it is a separate leaf) — the ``params`` / ``param_stash``
    memscope accountants' sizing primitive."""
    from veles_tpu.observe.memscope import pytree_nbytes
    return pytree_nbytes(params) + pytree_nbytes(embed_table)


def _slot_admit_many(params, embed_table, heads, state, slots,
                     prompt_x, req_keys, lengths):
    """Admit a whole same-bucket group in ONE dispatch: prefill
    ``prompt_x`` (B, T, E) — each row right-padded to the bucket T —
    and scatter the K/V slabs into slots ``slots`` (B,) int32.

    The prefill cost scales with the BUCKET (T), not ``max_len``: only
    positions [0, T) of each slot lane are written. Stale positions
    beyond the bucket from a retired occupant are harmless — a lane's
    position is always (re)written by this sequence's own append
    before its mask first exposes it. One compiled program per
    (bucket, group size); the host pads a group to a power-of-two size
    with DUPLICATE rows (identical slot/prompt/key/length), which is
    well-defined because duplicate scatter writes carry equal values.

    ``req_keys`` (B,) seeds each slot's sampling stream; ``lengths``
    (B,) are the true prompt lengths inside the padded rows."""
    t = prompt_x.shape[1]
    # named after the host-side "decode.admit" span so the XLA device
    # trace and the span timeline line up in a profiler capture
    # (observe/profile.py; zero cost post-compile)
    with jax.named_scope("decode.admit"):
        logits, k_all, v_all, lengths = _prefill_forward(
            params, prompt_x, heads, lengths)
    new = dict(
        state,
        lengths=state["lengths"].at[slots].set(lengths),
        logits=state["logits"].at[slots].set(
            logits.astype(jnp.float32)),
        req_key=state["req_key"].at[slots].set(req_keys),
        step=state["step"].at[slots].set(jnp.zeros_like(lengths)),
    )
    if "k_scale" in state:
        for name, val in (("k", k_all), ("v", v_all)):
            q8, scale = _quantize_kv(val)    # (L,B,T,H,D), (L,B,T,H)
            # head-major, positions-minor slot layout (init_slot_state)
            new[name] = state[name].at[:, slots, :, :, :t].set(
                jnp.transpose(q8, (0, 1, 3, 4, 2)))
            new[name + "_scale"] = \
                state[name + "_scale"].at[:, slots, :, :t].set(
                    jnp.transpose(scale, (0, 1, 3, 2)))
    else:
        new["k"] = state["k"].at[:, slots, :t].set(
            k_all.astype(state["k"].dtype))
        new["v"] = state["v"].at[:, slots, :t].set(
            v_all.astype(state["v"].dtype))
    return new


def slot_admit(params, embed_table, heads, state, slot, prompt_x,
               req_key=None, length=None):
    """Prefill ``prompt_x`` (1, T, E) into slot ``slot`` — the B=1
    case of :func:`slot_admit_many` (one compiled program per prompt
    bucket T; the prefill cost scales with the bucket, not
    ``max_len``). ``req_key`` seeds the slot's sampling stream
    (ignored by greedy serving); ``length`` marks the true prompt
    length of a right-padded ``prompt_x``."""
    if req_key is None:
        req_key = jax.random.key(0)
    if length is None:
        length = prompt_x.shape[1]
    return slot_admit_many(
        params, embed_table, heads, state,
        jnp.reshape(jnp.asarray(slot, jnp.int32), (1,)), prompt_x,
        jnp.stack([req_key]),
        jnp.reshape(jnp.asarray(length, jnp.int32), (1,)))


def _slot_step(params, embed_table, heads, state, active,
               temperature=1.0, sample=False, top_k=0, span=None):
    """One decode step across ALL slots; ``active`` (S,) bool gates
    which slots advance (inactive slots' lanes are computed but their
    lengths/logits stay frozen and their emitted token is meaningless —
    the host filters by its own active set). Greedy by default;
    ``sample=True`` draws per slot from its own key stream
    (``fold_in(req_key, step)``) so a slot's sampled tokens equal
    ``generate(batch=1, key=req_key)``'s. Returns ``(state, emitted
    (S,))`` where ``emitted[s]`` is the token slot ``s`` generates THIS
    step — picked from the pre-step logits, matching ``generate``'s
    emission order (its first emitted token comes from the prefill
    logits).

    ``span`` (static, default ``max_len``) tiles the attended cache
    prefix: attention reads positions [0, span) only, so the per-step
    cost scales with the longest LIVE sequence (rounded up to
    ``SLOT_SPAN_TILE`` by the host) instead of ``max_len``. The host
    must pass ``span > max(lengths[active])`` — masked positions
    beyond a sequence's length contribute exact zeros, so any
    sufficient span produces identical tokens. Appends still write
    into the full-length cache. An inactive lane whose length reaches
    ``max_len`` keeps (harmlessly) rewriting the last position — its
    output is discarded and a re-admitted slot rewrites every position
    before attending to it."""
    slots = state["lengths"].shape[0]
    quantized = "k_scale" in state
    # head-major int8 layout keeps T minor; float layout keeps it at
    # axis 2 (see init_slot_state)
    max_len = state["k"].shape[-1] if quantized else state["k"].shape[2]
    if span is None or span > max_len:
        span = max_len
    lengths = state["lengths"]
    if sample:
        step_keys = jax.vmap(jax.random.fold_in)(state["req_key"],
                                                 state["step"])
        # inner shape (1, V): the SAME categorical shape generate's
        # batch-1 path draws, so the random bits match exactly
        tok_in = jax.vmap(
            lambda l, k: _pick_token(l[None], k, temperature, True,
                                     top_k)[0])(state["logits"],
                                                step_keys)
    else:
        tok_in = jnp.argmax(state["logits"], axis=-1)
    x = embed_table[tok_in][:, None, :]
    embed = x.shape[-1]
    # per-slot mask over the span: position p of slot s is visible iff
    # p <= length[s] (the new token attends to itself at index
    # length[s])
    visible = jnp.arange(span)[None, :] <= lengths[:, None]
    if quantized:
        mask_addend = jnp.where(visible, 0.0, -1e30).astype(jnp.float32)
        # python float (weak type): `q * inv_sqrt` must NOT promote a
        # bf16 q to f32 (see decode_step)
        inv_sqrt = (embed // heads) ** -0.5
    else:
        mask = visible[:, None, None, :]
    new_k, new_v = state["k"], state["v"]
    new_ks = state.get("k_scale")
    new_vs = state.get("v_scale")
    for i, blk in enumerate(params["blocks"]):
        q, k, v = _block_qkv(blk, x, heads)
        # per-slot append at each slot's own length. Unrolled
        # dynamic_update_slice per slot, NOT one scatter: XLA lowers a
        # multi-row scatter on TPU far worse than S in-place dus ops
        # (the single biggest cost of the pre-tiled slot step).
        if quantized:
            kq, ks = _quantize_kv(k)         # (S,1,H,D), (S,1,H)
            vq, vs = _quantize_kv(v)
            for s in range(slots):
                pos = lengths[s]
                new_k = lax.dynamic_update_slice(
                    new_k, jnp.transpose(kq[s:s + 1], (0, 2, 3, 1))[None],
                    (i, s, 0, 0, pos))
                new_v = lax.dynamic_update_slice(
                    new_v, jnp.transpose(vq[s:s + 1], (0, 2, 3, 1))[None],
                    (i, s, 0, 0, pos))
                new_ks = lax.dynamic_update_slice(
                    new_ks, jnp.transpose(ks[s:s + 1], (0, 2, 1))[None],
                    (i, s, 0, pos))
                new_vs = lax.dynamic_update_slice(
                    new_vs, jnp.transpose(vs[s:s + 1], (0, 2, 1))[None],
                    (i, s, 0, pos))
            att = int8_cache_attend(
                q * inv_sqrt,
                new_k[i, :, :, :, :span], new_ks[i, :, :, :span],
                new_v[i, :, :, :, :span], new_vs[i, :, :, :span],
                mask_addend)
        else:
            for s in range(slots):
                pos = lengths[s]
                new_k = lax.dynamic_update_slice(
                    new_k, k[s:s + 1][None].astype(new_k.dtype),
                    (i, s, pos, 0, 0))
                new_v = lax.dynamic_update_slice(
                    new_v, v[s:s + 1][None].astype(new_v.dtype),
                    (i, s, pos, 0, 0))
            att = _cache_attend(q, new_k[i][:, :span],
                                new_v[i][:, :span], mask)
        att = att.astype(x.dtype)
        x = x + matmul_any(att.reshape(slots, 1, embed),
                           blk["wout"]) + blk["bout"]
        x = _mlp(blk, x)
    logits = _head(params, x[:, 0]).astype(jnp.float32)
    new_state = dict(
        state, k=new_k, v=new_v,
        lengths=jnp.where(active, lengths + 1, lengths),
        logits=jnp.where(active[:, None], logits, state["logits"]),
        step=jnp.where(active, state["step"] + 1, state["step"]),
    )
    if quantized:
        new_state["k_scale"] = new_ks
        new_state["v_scale"] = new_vs
    return new_state, tok_in


def _slot_step_many(params, embed_table, heads, state, active, n,
                    temperature=1.0, sample=False, top_k=0, span=None):
    """``n`` lockstep ``slot_step``s as ONE ``lax.scan`` dispatch —
    the throughput mode: admission happens between chunks, so a
    high-RTT host pays one round trip per ``n`` tokens instead of per
    token. ``span`` (static) must cover the longest live sequence plus
    the whole chunk (each step appends one position). Returns
    ``(state, emitted (n, S))``; the host discards a slot's tail
    tokens past its budget/eos."""
    def body(state, _):
        state, emitted = _slot_step(params, embed_table, heads, state,
                                    active, temperature, sample, top_k,
                                    span=span)
        return state, emitted

    # named after the host-side "decode.dispatch" span (the profiler
    # alignment contract — observe/profile.py): the whole chunk scan
    # shows up as one labeled region in the XLA device trace
    with jax.named_scope("decode.dispatch"):
        return lax.scan(body, state, None, length=n)


# the single-chip jitted surface. One compiled program per (bucket,
# group) via the jit cache; the sharded layouts get their own jit
# objects with PINNED output shardings (sharded_slot_fns below), so a
# donated state can never drift off the canonical layout and defeat
# the cache.
slot_admit_many = functools.partial(
    jax.jit, static_argnames=("heads",),
    donate_argnames=("state",))(_slot_admit_many)
slot_step = functools.partial(
    jax.jit, static_argnames=("heads", "sample", "top_k", "span"),
    donate_argnames=("state",))(_slot_step)
slot_step_many = functools.partial(
    jax.jit, static_argnames=("heads", "n", "sample", "top_k", "span"),
    donate_argnames=("state",))(_slot_step_many)

# compile/cache-hit/FLOPs telemetry per slot program
# (observe/xla_stats.py): each name matches its host span and
# named_scope, so the veles_xla_* counters, the profiler timeline and
# the trace vocabulary line up. The wrappers delegate after one
# attribute check while device telemetry is off.
_generate_jit = instrument("decode.generate", _generate_jit)
slot_admit_many = instrument("decode.admit", slot_admit_many)
slot_step = instrument("decode.step", slot_step)
slot_step_many = instrument("decode.dispatch", slot_step_many)


def dispatch_program(fn, default):
    """The instrumented program name of a dispatch callable — the
    per-dispatch attribution key the request ledger records
    (``observe/reqledger.py``). ``instrument()`` stamps
    ``program_name`` on every wrapped slot program (live, sharded and
    paged alike); raw callables (a chaos monkeypatch, a bare jit) fall
    back to the call-family ``default`` so attribution never raises."""
    return getattr(fn, "program_name", default)


# -- dispatched-work accounting (observe/servescope.py) -----------------------

def admit_waste(bucket, lens, rows):
    """Token decomposition of ONE admission dispatch: ``lens`` live
    prompt/tail lengths prefilled into ``bucket``-position rows, the
    group padded to ``rows`` rows with duplicates. Returns
    ``(live, bucket_pad, group_dup)`` token counts — ONE definition
    for the serving goodput observatory and its tests, owned by the
    module that shapes the dispatch."""
    lens = [int(n) for n in lens]
    live = sum(lens)
    pad = sum(int(bucket) - n for n in lens)
    dup = (int(rows) - len(lens)) * int(bucket)
    return live, pad, dup


def span_overshoot_tokens(lens, span, chunk):
    """Masked attended positions PAST each live slot's sequence across
    one chunked decode dispatch: every lane-step attends ``span``
    positions, a slot at length ``n`` is live to ``n + i`` at step
    ``i`` — the rest is span-tile overshoot (exact zeros by the
    masking contract, but dispatched work all the same). Exact sum of
    ``max(0, span - (n + i))`` over ``i in 1..chunk`` per slot, in
    closed form."""
    span = int(span)
    chunk = int(chunk)
    total = 0
    for n in lens:
        d = span - int(n)
        k = min(chunk, max(0, d - 1))
        total += k * d - k * (k + 1) // 2
    return total


def page_overshoot_tokens(lens, pages, page_size, chunk):
    """The paged twin of :func:`span_overshoot_tokens`: each live slot
    gathers ``pages`` pages (``pages * page_size`` positions) per
    step, live to its sequence length — the rest is page-bucket
    overshoot (scratch rows and tail positions of partially-filled
    pages)."""
    return span_overshoot_tokens(lens, int(pages) * int(page_size),
                                 chunk)


def tile_pad_tokens(lens, page_size, chunk):
    """The fused-kernel residual: the paged-attention kernel
    (ops/paged_attention.py) walks only each slot's LIVE pages, so the
    span/page overshoot of the gather formulations is structurally
    zero — what remains is the dead tail of the last partial page,
    ``ceil((n + 1) / page_size) * page_size - (n + 1)`` lanes per
    slot-step for a slot live to ``n`` (position ``n`` itself is
    attended: append precedes attend). Exact sum over ``i in
    1..chunk`` with the slot live to ``n + i - 1`` at step ``i``."""
    ps = int(page_size)
    chunk = int(chunk)
    total = 0
    for n in lens:
        for i in range(1, chunk + 1):
            live = int(n) + i
            total += -(-live // ps) * ps - live
    return total


# -- tensor-parallel decode (Megatron-style weight sharding) ------------------

def _repack_block(blk, heads):
    """Host-side repack of one block into head-major layouts the TP
    specs can shard: qkv (E, 3E) → (E, 3, H, D) so each device owns
    whole heads (a flat column shard would give device 0 all the Q
    columns), out-proj (E, E) → (H, D, E) row-sharded by head."""
    embed = blk["wqkv"].shape[0]
    head_dim = embed // heads
    return dict(
        blk,
        wqkv=blk["wqkv"].reshape(embed, 3, heads, head_dim),
        bqkv=blk["bqkv"].reshape(3, heads, head_dim),
        wout=blk["wout"].reshape(heads, head_dim, embed),
    )


def _tp_specs(n_blocks, axis):
    """PartitionSpec pytree for the repacked params under ``axis``:
    whole heads and FFN columns shard; norms and biases that are added
    AFTER a psum stay replicated."""
    from jax.sharding import PartitionSpec as P

    block = {
        "ln1_w": P(), "ln1_b": P(),
        "wqkv": P(None, None, axis, None),
        "bqkv": P(None, axis, None),
        "wout": P(axis, None, None),
        "bout": P(),
        "ln2_w": P(), "ln2_b": P(),
        "w1": P(None, axis), "b1": P(axis),
        "w2": P(axis, None), "b2": P(),
    }
    return {"blocks": [dict(block) for _ in range(n_blocks)],
            "lnf_w": P(), "lnf_b": P(),
            "head": P(None, axis)}


def _tp_local_qkv(blk, x):
    """(B, S, E) → q, k, v each (B, S, h_local, D) from the device's
    head slice of the repacked qkv projection."""
    from veles_tpu.parallel.transformer_step import _ln

    h = _ln(x, blk["ln1_w"], blk["ln1_b"])
    qkv = jnp.einsum("bse,eihd->bsihd", h, blk["wqkv"]) + blk["bqkv"]
    return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]


def make_tp_generate(mesh, heads, n_tokens, axis="model"):
    """Tensor-parallel greedy decoding over ``mesh``'s ``axis``: every
    device holds a head slice of each attention block, a column/row
    slice of each FFN, and a vocab slice of the head — activations are
    replicated, the two per-block matmul reductions ``psum`` over ICI
    (the Megatron inference recipe). The KV cache shards over heads, so
    per-device cache HBM scales with H/n.

    Returns ``run(params, embed_table, prompt_tokens) -> tokens``; the
    params are the standard ``init_transformer_params`` pytree (repacked
    and sharded internally). Requires ``heads`` and the FFN hidden dim
    divisible by the axis size."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from veles_tpu.parallel.transformer_step import _ln

    n = mesh.shape[axis]

    def tp_mlp(blk, x):
        # the shared _mlp with the TP reduction injected: w1
        # col-sharded, w2 row-sharded, psum completes the contraction
        return _mlp(blk, x, reduce=lambda y: lax.psum(y, axis))

    def device_step(params, embed_table, cache, logits):
        """One decode step on each device's shard (inside shard_map)."""
        tok = jnp.argmax(logits, axis=-1)
        x = embed_table[tok][:, None, :]
        length = cache["length"]
        max_len = cache["k"].shape[2]
        mask = (jnp.arange(max_len) <= length)[None, None, None, :]
        new_k, new_v = cache["k"], cache["v"]
        for i, blk in enumerate(params["blocks"]):
            q, k, v = _tp_local_qkv(blk, x)
            new_k = lax.dynamic_update_slice(
                new_k, k[None].astype(new_k.dtype), (i, 0, length, 0, 0))
            new_v = lax.dynamic_update_slice(
                new_v, v[None].astype(new_v.dtype), (i, 0, length, 0, 0))
            # the SAME cache-attend the single-device decode_step runs
            att = _cache_attend(q, new_k[i], new_v[i], mask)
            # row-sharded out-projection: psum completes the contraction
            out = lax.psum(
                jnp.einsum("bqhd,hde->bqe", att.astype(x.dtype),
                           blk["wout"]), axis)
            x = x + out + blk["bout"]
            x = tp_mlp(blk, x)
        local_logits = _ln(x[:, 0], params["lnf_w"], params["lnf_b"]) \
            @ params["head"]
        logits = lax.all_gather(local_logits, axis, axis=1, tiled=True)
        return {"k": new_k, "v": new_v, "length": length + 1}, logits, tok

    def device_run(params, embed_table, prompt_x, cache):
        # prefill on the local head slice (full causal attention)
        batch, t, embed = prompt_x.shape
        x = prompt_x
        ks, vs = [], []
        for blk in params["blocks"]:
            q, k, v = _tp_local_qkv(blk, x)
            ks.append(k)
            vs.append(v)
            att = jax.nn.dot_product_attention(q, k, v, is_causal=True)
            out = lax.psum(
                jnp.einsum("bshd,hde->bse", att.astype(x.dtype),
                           blk["wout"]), axis)
            x = x + out + blk["bout"]
            x = tp_mlp(blk, x)
        local_logits = _ln(x[:, -1], params["lnf_w"], params["lnf_b"]) \
            @ params["head"]
        logits = lax.all_gather(local_logits, axis, axis=1, tiled=True)
        cache = {
            "k": lax.dynamic_update_slice(
                cache["k"], jnp.stack(ks).astype(cache["k"].dtype),
                (0, 0, 0, 0, 0)),
            "v": lax.dynamic_update_slice(
                cache["v"], jnp.stack(vs).astype(cache["v"].dtype),
                (0, 0, 0, 0, 0)),
            "length": jnp.int32(t),
        }

        def body(carry, _):
            cache, logits = carry
            cache, logits, tok = device_step(params, embed_table, cache,
                                             logits)
            return (cache, logits), tok

        (cache, logits), toks = lax.scan(body, (cache, logits), None,
                                         length=n_tokens)
        return jnp.swapaxes(toks, 0, 1)

    cache_spec = P(None, None, None, axis, None)
    param_specs = None  # built on first call (needs n_blocks)
    # the jitted program is memoized in the closure: jax.jit keys on
    # the callable's IDENTITY, and a fresh shard_map wrapper per run()
    # call would re-trace every generate (retrace.local-jit-dispatch)
    tp_fn = None

    def run(params, embed_table, prompt_tokens):
        nonlocal param_specs, tp_fn
        if isinstance(params["head"], dict):
            raise ValueError(
                "tensor-parallel decode takes unquantized params (the "
                "int8 tier is single-device serving; TP shards bf16)")
        n_blocks = len(params["blocks"])
        embed = embed_table.shape[1]
        head_dim = embed // heads
        if heads % n or (params["blocks"][0]["w1"].shape[1] % n) \
                or (embed_table.shape[0] % n):
            raise ValueError(
                "tensor-parallel decode needs heads (%d), ffn hidden "
                "(%d) and vocab (%d) divisible by the %r axis size %d"
                % (heads, params["blocks"][0]["w1"].shape[1],
                   embed_table.shape[0], axis, n))
        packed = {"blocks": [_repack_block(blk, heads)
                             for blk in params["blocks"]],
                  "lnf_w": params["lnf_w"], "lnf_b": params["lnf_b"],
                  "head": params["head"]}
        if param_specs is None:
            param_specs = _tp_specs(n_blocks, axis)
        batch, t = prompt_tokens.shape
        cache = init_kv_cache(n_blocks, batch, t + n_tokens, heads,
                              head_dim, dtype=embed_table.dtype)
        prompt_x = embed_table[prompt_tokens]
        cache_specs = {"k": cache_spec, "v": cache_spec,
                       "length": P()}
        # the TABLE is replicated (every device embeds the full token
        # vector); the VOCAB sharding lives in params["head"], whose
        # local logits all_gather back to full width
        if tp_fn is None:
            tp_fn = jax.jit(shard_map(
                device_run, mesh=mesh,
                in_specs=(param_specs, P(), P(), cache_specs),
                out_specs=P()))
        # place the shards explicitly (shard_map would otherwise
        # require pre-sharded inputs for non-replicated specs)
        packed = jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            packed, param_specs)
        table_sharded = jax.device_put(
            embed_table, NamedSharding(mesh, P()))
        cache = jax.tree.map(
            lambda a: jax.device_put(
                a, NamedSharding(
                    mesh, cache_spec if a.ndim == 5 else P())), cache)
        return tp_fn(packed, table_sharded, prompt_x, cache)

    return run


# -- mesh-sharded slot serving (layout path) ----------------------------------
#
# The continuous-batching engine above goes multi-chip by LAYOUT, not by
# a second implementation: the params shard tensor-parallel over the
# mesh's ``model`` axis, the slot KV slab shards over its HEADS dim,
# and the ONE copy of the slot math (slot_admit_many / slot_step /
# slot_step_many) runs unchanged — XLA's SPMD partitioner splits the
# sharded matmuls and the head-sharded cache ops along the operand
# shardings and inserts the psum/all-gather collectives. Token streams
# stay identical to the single-chip engine (the collectives only
# reassociate reductions, below token granularity — the same contract
# the TP generate tests pin). One compiled program exists per
# (bucket, group, layout): jit specializes on operand shardings, so the
# instrument() compile counters and the dispatch-count CI hooks keep
# working per layout. docs/sharded_serving.md is the recipe.
#
# Known layout cost vs the hand-written make_tp_generate partition: the
# fused qkv matrix (E, 3E) shards by FLAT columns, whose chunk
# boundaries straddle the q/k/v and head boundaries — the partitioner
# then reshards the (small) qkv activation around the per-head
# reshape/split instead of handing each device whole heads. Fixing it
# needs the head-major repack _repack_block does, i.e. a repacked
# variant of the shared sublayer math — a measured follow-on, not a
# spec change (tracked in docs/sharded_serving.md Limits).

def validate_slot_mesh(mesh, heads, params, embed_table, axis="model"):
    """Fail a bad serving mesh at build time with an error naming the
    offending dimension — never as an opaque partitioner error from
    inside the first admit dispatch."""
    n = dict(mesh.shape).get(axis, 1)
    if n <= 1:
        return n
    blk = params["blocks"][0]
    w1 = blk["w1"]["q8"] if isinstance(blk["w1"], dict) else blk["w1"]
    ffn_hidden = w1.shape[1]
    vocab = embed_table.shape[0]
    if heads % n or ffn_hidden % n or vocab % n:
        raise ValueError(
            "sharded slot serving needs heads (%d), ffn hidden (%d) "
            "and vocab (%d) divisible by the %r axis size %d"
            % (heads, ffn_hidden, vocab, axis, n))
    return n


def slot_param_specs(params, axis="model"):
    """PartitionSpec pytree (same structure as ``params``) for
    tensor-parallel slot serving: attention qkv/FFN-up columns and the
    vocab head shard over ``axis``, out-proj/FFN-down rows shard over
    ``axis``, norms and post-reduction biases replicate. int8-quantized
    leaves (``{"q8", "scale"}``) shard the payload like the float
    matrix; per-output-column scales follow their columns."""
    from jax.sharding import PartitionSpec as P

    def mat(leaf, spec, scale_spec):
        if isinstance(leaf, dict):
            return {"q8": spec, "scale": scale_spec}
        return spec

    blocks = []
    for blk in params["blocks"]:
        specs = {
            "ln1_w": P(), "ln1_b": P(),
            "wqkv": mat(blk["wqkv"], P(None, axis), P(axis)),
            "bqkv": P(axis),
            "wout": mat(blk["wout"], P(axis, None), P()),
            "bout": P(),
            "ln2_w": P(), "ln2_b": P(),
            "w1": mat(blk["w1"], P(None, axis), P(axis)),
            "b1": P(axis),
            "w2": mat(blk["w2"], P(axis, None), P()),
            "b2": P(),
        }
        blocks.append(specs)
    return {"blocks": blocks, "lnf_w": P(), "lnf_b": P(),
            "head": mat(params["head"], P(None, axis), P(axis))}


def slot_state_specs(quantized=False, axis="model"):
    """PartitionSpec dict for the slot state: the KV slab (and the
    int8 tier's scales) shard over their HEADS dim, control leaves
    (lengths/logits/req_key/step) replicate."""
    from jax.sharding import PartitionSpec as P

    if quantized:
        kv = P(None, None, axis, None, None)   # (L, S, H, D, T)
        scale = P(None, None, axis, None)      # (L, S, H, T)
        extra = {"k_scale": scale, "v_scale": scale}
    else:
        kv = P(None, None, None, axis, None)   # (L, S, T, H, D)
        extra = {}
    return dict({"k": kv, "v": kv, "lengths": P(), "logits": P(),
                 "req_key": P(), "step": P()}, **extra)


def shard_slot_tree(tree, mesh, specs):
    """``device_put`` a pytree into ``mesh`` under a matching spec
    pytree (fresh placement — callers moving LIVE state between
    layouts use ``parallel/reshard.reshard``, which rides collectives
    and is measured)."""
    from jax.sharding import NamedSharding

    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: not isinstance(x, (dict, list, tuple)))
    return jax.device_put(tree, shardings)


def shard_slot_params(params, embed_table, heads, mesh, axis="model"):
    """Place decode params + embed table into the serving layout:
    params tensor-parallel over ``axis``, table replicated. Returns
    ``(params, embed_table)``; validates divisibility first."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    validate_slot_mesh(mesh, heads, params, embed_table, axis=axis)
    params = shard_slot_tree(params, mesh, slot_param_specs(params, axis))
    return params, jax.device_put(embed_table, NamedSharding(mesh, P()))


#: (mesh, axis, quantized) -> (admit, step, step_many) jit objects with
#: the state's output shardings PINNED to the canonical serving layout.
#: Without the pin, the compiler is free to hand a donated state back
#: in whatever layout the last program preferred — the next call then
#: misses the jit cache and every admit recompiles (a recompile storm
#: by construction). One entry per layout keeps the compile count at
#: one program per (bucket, group, mesh), which is what the
#: dispatch-count and storm regression tests assert — so the
#: check-then-insert is LOCKED: two tiers of the same layout built
#: concurrently (a bf16 and an int8 GenerateAPI, a breaker rebuild
#: racing a new API) must share one jit object, not compile twice.
_SHARDED_SLOT_FNS = {}
_SHARDED_SLOT_LOCK = threading.Lock()


# -- AOT wire format (veles_tpu/aot/) -----------------------------------------
#
# jax.export's flatbuffer schema cannot serialize extended PRNG-key
# dtypes (key<fry>), so every program crossing the AOT artifact boundary
# carries the slot state's ``req_key`` leaf — and the admit path's
# ``req_keys`` operand — as raw uint32 key DATA. ``wrap_key_data``/
# ``key_data`` are bit-level reinterpretations, so wire-format streams
# stay bit-identical to the live programs' (tests/test_aot.py pins it).
# One copy of the convention here, next to the state definition; the
# paged state (parallel/kv_pool.py) shares the leaf name so the same
# helpers serve both engines.

def wire_slot_state(state):
    """Slot/paged state with the ``req_key`` leaf as raw uint32 data —
    the calling convention of every exported slot program."""
    import jax

    return dict(state, req_key=jax.random.key_data(state["req_key"]))


def unwire_slot_state(state):
    """Invert :func:`wire_slot_state`: re-wrap the raw key data into
    the typed PRNG keys the live jit surface expects."""
    import jax

    return dict(state,
                req_key=jax.random.wrap_key_data(state["req_key"]))


def sharded_slot_fns(mesh, mesh_axis="model", quantized=False):
    """The sharded slot engine's jitted call surface: the SAME raw
    functions as the single-chip ``slot_admit_many``/``slot_step``/
    ``slot_step_many`` (one copy of the math — the bit-identity
    contract), jitted per layout with the state outputs pinned to
    :func:`slot_state_specs` and the emitted tokens replicated.
    Instrumented under the same program names, so the veles_xla_*
    counters, profiler spans and flight-recorder vocabulary are
    layout-blind."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    key = (mesh, mesh_axis, bool(quantized))
    with _SHARDED_SLOT_LOCK:
        fns = _SHARDED_SLOT_FNS.get(key)
    if fns is not None:
        return fns
    state_sh = {
        name: NamedSharding(mesh, spec)
        for name, spec in slot_state_specs(quantized,
                                           axis=mesh_axis).items()}
    replicated = NamedSharding(mesh, P())
    admit = instrument("decode.admit", jax.jit(
        _slot_admit_many, static_argnames=("heads",),
        donate_argnames=("state",), out_shardings=state_sh))
    step = instrument("decode.step", jax.jit(
        _slot_step,
        static_argnames=("heads", "sample", "top_k", "span"),
        donate_argnames=("state",),
        out_shardings=(state_sh, replicated)))
    step_many = instrument("decode.dispatch", jax.jit(
        _slot_step_many,
        static_argnames=("heads", "n", "sample", "top_k", "span"),
        donate_argnames=("state",),
        out_shardings=(state_sh, replicated)))
    fns = (admit, step, step_many)
    with _SHARDED_SLOT_LOCK:
        # a racing builder may have won; keep ITS jit objects (their
        # compiled programs are already cached)
        fns = _SHARDED_SLOT_FNS.setdefault(key, fns)
    return fns
