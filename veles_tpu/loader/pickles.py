"""Pickled-dataset loader.

TPU-native re-design of reference ``veles/loader/pickles.py:55-148``: each
sample class is fed by a list of pickle files; every pickle holds either a
``(data, labels)`` tuple, a ``{"data": ..., "labels": ...}`` dict, or a
bare sample array (no labels). Per-class arrays are concatenated and handed
to the device-resident FullBatchLoader machinery, so after load the gather
path is identical to any other full-batch dataset.

``reshape``/``transform_data`` hooks mirror the reference's subclass
extension points (``pickles.py:79-84``).
"""

import pickle

import numpy

from veles_tpu.loader.base import register_loader
from veles_tpu.loader.fullbatch import FullBatchLoader


@register_loader("pickles")
class PicklesLoader(FullBatchLoader):
    """Samples from per-class pickle file lists (reference
    ``PicklesLoader``, ``pickles.py:55``)."""

    def __init__(self, workflow, **kwargs):
        self.test_pickles = list(kwargs.pop("test_pickles", []))
        self.validation_pickles = list(kwargs.pop("validation_pickles", []))
        self.train_pickles = list(kwargs.pop("train_pickles", []))
        super().__init__(workflow, **kwargs)

    # -- extension hooks (reference pickles.py:79-84) -------------------------
    def reshape(self, shape):
        return shape

    def transform_data(self, data):
        return data

    @staticmethod
    def _split_payload(payload):
        if isinstance(payload, dict):
            return payload["data"], payload.get("labels")
        if isinstance(payload, (tuple, list)) and len(payload) == 2:
            return payload
        return payload, None

    def load_data(self):
        per_class_data, per_class_labels = [], []
        has_labels = None
        for pickles in (self.test_pickles, self.validation_pickles,
                        self.train_pickles):
            datas, labels = [], []
            for path in pickles:
                with open(path, "rb") as fin:
                    data, labs = self._split_payload(pickle.load(fin))
                data = numpy.asarray(data)
                if has_labels is not None and (labs is not None) \
                        != has_labels:
                    raise ValueError(
                        "%s: some pickles have labels and some do not"
                        % self.name)
                has_labels = labs is not None
                datas.append(self.transform_data(
                    numpy.asarray(data, numpy.float32)))
                if labs is not None:
                    labels.append(numpy.asarray(labs))
            per_class_data.append(
                numpy.concatenate(datas) if datas else None)
            per_class_labels.append(
                numpy.concatenate(labels) if labels else None)
        shapes = {d.shape[1:] for d in per_class_data if d is not None}
        if len(shapes) > 1:
            raise ValueError("%s: sample shapes differ between classes: %s"
                             % (self.name, sorted(shapes)))
        if not shapes:
            raise ValueError("%s: no pickles given" % self.name)
        lengths = [0 if d is None else len(d) for d in per_class_data]
        data = numpy.concatenate(
            [d for d in per_class_data if d is not None])
        shape = self.reshape(data.shape[1:])
        self._provided_data = data.reshape((len(data),) + tuple(shape))
        if has_labels:
            self._provided_labels = numpy.concatenate(
                [l for l in per_class_labels if l is not None])
        self._provided_lengths = lengths
        super().load_data()
