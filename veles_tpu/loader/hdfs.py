"""HDFS text streaming loader (reference ``loader/hdfs_loader.py:48-77``).

The reference used the snakebite native-protocol client; that requires a
protobuf RPC stack. The TPU rebuild speaks **WebHDFS** — the REST API
every Hadoop namenode serves — via stdlib ``urllib`` only, so the loader
works in any environment without extra dependencies.

Contract (matching the reference unit exactly):

- ``HDFSTextLoader(wf, file="/path", address="namenode:50070",
  chunk=1000)`` streams the file as text lines;
- each ``run()`` fills ``output`` (a list of ``chunk`` lines) with the
  next chunk and raises the ``finished`` Bool at EOF;
- ``initialize()`` stats the file (existence/permission check up front).

The namenode may redirect OPEN to a datanode (standard WebHDFS flow);
``urllib`` follows it automatically.
"""

import json
import urllib.parse
import urllib.request

from veles_tpu.core.distributable import TriviallyDistributable
from veles_tpu.core.mutable import Bool
from veles_tpu.core.units import Unit


class HDFSTextLoader(Unit, TriviallyDistributable):
    """Streams a text file from HDFS in fixed-size line chunks."""

    def __init__(self, workflow, **kwargs):
        self.file_name = kwargs.pop("file")
        self.chunk_lines_number = kwargs.pop("chunk", 1000)
        address = kwargs.pop("address", "localhost:9870")
        self.user = kwargs.pop("user", None)
        self.encoding = kwargs.pop("encoding", "utf-8")
        #: a hung namenode/datanode must not block the workflow forever
        self.timeout = kwargs.pop("timeout", 60.0)
        super().__init__(workflow, **kwargs)
        #: lines already served — pickled with the unit so a snapshot
        #: resume re-opens the stream past the consumed prefix instead
        #: of re-serving it from offset 0
        self.lines_consumed = 0
        self.base_url = ("http://%s/webhdfs/v1" % address
                         if "://" not in address
                         else address.rstrip("/") + "/webhdfs/v1")
        self.output = [""] * self.chunk_lines_number
        self.finished = Bool(False)

    def _url(self, op):
        query = {"op": op}
        if self.user:
            query["user.name"] = self.user
        return "%s%s?%s" % (self.base_url,
                            urllib.parse.quote(self.file_name),
                            urllib.parse.urlencode(query))

    def stat(self):
        """GETFILESTATUS — size/type/permission metadata."""
        with urllib.request.urlopen(self._url("GETFILESTATUS"),
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))["FileStatus"]

    def initialize(self, **kwargs):
        status = self.stat()
        self.debug("opened %s (%d bytes)", self.file_name,
                   status.get("length", -1))
        self._response_ = urllib.request.urlopen(self._url("OPEN"),
                                                 timeout=self.timeout)
        self._generator_ = (line.rstrip("\n") for line in
                            (raw.decode(self.encoding)
                             for raw in self._response_))
        for _ in range(self.lines_consumed):
            # skip the prefix a restored snapshot already served (OPEN
            # has a byte offset= parameter, but line counting is what
            # the unit actually tracks)
            next(self._generator_, None)

    def init_unpickled(self):
        super().init_unpickled()
        self._response_ = None
        self._generator_ = None

    def run(self):
        assert not self.finished
        filled = 0
        try:
            for i in range(self.chunk_lines_number):
                self.output[i] = next(self._generator_)
                filled += 1
                self.lines_consumed += 1
        except StopIteration:
            # truncate to the valid lines: the stale tail of the previous
            # chunk must not be served as data (consumers iterate output)
            del self.output[filled:]
            self.finished.set()
            self._response_.close()
