"""HDF5 dataset loaders.

TPU-native re-design of reference ``veles/loader_hdf5.py:48-151``: one HDF5
file per sample class with a ``data`` dataset and an optional ``label``
dataset.

Two tiers, same split as the reference:

- :class:`FullBatchHDF5Loader` — reads everything into the device-resident
  full-batch path (the common case; minibatch gather happens in-jit);
- :class:`HDF5Loader` — streaming: keeps the h5py datasets open and reads
  minibatch rows on demand, for datasets larger than HBM+host RAM. Rows
  are fetched per shuffled index on the host, so this path trades
  throughput for footprint exactly like the reference's non-fullbatch
  variant.
"""

import numpy

import jax.numpy as jnp

from veles_tpu.loader.base import (Loader, TEST, VALID, TRAIN,
                                   register_loader)
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.normalization import make_normalizer


def _open_class_file(path, expect_labels):
    """Open one class file, returning (h5file, data, labels)."""
    import h5py
    h5f = h5py.File(path, "r")
    data = h5f["data"]
    labels = h5f["label"] if "label" in h5f else None
    if expect_labels is not None and (labels is None) == expect_labels:
        h5f.close()
        raise ValueError("%s: some class files have labels and some do not"
                         % path)
    if labels is not None and len(labels) != len(data):
        h5f.close()
        raise ValueError("%s: data and label lengths differ" % path)
    return h5f, data, labels


class HDF5PathsMixin:
    def _pop_paths(self, kwargs):
        self.class_paths = (kwargs.pop("test_path", None),
                            kwargs.pop("validation_path", None),
                            kwargs.pop("train_path", None))


@register_loader("full_batch_hdf5")
class FullBatchHDF5Loader(HDF5PathsMixin, FullBatchLoader):
    """Whole HDF5 dataset resident on device (reference
    ``FullBatchHDF5Loader``, ``loader_hdf5.py:127-151``)."""

    def __init__(self, workflow, **kwargs):
        self._pop_paths(kwargs)
        super().__init__(workflow, **kwargs)

    def load_data(self):
        datas, labels, lengths = [], [], []
        expect_labels = None
        for path in self.class_paths:
            if not path:
                lengths.append(0)
                continue
            h5f, data, labs = _open_class_file(path, expect_labels)
            expect_labels = labs is not None
            lengths.append(len(data))
            # copy out, then close — nothing references the live handles
            datas.append(numpy.asarray(data[:], numpy.float32))
            if labs is not None:
                labels.append(numpy.asarray(labs[:]))
            h5f.close()
        if not datas:
            raise ValueError("%s: no HDF5 paths given" % self.name)
        self._provided_data = numpy.concatenate(datas)
        self._provided_labels = (numpy.concatenate(labels)
                                 if labels else None)
        self._provided_lengths = lengths
        super().load_data()


@register_loader("hdf5")
class HDF5Loader(HDF5PathsMixin, Loader):
    """Streaming HDF5 loader: rows fetched from disk per minibatch
    (reference ``HDF5Loader``, ``loader_hdf5.py:94-124``)."""

    def __init__(self, workflow, **kwargs):
        self._pop_paths(kwargs)
        self.normalization_type = kwargs.pop("normalization_type", "none")
        self.normalization_parameters = kwargs.pop(
            "normalization_parameters", {})
        super().__init__(workflow, **kwargs)
        self.normalizer = None
        self.sample_shape = None

    def init_unpickled(self):
        super().init_unpickled()
        self._datasets_ = [None, None, None]
        self._h5_files_ = []

    def stop(self):
        for h5f in self._h5_files_:
            try:
                h5f.close()
            except Exception:
                pass
        self._h5_files_ = []

    def load_data(self):
        expect_labels = None
        self._raw_labels = None
        raw_label_parts = []
        for klass, path in enumerate(self.class_paths):
            if not path:
                continue
            h5f, data, labs = _open_class_file(path, expect_labels)
            self._h5_files_.append(h5f)
            expect_labels = labs is not None
            self._datasets_[klass] = (data, labs)
            self.class_lengths[klass] = len(data)
            if labs is not None:
                raw_label_parts.append(numpy.asarray(labs[:]))
            shape = tuple(data.shape[1:])
            if self.sample_shape not in (None, shape):
                raise ValueError("%s: class sample shapes differ"
                                 % self.name)
            self.sample_shape = shape
        if raw_label_parts:
            self._raw_labels = numpy.concatenate(raw_label_parts)
        self.normalizer = make_normalizer(self.normalization_type,
                                          **self.normalization_parameters)
        if not self.normalizer.STATELESS:
            # analyze streams over the train split in minibatch-size blocks
            data, _ = self._datasets_[TRAIN] or (None, None)
            if data is not None:
                step = max(1, self.max_minibatch_size)
                for start in range(0, len(data), step):
                    self.normalizer.analyze(
                        numpy.asarray(data[start:start + step],
                                      numpy.float32))

    def get_raw_labels(self):
        return self._raw_labels

    def create_minibatch_data(self):
        size = self.max_minibatch_size
        self.minibatch_data.reset(numpy.zeros(
            (size,) + self.sample_shape, numpy.float32))
        if self._raw_labels is not None:
            self.minibatch_labels.reset(numpy.zeros(size, numpy.int32))
        self.minibatch_indices.reset(numpy.zeros(size, numpy.int64))
        self.sample_mask.reset(numpy.zeros(size, numpy.float32))

    def _row(self, global_index):
        for klass in (TEST, VALID, TRAIN):
            offset = self.class_offset(klass)
            if global_index < offset + self.class_lengths[klass]:
                return klass, global_index - offset
        raise IndexError(global_index)

    def fill_minibatch(self, indices, valid):
        batch = numpy.zeros(self.minibatch_data.shape, numpy.float32)
        labels = numpy.zeros(len(indices), numpy.int32)
        for i, gi in enumerate(indices[:valid]):
            klass, row = self._row(int(gi))
            data, labs = self._datasets_[klass]
            batch[i] = data[row]
            if labs is not None:
                labels[i] = self.labels_mapping.get(
                    labs[row], labs[row]) if self.labels_mapping \
                    else labs[row]
        batch = self.normalizer.apply_batch(numpy, batch)
        mask = (numpy.arange(len(indices)) < valid).astype(numpy.float32)
        self.minibatch_data.data = jnp.asarray(batch)
        if self._raw_labels is not None:
            self.minibatch_labels.data = jnp.asarray(labels)
        self.sample_mask.data = jnp.asarray(mask)
        self.minibatch_indices.data = jnp.asarray(indices)
