"""Streaming ingestion loader.

TPU-native re-design of reference ``veles/zmq_loader.py:47-74``
(ZeroMQLoader): external producers push work items into the training
process over the network; the loader serves them as TEST minibatches as
they arrive. The ZeroMQ PULL socket becomes an asyncio TCP listener
speaking the fleet wire protocol — length-prefixed pickled frames behind
the same shared-secret HMAC (``fleet/protocol.py``), so untrusted peers
never reach ``pickle.loads``.

Producer side: :class:`StreamFeeder` connects and ``push()``es numpy
arrays (optionally in batches).
"""

import asyncio
import queue
import threading

import numpy

import jax.numpy as jnp

from veles_tpu.core.mutable import Bool
from veles_tpu.fleet.protocol import (read_frame, resolve_secret,
                                      write_frame)
from veles_tpu.loader.base import Loader, TEST, register_loader


@register_loader("stream")
class StreamLoader(Loader):
    """Serve minibatches from a network-fed queue (reference
    ``ZeroMQLoader``)."""

    def __init__(self, workflow, **kwargs):
        self.sample_shape = tuple(kwargs.pop("sample_shape", ()))
        self.listen_address = kwargs.pop("listen_address", "127.0.0.1:0")
        self.queue_maxsize = kwargs.pop("queue_maxsize", 1024)
        secret = kwargs.pop("secret", None)
        super().__init__(workflow, **kwargs)
        self.complete = Bool(False)
        self._secret = resolve_secret(workflow, secret)
        self.port = None

    def init_unpickled(self):
        super().init_unpickled()
        self._queue_ = queue.Queue(maxsize=self.queue_maxsize)
        self._loop_ = None
        self._thread_ = None

    # -- ILoader --------------------------------------------------------------
    def load_data(self):
        if not self.sample_shape:
            raise ValueError("%s: set sample_shape=" % self.name)
        self.class_lengths = [self.max_minibatch_size, 0, 0]
        self._start_listener()

    def create_minibatch_data(self):
        mb = self.max_minibatch_size
        self.minibatch_data.reset(numpy.zeros(
            (mb,) + self.sample_shape, numpy.float32))
        self.minibatch_indices.reset(numpy.zeros(mb, numpy.int64))
        self.sample_mask.reset(numpy.zeros(mb, numpy.float32))

    def fill_minibatch(self, indices, valid):
        raise AssertionError("StreamLoader overrides run()")

    # -- listener -------------------------------------------------------------
    def _start_listener(self):
        host, _, port = self.listen_address.rpartition(":")
        ready = threading.Event()

        def run_loop():
            self._loop_ = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop_)
            server = self._loop_.run_until_complete(asyncio.start_server(
                self._handle_producer, host or "127.0.0.1", int(port)))
            self.port = server.sockets[0].getsockname()[1]
            ready.set()
            self._loop_.run_forever()
            server.close()

        self._thread_ = threading.Thread(target=run_loop, daemon=True,
                                         name="stream-loader")
        self._thread_.start()
        ready.wait()
        self.info("stream loader listening on port %d", self.port)

    async def _handle_producer(self, reader, writer):
        try:
            while True:
                msg = await read_frame(reader, self._secret)
                mtype = msg.get("type")
                if mtype == "push":
                    # never block the event loop: a full queue answers
                    # "busy" with the accepted count (producer-side
                    # backpressure), instead of stalling acks/end frames
                    accepted = 0
                    busy = False
                    for sample in msg["samples"]:
                        try:
                            self._queue_.put_nowait(
                                numpy.asarray(sample, numpy.float32))
                            accepted += 1
                        except queue.Full:
                            busy = True
                            break
                    await write_frame(
                        writer,
                        {"type": "busy" if busy else "ack",
                         "accepted": accepted}, self._secret)
                elif mtype == "end":
                    try:
                        self._queue_.put_nowait(None)
                    except queue.Full:
                        self.complete.set(True)
                    await write_frame(writer, {"type": "ack"},
                                      self._secret)
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    # -- serving --------------------------------------------------------------
    def run(self):
        """Block for the first queued sample, then drain greedily up to
        one minibatch (latency for the first, throughput for bursts)."""
        mb = self.max_minibatch_size
        batch = numpy.zeros((mb,) + self.sample_shape, numpy.float32)
        first = self._queue_.get()
        if first is None:
            self.complete.set(True)
            return
        batch[0] = first
        n = 1
        while n < mb:
            try:
                sample = self._queue_.get_nowait()
            except queue.Empty:
                break
            if sample is None:
                self.complete.set(True)
                break
            batch[n] = sample
            n += 1
        self.minibatch_class = TEST
        self.minibatch_valid_size = n
        self.minibatch_data.data = jnp.asarray(batch)
        self.sample_mask.data = jnp.asarray(
            (numpy.arange(mb) < n).astype(numpy.float32))
        self.samples_served += n

    def stop(self):
        self.complete.set(True)
        try:  # wake a blocked run(); never block the caller
            self._queue_.put_nowait(None)
        except queue.Full:
            pass
        if self._loop_ is not None and self._loop_.is_running():
            self._loop_.call_soon_threadsafe(self._loop_.stop)


class StreamFeeder:
    """Producer-side client: push numpy samples into a StreamLoader."""

    def __init__(self, address, secret=None, workflow=None):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self._secret = resolve_secret(workflow, secret)
        self._loop = asyncio.new_event_loop()
        self._reader, self._writer = self._loop.run_until_complete(
            asyncio.open_connection(self.host, self.port))

    def _call(self, msg):
        async def roundtrip():
            await write_frame(self._writer, msg, self._secret)
            return await read_frame(self._reader, self._secret)

        return self._loop.run_until_complete(roundtrip())

    def push(self, *samples):
        """Returns the loader's reply: ``{"type": "ack"|"busy",
        "accepted": n}`` — on "busy" retry the samples beyond
        ``accepted`` after a pause (consumer-side queue full)."""
        return self._call({"type": "push",
                           "samples": [numpy.asarray(s, numpy.float32)
                                       for s in samples]})

    def end(self):
        try:
            return self._call({"type": "end"})
        finally:
            self._writer.close()
            self._loop.close()
