"""Feature normalizer family with a name registry.

TPU-native re-design of reference ``veles/normalization.py:110-636``. The
reference normalizers are *stateful objects* that mutate numpy arrays in
place; here they are stateful only in their accumulated statistics
(``analyze``) while ``normalize``/``denormalize`` are **functional** — they
return new arrays — because in-place mutation is meaningless for jax.Arrays.

Every normalizer also exposes ``apply_batch(xp, batch)``: the same
normalization expressed over an array-namespace parameter (``numpy`` or
``jax.numpy``), so the FullBatchLoader's jitted fill applies normalization
*inside* the XLA computation (the reference instead shipped a dedicated
``mean_disp_normalizer`` GPU kernel — XLA fuses the equivalent for free).

Registry semantics follow the reference ``NormalizerRegistry`` metaclass
(``normalization.py:110-121``): every concrete class with a ``MAPPING`` name
is registered and constructible via :func:`make_normalizer`.

The eight reference types, with their accumulation semantics
(``normalization.py`` line anchors in each class docstring):

========== =====================================================
name        behavior
========== =====================================================
none        identity
mean_disp   subtract global mean, divide by (max - min)
linear      samplewise rescale of [min, max] to an interval
range_linear like linear but the global range is fixed at init
exp         samplewise softmax
pointwise   per-feature rescale of accumulated [min, max] to [-1, 1]
external_mean subtract a mean sample loaded from a file
internal_mean subtract the accumulated global mean sample
========== =====================================================
"""

import pickle

import numpy

#: MAPPING name -> class (reference NormalizerRegistry.normalizers).
normalizer_registry = {}


def register_normalizer(cls):
    assert cls.MAPPING, "normalizer must define MAPPING"
    normalizer_registry[cls.MAPPING] = cls
    return cls


def make_normalizer(name, **kwargs):
    """Instantiate a registered normalizer by MAPPING name."""
    try:
        cls = normalizer_registry[name]
    except KeyError:
        raise ValueError(
            "unknown normalization type %r (have: %s)"
            % (name, ", ".join(sorted(normalizer_registry))))
    return cls(**kwargs)


def _feature_axes(batch):
    return tuple(range(1, batch.ndim))


class NormalizerBase:
    """Base contract (reference ``normalization.py:124``): ``analyze(data)``
    accumulates statistics over (possibly several) train-set passes;
    ``normalize(data)`` returns the normalized copy; ``denormalize`` inverts
    it. ``state``/``state=`` round-trip everything for snapshots."""

    MAPPING = None
    #: stateless normalizers need no analyze() before normalize()
    STATELESS = False
    #: False when denormalize() needs per-call stats (samplewise types) —
    #: such types cannot be MSE target normalizers (loader/base.py)
    INVERTIBLE_FROM_STATE = True

    def __init__(self, state=None, **kwargs):
        self._initialized = False
        if state is not None:
            if not isinstance(state, dict):
                raise TypeError("state must be a dict")
            self.__dict__.update(state)
            self._initialized = True

    # -- accumulation -------------------------------------------------------
    def analyze(self, data):
        data = numpy.asarray(data)
        if not self._initialized:
            self._initialize(data)
            self._initialized = True
        self._analyze(data)

    def _initialize(self, data):
        pass

    def _analyze(self, data):
        pass

    @property
    def is_initialized(self):
        return self._initialized or self.STATELESS

    def reset(self):
        self._initialized = False

    @property
    def state(self):
        """Everything needed to reconstruct via ``cls(state=...)``."""
        return {k: v for k, v in self.__dict__.items()
                if k != "_initialized" and not callable(v)}

    def analyze_and_normalize(self, data):
        self.analyze(data)
        return self.normalize(data)

    # -- application --------------------------------------------------------
    def _require_initialized(self):
        if not self.is_initialized:
            raise RuntimeError(
                "%s.normalize() before analyze()" % type(self).__name__)

    def normalize(self, data):
        self._require_initialized()
        return self.apply_batch(numpy, numpy.asarray(data, numpy.float32))

    def denormalize(self, data, **kwargs):
        raise NotImplementedError

    def jit_state(self):
        """Coefficients as a flat dict of arrays/scalars — the traced
        inputs of the fused tick's normalization stage, so changing
        datasets never retraces (``parallel/fused.py``)."""
        return {}

    @classmethod
    def apply_state(cls, xp, batch, state):
        """Pure normalization over array namespace ``xp`` (numpy on host,
        jax.numpy inside jit) using only ``state`` — no instance data."""
        raise NotImplementedError

    def apply_batch(self, xp, batch):
        """Normalize ``batch`` (leading axis = samples) with this
        instance's accumulated coefficients."""
        return self.apply_state(xp, batch, self.jit_state())


@register_normalizer
class NoneNormalizer(NormalizerBase):
    """Identity (reference ``normalization.py:496``)."""

    MAPPING = "none"
    STATELESS = True

    @classmethod
    def apply_state(cls, xp, batch, state):
        return batch

    def denormalize(self, data, **kwargs):
        return numpy.asarray(data)


@register_normalizer
class MeanDispersionNormalizer(NormalizerBase):
    """Subtract the accumulated global mean and divide by (max - min); note
    "dispersion" here is the range, not the statistical variance (reference
    ``normalization.py:284-318``). Accumulates in float64 to dodge float32
    saturation on large sets."""

    MAPPING = "mean_disp"

    def _initialize(self, data):
        self._sum = numpy.zeros_like(data[0], dtype=numpy.float64)
        self._count = 0
        self._min = numpy.array(data[0], dtype=numpy.float64)
        self._max = numpy.array(data[0], dtype=numpy.float64)

    def _analyze(self, data):
        self._count += data.shape[0]
        self._sum += numpy.sum(data, axis=0, dtype=numpy.float64)
        numpy.minimum(self._min, numpy.min(data, axis=0), self._min)
        numpy.maximum(self._max, numpy.max(data, axis=0), self._max)

    @property
    def coefficients(self):
        mean = (self._sum / self._count).astype(numpy.float32)
        disp = (self._max - self._min).astype(numpy.float32)
        disp[disp == 0] = 1.0
        return mean, disp

    def jit_state(self):
        mean, disp = self.coefficients
        return {"mean": mean, "disp": disp}

    @classmethod
    def apply_state(cls, xp, batch, state):
        return (batch - state["mean"]) / state["disp"]

    def denormalize(self, data, **kwargs):
        mean, disp = self.coefficients
        return numpy.asarray(data) * disp + mean


class IntervalMixin:
    """Target-interval validation shared by linear normalizers (reference
    ``normalization.py:322-344``)."""

    def _set_interval(self, value):
        try:
            vmin, vmax = value
        except (TypeError, ValueError):
            raise ValueError("interval must consist of two values")
        for v in (vmin, vmax):
            if not isinstance(v, (int, float)):
                raise TypeError("interval bounds must be numbers")
        self.interval = (float(vmin), float(vmax))


@register_normalizer
class LinearNormalizer(IntervalMixin, NormalizerBase):
    """Samplewise rescale: each sample's own [min, max] maps to the target
    interval (reference ``normalization.py:347-395``). Stateless — the
    per-sample (dmin, dmax) needed to invert are returned by
    :meth:`normalize_with_stats`. Uniform samples land on the interval
    midpoint."""

    MAPPING = "linear"
    STATELESS = True
    INVERTIBLE_FROM_STATE = False

    def __init__(self, state=None, **kwargs):
        interval = kwargs.pop("interval", (-1, 1))
        super().__init__(state, **kwargs)
        if state is None:
            self._set_interval(interval)

    def jit_state(self):
        return {"imin": self.interval[0], "imax": self.interval[1]}

    @classmethod
    def apply_state(cls, xp, batch, state):
        axes = _feature_axes(batch)
        dmin = xp.min(batch, axis=axes, keepdims=True)
        dmax = xp.max(batch, axis=axes, keepdims=True)
        imin, imax = state["imin"], state["imax"]
        diff = xp.where(dmax == dmin, xp.ones_like(dmax), dmax - dmin)
        scaled = (batch - dmin) * ((imax - imin) / diff) + imin
        # uniform samples -> interval midpoint
        return xp.where(dmax == dmin,
                        xp.full_like(batch, (imin + imax) / 2), scaled)

    def normalize_with_stats(self, data):
        data = numpy.asarray(data, numpy.float32)
        axes = _feature_axes(data)
        stats = {"dmin": data.min(axis=axes), "dmax": data.max(axis=axes)}
        return self.apply_batch(numpy, data), stats

    def denormalize(self, data, **kwargs):
        data = numpy.asarray(data, numpy.float32)
        dmin = numpy.asarray(kwargs["dmin"], numpy.float32)
        dmax = numpy.asarray(kwargs["dmax"], numpy.float32)
        shape = (-1,) + (1,) * (data.ndim - 1)
        dmin, dmax = dmin.reshape(shape), dmax.reshape(shape)
        imin, imax = self.interval
        diff = numpy.where(dmax == dmin, 1.0, dmax - dmin)
        out = (data - imin) * (diff / (imax - imin)) + dmin
        return numpy.where(dmax == dmin, dmin, out)


@register_normalizer
class RangeLinearNormalizer(IntervalMixin, NormalizerBase):
    """Like linear but the *global* data range is fixed at first analyze and
    every later analyze must confirm it (reference
    ``normalization.py:398-464``) — guaranteeing the mapping is invertible
    from state alone."""

    MAPPING = "range_linear"

    def __init__(self, state=None, **kwargs):
        interval = kwargs.pop("interval", (-1, 1))
        super().__init__(state, **kwargs)
        if state is None:
            self._set_interval(interval)

    def _initialize(self, data):
        self._dmin = float(numpy.min(data))
        self._dmax = float(numpy.max(data))

    def _analyze(self, data):
        if float(numpy.min(data)) != self._dmin \
                or float(numpy.max(data)) != self._dmax:
            raise ValueError(
                "range_linear requires a stable global [min, max]: got "
                "[%f, %f], expected [%f, %f]" % (
                    float(numpy.min(data)), float(numpy.max(data)),
                    self._dmin, self._dmax))

    def jit_state(self):
        return {"imin": self.interval[0], "imax": self.interval[1],
                "dmin": self._dmin,
                "diff": (self._dmax - self._dmin) or 1.0}

    @classmethod
    def apply_state(cls, xp, batch, state):
        imin, imax = state["imin"], state["imax"]
        return (batch - state["dmin"]) \
            * ((imax - imin) / state["diff"]) + imin

    def denormalize(self, data, **kwargs):
        imin, imax = self.interval
        diff = (self._dmax - self._dmin) or 1.0
        return (numpy.asarray(data, numpy.float32) - imin) \
            * (diff / (imax - imin)) + self._dmin


@register_normalizer
class ExponentNormalizer(NormalizerBase):
    """Samplewise softmax: subtract the sample max, exponentiate, divide by
    the sample sum (reference ``normalization.py:467-492``). Stateless; the
    per-sample (dmax, dsum) to invert come from
    :meth:`normalize_with_stats`."""

    MAPPING = "exp"
    STATELESS = True
    INVERTIBLE_FROM_STATE = False

    @classmethod
    def apply_state(cls, xp, batch, state):
        axes = _feature_axes(batch)
        dmax = xp.max(batch, axis=axes, keepdims=True)
        e = xp.exp(batch - dmax)
        return e / xp.sum(e, axis=axes, keepdims=True)

    def normalize_with_stats(self, data):
        data = numpy.asarray(data, numpy.float32)
        axes = _feature_axes(data)
        dmax = data.max(axis=axes)
        shape = (-1,) + (1,) * (data.ndim - 1)
        e = numpy.exp(data - dmax.reshape(shape))
        dsum = e.sum(axis=axes)
        return e / dsum.reshape(shape), {"dmax": dmax, "dsum": dsum}

    def denormalize(self, data, **kwargs):
        data = numpy.asarray(data, numpy.float32)
        shape = (-1,) + (1,) * (data.ndim - 1)
        dmax = numpy.asarray(kwargs["dmax"]).reshape(shape)
        dsum = numpy.asarray(kwargs["dsum"]).reshape(shape)
        return numpy.log(data * dsum) + dmax


@register_normalizer
class PointwiseNormalizer(NormalizerBase):
    """Accumulates per-feature [min, max] over analyze passes, then rescales
    each feature to [-1, 1] (reference ``normalization.py:511-562``).
    Constant features normalize to 0 and denormalize back to their constant
    value (the reference divided by zero there)."""

    MAPPING = "pointwise"

    def _initialize(self, data):
        self._min = numpy.array(data[0], dtype=numpy.float32)
        self._max = numpy.array(data[0], dtype=numpy.float32)

    def _analyze(self, data):
        numpy.minimum(self._min, numpy.min(data, axis=0), self._min)
        numpy.maximum(self._max, numpy.max(data, axis=0), self._max)

    @property
    def coefficients(self):
        disp = self._max - self._min
        nz = disp != 0
        mul = numpy.zeros_like(disp)
        mul[nz] = 2.0 / disp[nz]
        add = numpy.zeros_like(disp)
        add[nz] = -1.0 - self._min[nz] * mul[nz]
        return mul, add

    def jit_state(self):
        mul, add = self.coefficients
        return {"mul": mul, "add": add}

    @classmethod
    def apply_state(cls, xp, batch, state):
        return batch * state["mul"] + state["add"]

    def denormalize(self, data, **kwargs):
        mul, add = self.coefficients
        safe_mul = numpy.where(mul == 0, 1.0, mul)
        out = (numpy.asarray(data, numpy.float32) - add) / safe_mul
        return numpy.where(mul == 0, self._min, out)


class MeanNormalizerBase(NormalizerBase):
    """Mean-subtraction family with an optional scalar scale (reference
    ``normalization.py:566-590``)."""

    def __init__(self, state=None, **kwargs):
        scale = kwargs.pop("scale", 1)
        super().__init__(state, **kwargs)
        if state is None:
            if not isinstance(scale, (int, float)):
                raise TypeError("scale must be a scalar")
            self.scale = float(scale)

    @property
    def mean(self):
        raise NotImplementedError

    def jit_state(self):
        return {"mean": self.mean, "scale": self.scale}

    @classmethod
    def apply_state(cls, xp, batch, state):
        return (batch - state["mean"]) * state["scale"]

    def denormalize(self, data, **kwargs):
        return numpy.asarray(data, numpy.float32) / self.scale + self.mean


@register_normalizer
class ExternalMeanNormalizer(MeanNormalizerBase):
    """Subtract a mean sample supplied externally — an image file, ``.npy``,
    a pickle, or an ndarray (reference ``normalization.py:593-633``)."""

    MAPPING = "external_mean"
    STATELESS = True

    def __init__(self, state=None, **kwargs):
        mean_source = kwargs.pop("mean_source", None)
        super().__init__(state, **kwargs)
        if state is not None:
            return
        if mean_source is None:
            raise ValueError("external_mean requires mean_source=")
        self._mean = self._load_mean(mean_source)

    @staticmethod
    def _load_mean(source):
        if isinstance(source, numpy.ndarray):
            return source.astype(numpy.float32)
        for attempt in ("image", "npy", "pickle"):
            try:
                if attempt == "image":
                    from PIL import Image
                    with open(source, "rb") as fin:
                        return numpy.array(Image.open(fin),
                                           dtype=numpy.float32)
                if attempt == "npy":
                    return numpy.load(source).astype(numpy.float32)
                with open(source, "rb") as fin:
                    loaded = pickle.load(fin)
                return numpy.asarray(loaded, numpy.float32)
            except Exception:
                continue
        raise ValueError("unable to load mean from %r" % (source,))

    @property
    def mean(self):
        return self._mean


@register_normalizer
class InternalMeanNormalizer(MeanNormalizerBase):
    """Subtract the mean sample accumulated over analyze passes (reference
    ``normalization.py:636-662``)."""

    MAPPING = "internal_mean"

    def _initialize(self, data):
        self._sum = numpy.zeros_like(data[0], dtype=numpy.float64)
        self._count = 0

    def _analyze(self, data):
        self._count += data.shape[0]
        self._sum += numpy.sum(data, axis=0, dtype=numpy.float64)

    @property
    def mean(self):
        return (self._sum / self._count).astype(numpy.float32)
