"""File-scanning loader bases.

TPU-native re-design of reference ``veles/loader/file_loader.py:48-277``.
The reference made these Unit subclasses combined into loaders by multiple
inheritance; here they are plain **mixins** layered onto a Loader (which
already provides logging), so there is no Unit diamond and the scanning
logic stays importable without a workflow.

- :class:`FileFilter` — include/ignore regexp lists + MIME filtering by
  ``file_type``/``file_subtypes`` (reference ``file_loader.py:54-148``);
- :class:`FileListScannerMixin` — sample lists from index files, either
  ``path label`` text lines or a JSON map (reference ``:150-203``);
- :class:`FileScannerMixin` — recursive directory walks over
  ``test_paths``/``validation_paths``/``train_paths`` (reference
  ``:205-264``);
- :class:`AutoLabelMixin` — labels extracted from file paths by regexp,
  defaulting to the parent directory name (reference ``:267-277``).
"""

import json
import os
import re
from mimetypes import guess_type


class FileFilter:
    """Filename filter: whitelist/blacklist regexps + MIME type match
    (reference ``file_loader.py:54-148``)."""

    def __init__(self, **kwargs):
        self.ignored_files = list(kwargs.pop("ignored_files", []))
        self.included_files = list(kwargs.pop("included_files", [".*"]))
        self.file_type = kwargs.pop("file_type")
        self.file_subtypes = list(kwargs.pop("file_subtypes"))
        # (?:...) groups the alternatives so EVERY pattern is both start-
        # and end-anchored, not just the first/last
        self._blacklist_re = re.compile(
            "^(?:%s)$" % "|".join(self.ignored_files)) \
            if self.ignored_files else None
        self._whitelist_re = re.compile(
            "^(?:%s)$" % "|".join(self.included_files))
        self._mime_re = re.compile(self.mime)

    @property
    def mime(self):
        return "%s/(%s)" % (self.file_type, "|".join(self.file_subtypes))

    def is_valid_filename(self, filename):
        if self._blacklist_re is not None \
                and self._blacklist_re.match(filename):
            return False
        if not self._whitelist_re.match(filename):
            return False
        mime = guess_type(filename)[0]
        if mime is None:
            return False
        return self._mime_re.match(mime) is not None


class FileScannerMixin:
    """Recursive directory scanning of per-class path lists (reference
    ``FileLoaderBase``, ``file_loader.py:205-264``). The host class must
    provide :meth:`is_valid_filename` (e.g. via :class:`FileFilter`) and
    ``info``/``warning`` logging (via Unit)."""

    def __init__(self, **kwargs):
        self.test_paths = self._check_paths(kwargs.pop("test_paths", []))
        self.validation_paths = self._check_paths(
            kwargs.pop("validation_paths", []))
        self.train_paths = self._check_paths(kwargs.pop("train_paths", []))

    @staticmethod
    def _check_paths(paths):
        if isinstance(paths, str) or not hasattr(paths, "__iter__"):
            raise TypeError("paths must be a list or tuple of directories")
        return list(paths)

    def scan_files(self, pathname):
        self.info("scanning %s...", pathname)
        files = []
        for basedir, dirs, filelist in os.walk(pathname):
            # deterministic traversal: os.walk's directory order is
            # filesystem-dependent; reproducible sample order (and MSE
            # sample<->target pairing) needs a stable scan
            dirs.sort()
            for name in sorted(filelist):
                full_name = os.path.join(basedir, name)
                if self.is_valid_filename(full_name):
                    files.append(full_name)
        if not files:
            self.warning("no files were taken from %s", pathname)
        return files

    def get_label_from_filename(self, filename):
        """Abstract: map a file path to its label."""
        raise NotImplementedError

    def collect_keys(self, paths):
        keys = []
        for path in paths:
            keys.extend(self.scan_files(path))
        return keys


class FileListScannerMixin:
    """Sample lists read from index files: ``path[ label]`` text lines or
    a JSON ``{name: {"path": ..., "label": [...]}}`` map (reference
    ``FileListLoaderBase``, ``file_loader.py:150-203``)."""

    def __init__(self, **kwargs):
        self.path_to_test_text_file = kwargs.pop(
            "path_to_test_text_file", "")
        self.path_to_val_text_file = kwargs.pop("path_to_val_text_file", "")
        self.path_to_train_text_file = kwargs.pop(
            "path_to_train_text_file", "")
        self.base_directory = kwargs.pop("base_directory", None)
        self._file_labels = {}

    def _abs_path(self, path):
        path = path.strip()
        if self.base_directory is not None:
            return os.path.join(self.base_directory, path)
        return path

    def scan_files(self, pathname):
        self.info("scanning %s...", pathname)
        files = []
        if pathname.endswith(".json"):
            with open(pathname, "r") as fin:
                for image in json.load(fin).values():
                    if image.get("label"):
                        path = self._abs_path(image["path"])
                        self._file_labels[path] = image["label"][0]
                        files.append(path)
        else:
            with open(pathname, "r") as fin:
                for line in fin:
                    if not line.strip():
                        continue
                    path, _, label = line.strip().partition(" ")
                    path = self._abs_path(path)
                    if label:
                        self._file_labels[path] = label
                    files.append(path)
        if not files:
            self.warning("no files were taken from %s", pathname)
        return files

    def get_label_from_filename(self, filename):
        return self._file_labels.get(filename)


class AutoLabelMixin:
    """Label = regexp group over the file path; the default pattern takes
    the parent directory name (reference ``AutoLabelFileLoader``,
    ``file_loader.py:267-277``)."""

    DEFAULT_LABEL_REGEXP = ".*%(sep)s([^%(sep)s]+)%(sep)s[^%(sep)s]+$" % {
        "sep": "\\" + os.sep}

    def __init__(self, **kwargs):
        self.label_regexp = re.compile(
            kwargs.pop("label_regexp", self.DEFAULT_LABEL_REGEXP))

    def get_label_from_filename(self, filename):
        match = self.label_regexp.search(filename)
        if match is None:
            raise ValueError("%s does not match label regexp %s"
                             % (filename, self.label_regexp.pattern))
        return match.group(1)
