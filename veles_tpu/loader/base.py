"""Loader base: the minibatch server.

TPU-native re-design of reference ``veles/loader/base.py`` (1181 LoC). Kept
semantics:

- three sample classes TEST(0)/VALID(1)/TRAIN(2) with per-class lengths and
  a fixed serving order TEST → VALID → TRAIN inside each epoch
  (``loader/base.py:72-80``);
- train-set reshuffling each epoch from the named "loader" PRNG stream,
  bounded by ``shuffle_limit`` (``loader/base.py:711-724``);
- epoch flags consumed by Decision/GD gating: ``minibatch_class``,
  ``last_minibatch``, ``epoch_ended_for_class``, ``epoch_ended``,
  ``epoch_number``;
- fleet-mode distribution: the master serves only (indices, class, epoch)
  payloads; slaves fill data locally; un-acked minibatches are requeued on
  slave drop (``loader/base.py:631-687``) — index payloads are tiny, so DCN
  traffic stays negligible;
- ``--train-ratio`` partial-train support and validation resplit hooks.

TPU deltas: minibatch tensors have **static shapes** (jit requirement) — a
short final minibatch keeps ``max_minibatch_size`` rows and exposes
``minibatch_valid_size`` + a 0/1 ``sample_mask`` that the evaluator folds
into loss/metrics (the reference instead re-served tail rows). Filling
happens on device (see FullBatchLoader) so the gather fuses into the tick.
"""

import collections

import numpy

from veles_tpu.core import prng
from veles_tpu.core.config import root
from veles_tpu.core.errors import NoMoreJobsError
from veles_tpu.core.mutable import Bool
from veles_tpu.core.units import Unit
from veles_tpu.memory import Array

TEST, VALID, TRAIN = 0, 1, 2
CLASS_NAMES = ("test", "validation", "train")

#: Name → loader-class map (reference ``loader/base.py:83``
#: UserLoaderRegistry); populated by the @register_loader decorator.
loader_registry = {}


def register_loader(name):
    def wrap(cls):
        loader_registry[name] = cls
        return cls
    return wrap


class Loader(Unit):
    """Minibatch server base (reference ``loader/base.py:120``)."""

    hide_from_registry = True
    VIEW_GROUP = "LOADER"

    def __init__(self, workflow, **kwargs):
        self.minibatch_size = kwargs.pop("minibatch_size", 100)
        self.train_ratio = kwargs.pop(
            "train_ratio", root.common.get("train_ratio", 1.0))
        # config-driven default (reference root.common.loader.shuffle_limit)
        self.shuffle_limit = kwargs.pop(
            "shuffle_limit", root.common.loader.get("shuffle_limit", None))
        self.prng_key = kwargs.pop("prng_key", "loader")
        on_initialized = kwargs.pop("on_initialized", None)
        super().__init__(workflow, **kwargs)
        # after super(): init_unpickled resets the slot (trailing-underscore
        # attrs are rebuilt, not pickled — the callback does not survive
        # snapshots, like the reference's marshal-pickled variant)
        self._on_initialized_ = on_initialized
        #: raw label -> contiguous class index (reference
        #: ``loader/base.py:925-944`` auto-mapping)
        self.labels_mapping = {}
        self._reversed_labels_mapping = []
        self.class_lengths = [0, 0, 0]
        self.epoch_number = 0
        self.samples_served = 0
        self.minibatch_class = TRAIN
        self.minibatch_epoch = 0
        self.minibatch_valid_size = 0
        self.minibatch_offset = 0
        self.last_minibatch = Bool(False)
        self.epoch_ended = Bool(False)
        self.epoch_ended_for_class = Bool(False)
        self.complete = Bool(False)
        # served tensors (static-shape device slots):
        self.minibatch_data = Array()
        self.minibatch_labels = Array()
        self.minibatch_indices = Array()
        self.sample_mask = Array()
        self.shuffled_indices = [None, None, None]
        self._position = [0, 0, 0]
        self._served_this_epoch = 0
        # fleet mode: minibatches handed to slaves but not yet acked, and
        # dropped slaves' work queued for re-serving
        self.pending_minibatches_ = collections.defaultdict(list)
        self.failed_minibatches = []

    def init_unpickled(self):
        super().init_unpickled()
        self.pending_minibatches_ = collections.defaultdict(list)
        self._on_initialized_ = None

    # -- the ILoader contract (reference loader/base.py:100-115) -------------
    def load_data(self):
        """Populate class_lengths and dataset storage. Abstract."""
        raise NotImplementedError

    def create_minibatch_data(self):
        """Allocate the static-shape minibatch slots. Abstract."""
        raise NotImplementedError

    def fill_minibatch(self, indices, valid):
        """Fill minibatch slots for ``indices`` (global sample ids);
        entries beyond ``valid`` are padding. Abstract."""
        raise NotImplementedError

    # -- derived sizes --------------------------------------------------------
    @property
    def total_samples(self):
        return int(sum(self.class_lengths))

    @property
    def max_minibatch_size(self):
        return self.minibatch_size

    def class_offset(self, klass):
        return int(sum(self.class_lengths[:klass]))

    @property
    def effective_class_lengths(self):
        """class_lengths with --train-ratio applied to TRAIN."""
        lengths = list(self.class_lengths)
        if self.train_ratio < 1.0:
            lengths[TRAIN] = max(1, int(lengths[TRAIN] * self.train_ratio))
        return lengths

    # -- lifecycle ------------------------------------------------------------
    def initialize(self, **kwargs):
        from veles_tpu.core.verified import ILOADER, verify_interface
        verify_interface(self, ILOADER, "ILoader")
        self.load_data()
        if self.total_samples == 0:
            raise ValueError("%s loaded an empty dataset" % self.name)
        self.info("dataset: test=%d validation=%d train=%d",
                  *self.class_lengths)
        if not self.restored_from_snapshot():
            for klass in (TEST, VALID, TRAIN):
                length = self.class_lengths[klass]
                self.shuffled_indices[klass] = (
                    numpy.arange(length, dtype=numpy.int64)
                    + self.class_offset(klass))
            self._shuffle_train()
        self.analyze_dataset()
        self.create_minibatch_data()
        # observability bridge (docs/observability.md): epoch progress
        # and serving tallies on /metrics. Weakly referenced — a loader
        # that goes away unregisters itself; scrape-time only, so a
        # run that never mounts /metrics pays nothing here.
        from veles_tpu.observe.metrics import (bridge,
                                               get_metrics_registry,
                                               publish_loader)
        bridge(get_metrics_registry(), self, publish_loader)
        if self._on_initialized_ is not None:
            self._on_initialized_()

    # -- label analysis (reference loader/base.py:925-1018) ------------------
    def get_raw_labels(self):
        """Full-length label array aligned with the [test|valid|train] row
        layout, or None when the dataset has no labels. Hook for
        subclasses; drives label mapping and distribution checks."""
        return None

    @property
    def has_labels(self):
        return self.get_raw_labels() is not None

    @property
    def unique_labels_count(self):
        return len(self.labels_mapping)

    @property
    def reversed_labels_mapping(self):
        """index -> raw label (for denormalizing predictions)."""
        return self._reversed_labels_mapping

    def map_labels(self, raw):
        """Raw labels -> contiguous int32 indices via labels_mapping."""
        raw = numpy.asarray(raw)
        if not self.labels_mapping:
            return raw.astype(numpy.int32)
        return numpy.fromiter(
            (self.labels_mapping[l] for l in raw.tolist()),
            numpy.int32, count=len(raw))

    def analyze_dataset(self):
        """Build the label auto-mapping from the train split, check the
        test/validation labels are a subset, log per-class cardinality
        stats, and chi-square-compare the split distributions (reference
        ``loader/base.py:925-1018``)."""
        raw = self.get_raw_labels()
        if raw is None:
            return
        counters = []
        for klass in (TEST, VALID, TRAIN):
            start = self.class_offset(klass)
            values = numpy.asarray(
                raw[start:start + self.class_lengths[klass]],
                dtype=object).tolist()
            missing = sum(1 for v in values if v is None)
            if missing:
                raise ValueError(
                    "%s: %d %s sample(s) have no label — label every "
                    "sample or provide none" % (
                        self.name, missing, CLASS_NAMES[klass]))
            counters.append(collections.Counter(values))
        self._setup_labels_mapping(counters)

    def _setup_labels_mapping(self, counters):
        test_counts, valid_counts, train_counts = counters
        if not self.labels_mapping:
            # evaluation-only datasets (empty train split) map over ALL
            # labels; the subset check below is train-relative so it only
            # applies when a train split exists
            source = sorted(train_counts) if train_counts else sorted(
                set(test_counts) | set(valid_counts))
            self.labels_mapping.update(
                {k: i for i, k in enumerate(source)})
            self._reversed_labels_mapping = sorted(self.labels_mapping)
        self._print_label_stats(train_counts, CLASS_NAMES[TRAIN])
        for klass, counts in ((TEST, test_counts), (VALID, valid_counts)):
            if not self.class_lengths[klass] or not train_counts:
                continue
            unknown = set(counts) - set(self.labels_mapping)
            if unknown:
                raise ValueError(
                    "%s: %s labels missing from the training set: %s"
                    % (self.name, CLASS_NAMES[klass], sorted(unknown)))
            missing = set(self.labels_mapping) - set(counts)
            if missing:
                self.warning("no %s samples for labels: %s",
                             CLASS_NAMES[klass], sorted(missing))
                for label in missing:
                    counts[label] = 0
            self._print_label_stats(counts, CLASS_NAMES[klass])
            self._compare_label_distributions(train_counts, counts,
                                              CLASS_NAMES[klass])

    def _print_label_stats(self, counts, set_name):
        values = numpy.array([v for _, v in sorted(counts.items())])
        if not values.sum():
            self.info("no %s labels specified", set_name)
            return
        mean = float(values.mean())
        std = float(values.std())
        self.info(
            "%s label cardinalities: min=%d max=%d avg=%d sigma=%d (%d%%)",
            set_name, values.min(), values.max(), mean, std,
            std * 100 // max(mean, 1))
        if std > mean / 2:
            self.warning("%s labels are heavily imbalanced", set_name)

    def _compare_label_distributions(self, train_counts, other_counts,
                                     other_name):
        """Chi-square test that the split's label distribution matches the
        train split's (reference ``loader/base.py:1006-1018``)."""
        try:
            from scipy.stats import chisquare
        except ImportError:  # scipy is optional
            return
        train = numpy.array(
            [v for _, v in sorted(train_counts.items())], numpy.float64)
        other = numpy.array(
            [v for _, v in sorted(other_counts.items())], numpy.float64)
        if not other.sum() or not train.sum():
            return
        # observed COUNTS against expected counts scaled to the observed
        # total — normalizing both to proportions would discard sample
        # size and make the test degenerate
        _, p = chisquare(other, train / train.sum() * other.sum())
        if p > 0.95:
            self.info("OK: train and %s label distributions match "
                      "(chi-square p=%.3f)", other_name, p)
        else:
            self.warning("train and %s label distributions differ "
                         "(chi-square p=%.3f)", other_name, p)

    def restored_from_snapshot(self):
        wf = self.workflow
        return bool(getattr(wf, "restored_from_snapshot", False)) \
            and self.shuffled_indices[TRAIN] is not None

    def draw_transform_seeds(self, n):
        """``n`` augmentation seeds in the SAME stream order graph-mode
        ``fill_minibatch`` draws them — one per TRAIN minibatch (any
        loader that exposes a ``jit_transform`` inherits this)."""
        gen = prng.get(self.prng_key)
        return numpy.asarray(
            [int(gen.randint(0, 2 ** 31 - 1)) for _ in range(n)],
            numpy.int64)

    def _shuffle_train(self):
        if self.shuffle_limit is not None \
                and self.epoch_number >= self.shuffle_limit:
            return
        prng.get(self.prng_key).shuffle(self.shuffled_indices[TRAIN])

    # -- serving --------------------------------------------------------------
    def _next_block(self):
        """Compute the next (class, start, size) to serve, advancing epoch
        state. Returns None when a full epoch just completed."""
        lengths = self.effective_class_lengths
        for klass in (TEST, VALID, TRAIN):
            pos = self._position[klass]
            if pos < lengths[klass]:
                size = min(self.max_minibatch_size, lengths[klass] - pos)
                self._position[klass] = pos + size
                return klass, pos, size
        return None

    def _roll_epoch(self):
        self.epoch_number += 1
        self._position = [0, 0, 0]
        self._shuffle_train()

    def serve_next_minibatch(self, slave_id=None):
        """Pick the next minibatch (failed ones first — reference
        ``loader/base.py:726-753``), record it pending for the slave, and
        return (klass, indices, valid_size, last_of_class, last_of_epoch,
        epoch). The epoch tag lets the master's Decision bucket updates
        that arrive out of order across epoch boundaries."""
        if self.failed_minibatches:
            # re-serve with the ORIGINAL last_of_class/last_of_epoch
            # flags: a requeued job must be bit-identical to the one the
            # dead slave held, or an epoch-closing minibatch would lose
            # its epoch-end semantics on retry (the chaos harness asserts
            # faulted == fault-free convergence on exactly this)
            (klass, indices, valid, last_of_class, last_of_epoch,
             epoch) = self.failed_minibatches.pop()
        else:
            block = self._next_block()
            if block is None:
                self._roll_epoch()
                block = self._next_block()
            klass, pos, valid = block
            epoch = self.epoch_number
            # copy, not view: the epoch reshuffle mutates shuffled_indices
            # in place, which would corrupt pending/requeued payloads
            indices = self.shuffled_indices[klass][pos:pos + valid].copy()
            lengths = self.effective_class_lengths
            last_of_class = self._position[klass] >= lengths[klass]
            last_of_epoch = last_of_class and all(
                self._position[k] >= lengths[k] or lengths[k] == 0
                for k in (TEST, VALID, TRAIN))
        if slave_id is not None:
            self.pending_minibatches_[slave_id].append(
                (klass, indices, valid, last_of_class, last_of_epoch,
                 epoch))
        return klass, indices, valid, last_of_class, last_of_epoch, epoch

    def serve_next_class_sweep(self):
        """Serve one ENTIRE sample-class sweep at once: the fused sweep
        engine scans the minibatches inside one XLA computation, so the
        host loop runs once per class per epoch instead of once per
        minibatch (the dispatch-latency killer on a tunneled TPU).

        Returns (klass, index_matrix(n_batches, mb), valid_sizes
        (n_batches,), total_valid, last_of_epoch, epoch)."""
        lengths = self.effective_class_lengths
        klass = next((k for k in (TEST, VALID, TRAIN)
                      if self._position[k] < lengths[k]), None)
        if klass is None:
            self._roll_epoch()
            klass = next(k for k in (TEST, VALID, TRAIN) if lengths[k])
        mb = self.max_minibatch_size
        start = self._position[klass]
        n = lengths[klass] - start
        n_batches = (n + mb - 1) // mb
        idx = self.shuffled_indices[klass][start:start + n]
        matrix = numpy.zeros((n_batches, mb), dtype=numpy.int64)
        matrix.reshape(-1)[:n] = idx
        valid_sizes = numpy.full(n_batches, mb, dtype=numpy.int32)
        if n % mb:
            valid_sizes[-1] = n % mb
        self._position[klass] = lengths[klass]
        last_of_epoch = all(self._position[k] >= lengths[k]
                            or lengths[k] == 0
                            for k in (TEST, VALID, TRAIN))
        return (klass, matrix, valid_sizes, n, last_of_epoch,
                self.epoch_number)

    def run(self):
        """Standalone: pick the next indices and fill on device. On a slave
        the minibatch was already applied from the master's job payload
        (``apply_data_from_master``) — serving locally here would silently
        train on the wrong data (reference ``loader/base.py:641-663``)."""
        if self.is_slave:
            return
        if getattr(self, "sweep_serving", False):
            (klass, matrix, valid_sizes, total, last_of_epoch,
             epoch) = self.serve_next_class_sweep()
            self._publish_flags(klass, matrix.reshape(-1), total, True,
                                last_of_epoch, epoch)
            self.minibatch_indices.data = matrix
            self.sweep_valid_sizes = valid_sizes
            # per-minibatch augmentation seeds for the fused tick, drawn
            # in the same stream order graph mode would (one per TRAIN
            # minibatch at fill time)
            if klass == TRAIN and getattr(self, "jit_transform", None):
                self.sweep_transform_seeds = self.draw_transform_seeds(
                    len(matrix))
            else:
                self.sweep_transform_seeds = None
            self._account_served(total, last_of_epoch)
            return
        (klass, indices, valid, last_of_class,
         last_of_epoch, epoch) = self.serve_next_minibatch()
        self._apply_minibatch(klass, indices, valid, last_of_class,
                              last_of_epoch, epoch)

    def _publish_flags(self, klass, indices, valid, last_of_class,
                       last_of_epoch, epoch):
        """The serve-side state every consumer reads — single source for
        both per-minibatch and sweep serving."""
        self.minibatch_class = klass
        self.minibatch_epoch = epoch
        self.minibatch_valid_size = valid
        self.minibatch_offset = int(indices[0]) if len(indices) else 0
        self.last_minibatch.set(last_of_class)
        self.epoch_ended_for_class.set(last_of_class)
        self.epoch_ended.set(last_of_epoch)

    def _account_served(self, valid, last_of_epoch):
        self.samples_served += valid
        self._served_this_epoch += valid
        if last_of_epoch:
            self.event("epoch", "single", number=self.epoch_number)
            self._served_this_epoch = 0

    def _apply_minibatch(self, klass, indices, valid, last_of_class,
                         last_of_epoch, epoch=0):
        self._publish_flags(klass, indices, valid, last_of_class,
                            last_of_epoch, epoch)
        padded = self._pad_indices(indices)
        if getattr(self, "fill_data", True):
            self.fill_minibatch(padded, valid)
        else:
            # fused-tick mode: the tick gathers in-jit from the originals;
            # the loader only publishes the served indices (host numpy —
            # the transfer rides the fused step's dispatch)
            self.minibatch_indices.data = padded
            if klass == TRAIN and getattr(self, "jit_transform", None):
                self.minibatch_transform_seed = int(
                    self.draw_transform_seeds(1)[0])
            else:
                self.minibatch_transform_seed = 0
        self._account_served(valid, last_of_epoch)

    def _pad_indices(self, indices):
        """Static shapes: pad short index blocks by repeating index 0; the
        mask zeroes their contribution."""
        size = self.max_minibatch_size
        padded = numpy.zeros(size, dtype=numpy.int64)
        padded[:len(indices)] = indices
        return padded

    # -- fleet-mode distribution (reference loader/base.py:631-687) ----------
    def generate_data_for_slave(self, slave=None):
        slave_id = getattr(slave, "id", slave)
        if self.complete:
            raise NoMoreJobsError()
        return self.serve_next_minibatch(slave_id)

    def apply_data_from_master(self, data):
        klass, indices, valid, last_of_class, last_of_epoch, epoch = data
        self._apply_minibatch(klass, numpy.asarray(indices), valid,
                              last_of_class, last_of_epoch, epoch)

    def generate_data_for_master(self):
        return {"samples_served": self.samples_served}

    def apply_data_from_slave(self, data, slave=None):
        slave_id = getattr(slave, "id", slave)
        if self.pending_minibatches_.get(slave_id):
            self.pending_minibatches_[slave_id].pop(0)

    def drop_slave(self, slave=None):
        """Requeue the dropped slave's un-acked minibatches so no sample is
        lost (reference ``loader/base.py:679-687``)."""
        slave_id = getattr(slave, "id", slave)
        pending = self.pending_minibatches_.pop(slave_id, [])
        self.failed_minibatches.extend(pending)
        if pending:
            self.warning("requeued %d minibatches from dropped slave %s",
                         len(pending), slave_id)

    @property
    def has_data_for_slave(self):
        # backpressure means "not ready YET"; exhaustion is signalled by
        # NoMoreJobsError from generate_data_for_slave — returning False
        # here on completion would park job requests forever
        return True

    # -- results --------------------------------------------------------------
    # (the "epochs" metric belongs to the Decision unit — its completed-epoch
    # count, not this serving-side counter, is the published one)
    def get_metric_names(self):
        return ["total_samples"]

    def get_metric_values(self):
        return [self.total_samples]


class LoaderMSEMixin:
    """Adds regression targets to a Loader (reference
    ``loader/base.py:1034-1155`` LoaderMSEMixin/LoaderMSE).

    Serves ``minibatch_targets`` alongside data/labels, normalized by a
    *separate* target normalizer whose state supports ``denormalize()`` —
    stateless normalizers (other than "none") are rejected because the
    network output could never be mapped back to target units (reference
    ``base.py:1100-1111``)."""

    def __init__(self, workflow, **kwargs):
        self.targets_shape = kwargs.pop("targets_shape", ())
        self.target_normalization_type = kwargs.pop(
            "target_normalization_type",
            kwargs.get("normalization_type", "none"))
        self.target_normalization_parameters = kwargs.pop(
            "target_normalization_parameters",
            kwargs.get("normalization_parameters", {}))
        super().__init__(workflow, **kwargs)
        from veles_tpu.loader.normalization import normalizer_registry
        cls = normalizer_registry.get(self.target_normalization_type)
        if cls is None:
            raise ValueError("unknown target_normalization_type %r"
                             % self.target_normalization_type)
        if not cls.INVERTIBLE_FROM_STATE:
            raise ValueError(
                "target normalization %r needs per-sample stats to invert: "
                "test-time forward propagation could not be denormalized"
                % self.target_normalization_type)
        self.minibatch_targets = Array()
        self.target_normalizer = None
