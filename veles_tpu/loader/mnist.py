"""MNIST loader: the idx-format pipeline behind the MNIST784 parity model.

The reference's MNIST workflow (znicz MNIST784 sample; topology and error
anchors in ``docs/source/manualrst_veles_example.rst:55-66``) reads the
LeCun idx files. This loader parses idx1 (labels) / idx3 (images) —
gzipped or raw — into a device-resident FullBatchLoader with the
reference's split: the 10k test set serves as VALIDATION, the 60k train
set as TRAIN (class order [test=0, valid=10000, train=60000]).

Files are fetched via :mod:`veles_tpu.downloader` when ``url_base`` is
given; offline runs point ``directory`` at pre-downloaded files.
"""

import gzip
import os
import struct

import numpy

from veles_tpu.core.config import root
from veles_tpu.loader.base import register_loader
from veles_tpu.loader.fullbatch import FullBatchLoader

FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}

#: idx payloads are big-endian (the format predates little-endian wins)
IDX_DTYPES = {0x08: ">u1", 0x09: ">i1", 0x0B: ">i2",
              0x0C: ">i4", 0x0D: ">f4", 0x0E: ">f8"}


def read_idx(path):
    """Parse one idx file (``.gz`` accepted) into a numpy array."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fin:
        zero, dtype_code, ndim = struct.unpack(">HBB", fin.read(4))
        if zero != 0 or dtype_code not in IDX_DTYPES:
            raise ValueError("%s: not an idx file" % path)
        shape = struct.unpack(">" + "I" * ndim, fin.read(4 * ndim))
        data = numpy.frombuffer(fin.read(), IDX_DTYPES[dtype_code])
    return data.reshape(shape).astype(data.dtype.newbyteorder("="))


@register_loader("mnist")
class MNISTLoader(FullBatchLoader):
    """MNIST via idx files (the MNIST784 data pipeline)."""

    def __init__(self, workflow, directory=None, url_base=None, flat=True,
                 **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super().__init__(workflow, **kwargs)
        self.directory = directory or os.path.join(
            root.common.dirs.get("datasets"), "mnist")
        self.url_base = url_base
        #: flat=True serves (N, 784) rows (the MNIST784 MLP form);
        #: flat=False serves (N, 28, 28, 1) NHWC for conv topologies
        #: (the reference's mnist_conv/mnist_caffe configs)
        self.flat = flat

    def _resolve(self, stem):
        for name in (stem, stem + ".gz"):
            path = os.path.join(self.directory, name)
            if os.path.exists(path):
                return path
        return None

    def load_data(self):
        if any(self._resolve(stem) is None for stem in FILES.values()):
            if self.url_base is None:
                raise FileNotFoundError(
                    "%s: idx files not found in %s and no url_base given"
                    % (self.name, self.directory))
            from veles_tpu.downloader import fetch
            for stem in FILES.values():
                if self._resolve(stem) is None:
                    fetch(self.url_base.rstrip("/") + "/" + stem + ".gz",
                          self.directory, logger=self)
        train_x = read_idx(self._resolve(FILES["train_images"]))
        train_y = read_idx(self._resolve(FILES["train_labels"]))
        test_x = read_idx(self._resolve(FILES["test_images"]))
        test_y = read_idx(self._resolve(FILES["test_labels"]))
        n_valid, n_train = len(test_x), len(train_x)
        shape = (-1,) if self.flat else (28, 28, 1)
        data = numpy.concatenate([
            test_x.reshape((n_valid,) + shape).astype(numpy.float32),
            train_x.reshape((n_train,) + shape).astype(numpy.float32)])
        labels = numpy.concatenate([
            test_y.astype(numpy.int32), train_y.astype(numpy.int32)])
        self._provided_data = data
        self._provided_labels = labels
        self._provided_lengths = [0, n_valid, n_train]
        super().load_data()
