"""Sound file loaders.

TPU-native re-design of reference ``veles/loader/libsndfile_loader.py``
(+ the ctypes ``libsndfile.py`` binding): the reference decoded
WAV/FLAC/OGG through libsndfile; here decoding uses the stdlib ``wave``
module (16/8/32-bit PCM WAV, mono/stereo — the training-set formats) with
a hook (:meth:`SoundDecoderMixin.decode_file`) where a soundfile/ffmpeg
decoder slots in for compressed formats when available.

The loader tier mirrors the image tier: decoded waveforms are windowed
into fixed-length frames (``window_size`` samples, ``window_stride``
hop — the reference's ``window_size`` kwarg), optionally averaged to
mono, and served through the device-resident full-batch machinery.
"""

import os
import wave

import numpy

from veles_tpu.loader.base import TEST, VALID, TRAIN, register_loader
from veles_tpu.loader.file_loader import AutoLabelMixin, FileScannerMixin
from veles_tpu.loader.fullbatch import FullBatchLoader


class SoundDecoderMixin:
    """WAV decoding (reference ``SndFileMixin``,
    ``libsndfile_loader.py:46-91``)."""

    @staticmethod
    def decode_file(path):
        """-> dict(data (frames, channels) float32 in [-1, 1],
        sampling_rate, samples, channels, name)."""
        with wave.open(path, "rb") as snd:
            channels = snd.getnchannels()
            if channels > 2:
                raise ValueError(
                    "%s has %d channels; only mono or stereo are allowed"
                    % (path, channels))
            width = snd.getsampwidth()
            frames = snd.getnframes()
            raw = snd.readframes(frames)
            rate = snd.getframerate()
        if width == 2:
            data = numpy.frombuffer(raw, numpy.int16) / 32768.0
        elif width == 4:
            data = numpy.frombuffer(raw, numpy.int32) / 2147483648.0
        elif width == 1:  # unsigned 8-bit PCM
            data = (numpy.frombuffer(raw, numpy.uint8).astype(
                numpy.float32) - 128.0) / 128.0
        else:
            raise ValueError("%s: unsupported sample width %d"
                             % (path, width))
        # derive frames from the DECODED length: a truncated data chunk
        # must not crash an opaque reshape against the header count
        data = data.astype(numpy.float32).reshape(-1, channels)
        if len(data) != frames:
            import logging
            logging.getLogger("SoundDecoder").warning(
                "%s: header says %d frames, decoded %d (truncated?)",
                path, frames, len(data))
        return {"data": data, "sampling_rate": rate,
                "samples": len(data), "channels": channels, "name": path}


@register_loader("sound_file")
class SoundFileLoader(SoundDecoderMixin, FileScannerMixin,
                      FullBatchLoader):
    """Windowed waveforms from directory scans, label =
    :meth:`get_label_from_filename` (reference ``SndFileLoaderBase``,
    ``libsndfile_loader.py:93-105``)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.window_size = int(kwargs.pop("window_size", 1024))
        self.window_stride = int(kwargs.pop("window_stride",
                                            self.window_size))
        self.mono = kwargs.pop("mono", True)
        self._expected_channels = None
        FileScannerMixin.__init__(
            self, **{k: kwargs.pop(k) for k in
                     ("test_paths", "validation_paths", "train_paths")
                     if k in kwargs})
        FullBatchLoader.__init__(self, workflow, **kwargs)

    def is_valid_filename(self, filename):
        return filename.lower().endswith(".wav")

    def get_label_from_filename(self, filename):
        raise NotImplementedError

    def _windows(self, path):
        """Window over FRAMES (not interleaved samples): a stereo window
        of ``window_size`` covers window_size time steps and its feature
        layout is channel-consistent across windows regardless of
        stride parity."""
        decoded = self.decode_file(path)
        data = decoded["data"]  # (frames, channels)
        if self.mono and decoded["channels"] > 1:
            data = data.mean(axis=1, keepdims=True)
        elif not self.mono:
            # mixed mono/stereo datasets would produce ragged windows and
            # die in numpy.stack with no filename — fail HERE with one
            if self._expected_channels is None:
                self._expected_channels = data.shape[1]
            elif data.shape[1] != self._expected_channels:
                raise ValueError(
                    "%s has %d channels but the dataset started with %d "
                    "(use mono=True to mix)" % (
                        path, data.shape[1], self._expected_channels))
        frames = len(data)
        out = []
        for start in range(0, frames - self.window_size + 1,
                           self.window_stride):
            out.append(data[start:start + self.window_size].reshape(-1))
        if not out and frames:  # short clip: one zero-padded window
            padded = numpy.zeros((self.window_size, data.shape[1]),
                                 numpy.float32)
            padded[:frames] = data
            out.append(padded.reshape(-1))
        return out

    def load_data(self):
        rows, labels, lengths = [], [], []
        for klass in (TEST, VALID, TRAIN):
            paths = (self.test_paths, self.validation_paths,
                     self.train_paths)[klass]
            count = 0
            for path in self.collect_keys(paths):
                label = self.get_label_from_filename(path)
                for window in self._windows(path):
                    rows.append(window)
                    labels.append(label)
                    count += 1
            lengths.append(count)
        if not rows:
            raise ValueError("%s found no audio windows" % self.name)
        self._provided_data = numpy.stack(rows)
        self._provided_labels = labels
        self._provided_lengths = lengths
        super().load_data()


@register_loader("auto_label_sound_file")
class AutoLabelSoundFileLoader(AutoLabelMixin, SoundFileLoader):
    """Sound files labeled by path regexp, default = parent directory
    (the FLAC/WAV auto-label combination the reference assembled from
    its mixins)."""

    def __init__(self, workflow, **kwargs):
        AutoLabelMixin.__init__(
            self, **{k: kwargs.pop(k) for k in ("label_regexp",)
                     if k in kwargs})
        SoundFileLoader.__init__(self, workflow, **kwargs)
