"""Image loading pipeline.

TPU-native re-design of reference ``veles/loader/image.py:106-705`` +
``fullbatch_image.py:56-266``. The reference decoded with PIL/OpenCV,
scaled/cropped/rotated each sample on the host, and *inflated* the dataset
(``samples_inflation`` copies per mirror/rotation/crop combination) before
uploading to the device.

TPU design decisions:

- **decode once, host-side** (PIL): color conversion, aspect-preserving
  scale onto a background canvas, fixed/center crop — these are one-time
  load costs, exactly like the reference's load pass;
- **dataset device-resident** afterwards (inherits FullBatchLoader's HBM
  residency + jitted gather);
- **augmentation in-jit, not by inflation**: random mirror (and random
  crop jitter) are applied inside a jitted transform on the *gathered
  minibatch*, re-randomized every epoch from the loader PRNG stream. The
  reference's N-fold ``samples_inflation`` costs N× HBM and sees each
  fixed distortion once per epoch; transforming in-jit costs zero extra
  HBM and samples fresh distortions forever.

Loaders that declare in-fill transforms set ``has_fill_transforms``; when
the transform is one the fused engine replicates in-tick
(``jit_transform`` — currently the random mirror, via the SHARED
``ops.augment.mirror_batch``), fusion stays on with loader-drawn seeds
and identical numerics; any other fill-time transform makes the fused
engine decline so the graph path — which does run the transform —
executes instead.
"""

import numpy

import jax
import jax.numpy as jnp

from veles_tpu.loader.base import TEST, VALID, TRAIN, register_loader
from veles_tpu.loader.file_loader import (AutoLabelMixin, FileFilter,
                                          FileListScannerMixin,
                                          FileScannerMixin)
from veles_tpu.loader.fullbatch import FullBatchLoader, FullBatchLoaderMSE
from veles_tpu.core import prng

#: PIL modes for the supported color spaces.
_COLOR_MODES = {"RGB": "RGB", "GRAY": "L", "L": "L", "RGBA": "RGBA"}


def decode_image(source, color_space="RGB", background_color=None):
    """Decode an image file/path to float32 HWC (reference ImageLoader
    decode + background blending, ``image.py:406-443``). RGBA sources are
    alpha-blended over ``background_color`` when converting to RGB."""
    from PIL import Image
    img = Image.open(source)
    mode = _COLOR_MODES.get(color_space)
    if mode is None:
        raise ValueError("unsupported color_space %r" % color_space)
    if img.mode == "RGBA" and mode != "RGBA":
        background = Image.new(
            "RGBA", img.size,
            tuple(background_color or (0, 0, 0)) + (255,))
        img = Image.alpha_composite(background, img)
    if img.mode != mode:
        img = img.convert(mode)
    arr = numpy.asarray(img, dtype=numpy.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def scale_image(arr, target_hw, maintain_aspect_ratio=False,
                background_color=0):
    """Bicubic resize to (H, W); with ``maintain_aspect_ratio`` the image
    is fit inside and centered on a background canvas (reference
    ``scale_image``, ``image.py:444-483``)."""
    from PIL import Image
    th, tw = target_hw
    h, w = arr.shape[:2]
    if (h, w) == (th, tw):
        return arr
    channels = arr.shape[2]
    img = Image.fromarray(arr.astype(numpy.uint8).squeeze()
                          if channels == 1 else arr.astype(numpy.uint8))
    if maintain_aspect_ratio:
        if w >= h:
            dw, dh = tw, max(1, int(round(tw * h / w)))
        else:
            dh, dw = th, max(1, int(round(th * w / h)))
        img = img.resize((dw, dh), Image.BICUBIC)
        canvas = numpy.full((th, tw, channels), background_color,
                            numpy.float32)
        y0, x0 = (th - dh) // 2, (tw - dw) // 2
        resized = numpy.asarray(img, dtype=numpy.float32)
        if resized.ndim == 2:
            resized = resized[:, :, None]
        canvas[y0:y0 + dh, x0:x0 + dw] = resized
        return canvas
    img = img.resize((tw, th), Image.BICUBIC)
    out = numpy.asarray(img, dtype=numpy.float32)
    if out.ndim == 2:
        out = out[:, :, None]
    return out


def crop_image(arr, crop_hw, offset="center", rng=None):
    """Cut a (H, W) window; ``offset`` is "center", "random", or explicit
    (y, x). Fractional crop sizes are ratios of the source (reference
    ``crop_image``, ``image.py:508-531``)."""
    h, w = arr.shape[:2]
    ch, cw = (int(c * s) if isinstance(c, float) else int(c)
              for c, s in zip(crop_hw, (h, w)))
    if ch > h or cw > w:
        raise ValueError("crop %s larger than image %s" % ((ch, cw), (h, w)))
    if offset == "center":
        y0, x0 = (h - ch) // 2, (w - cw) // 2
    elif offset == "random":
        gen = rng or prng.get("loader")
        y0 = int(gen.randint(0, h - ch + 1))
        x0 = int(gen.randint(0, w - cw + 1))
    else:
        y0, x0 = offset
    return arr[y0:y0 + ch, x0:x0 + cw]


class FullBatchImageLoader(FullBatchLoader):
    """Device-resident image dataset with load-time scale/crop and in-jit
    train-time mirror augmentation (reference ``FullBatchImageLoader``,
    ``fullbatch_image.py:56-177``).

    Subclasses (or mixins) provide the image source:
    ``get_keys(klass) -> [key...]``, ``get_image_label(key)``,
    ``get_image_data(key) -> float32 HWC`` (reference IImageLoader,
    ``image.py:83-104``).
    """

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.size = tuple(kwargs.pop("size"))
        self.color_space = kwargs.pop("color_space", "RGB")
        self.scale_maintain_aspect_ratio = kwargs.pop(
            "scale_maintain_aspect_ratio", False)
        self.crop = kwargs.pop("crop", None)
        self.crop_offset = kwargs.pop("crop_offset", "center")
        self.mirror = kwargs.pop("mirror", False)
        self.background_color = kwargs.pop("background_color", 0)
        if self.mirror not in (False, "random"):
            raise ValueError(
                "mirror must be False or 'random' (deterministic mirror "
                "inflation is replaced by in-jit random augmentation)")
        super().__init__(workflow, **kwargs)

    #: the fused tick's in-XLA gather bypasses fill_minibatch; loaders
    #: with fill-time transforms must run the graph path — UNLESS the
    #: transform is one the fused engine replicates in-tick
    #: (``jit_transform``), in which case fusion stays on
    @property
    def has_fill_transforms(self):
        return self.mirror == "random"

    @property
    def jit_transform(self):
        """Name of the fill transform the fused tick can apply itself
        (seeded identically, so fused == graph numerics)."""
        return "mirror" if self.mirror == "random" else None

    # -- image source contract ----------------------------------------------
    def get_keys(self, klass):
        raise NotImplementedError

    def get_image_label(self, key):
        raise NotImplementedError

    def get_image_data(self, key):
        """Decode one sample. Default: treat key as a file path."""
        return decode_image(key, self.color_space, self.background_color)

    # -- loading -------------------------------------------------------------
    @property
    def sample_shape(self):
        if self.crop:
            # fractional crops are ratios of the scaled size
            hw = tuple(int(c * s) if isinstance(c, float) else int(c)
                       for c, s in zip(self.crop, self.size))
        else:
            hw = self.size
        channels = 1 if self.color_space in ("GRAY", "L") else (
            4 if self.color_space == "RGBA" else 3)
        return (int(hw[0]), int(hw[1]), channels)

    def _load_one(self, key):
        arr = self.get_image_data(key)
        arr = scale_image(arr, self.size, self.scale_maintain_aspect_ratio,
                          self.background_color)
        if self.crop:
            arr = crop_image(arr, self.crop, self.crop_offset,
                             prng.get(self.prng_key))
        return arr

    def load_data(self):
        keys = getattr(self, "_prescanned_keys_", None) \
            or [self.get_keys(klass) for klass in (TEST, VALID, TRAIN)]
        self._prescanned_keys_ = None
        self.class_keys = keys
        total = sum(len(k) for k in keys)
        if not total:
            raise ValueError("%s found no images" % self.name)
        shape = self.sample_shape
        data = numpy.zeros((total,) + shape, numpy.float32)
        labels = []
        row = 0
        for klass in (TEST, VALID, TRAIN):
            for key in keys[klass]:
                arr = self._load_one(key)
                if arr.shape != shape:
                    raise ValueError("image %s decoded to %s, expected %s"
                                     % (key, arr.shape, shape))
                data[row] = arr
                labels.append(self.get_image_label(key))
                row += 1
        self._provided_data = data
        has_labels = any(l is not None for l in labels)
        self._provided_labels = labels if has_labels else None
        self._provided_lengths = [len(k) for k in keys]
        super().load_data()

    # -- in-jit augmentation --------------------------------------------------
    def init_unpickled(self):
        super().init_unpickled()
        self._augment_jit_ = None

    @property
    def _augment_jit(self):
        if self._augment_jit_ is None:
            from veles_tpu.ops.augment import mirror_batch
            self._augment_jit_ = jax.jit(mirror_batch)
        return self._augment_jit_

    def fill_minibatch(self, indices, valid):
        super().fill_minibatch(indices, valid)
        if self.mirror == "random" and self.minibatch_class == TRAIN:
            seed = int(self.draw_transform_seeds(1)[0])
            self.minibatch_data.data = self._augment_jit(
                self.minibatch_data.data, seed)


class ImageLoaderMSEMixin:
    """Target-IMAGE regression tier (reference ``loader/image_mse.py:47-158``
    ImageLoaderMSEMixin): each sample's MSE target is itself an image.

    Target matching follows the reference contract:

    - labeled datasets: every target key carries a unique label
      (``get_image_label``); a sample's target is the target image with
      the SAME label (reference ``target_label_map``);
    - unlabeled datasets: the i-th sample (over TEST+VALID+TRAIN, serving
      order) maps to the i-th sorted target key — counts must match.

    Targets are decoded through the same scale/crop pipeline as the
    samples, so ``targets_shape`` equals the sample shape. Design note:
    the reference gathered target rows per minibatch on the host; here
    the per-sample target matrix is materialized once and rides the
    device-resident full-batch gather (labels sharing a target duplicate
    its rows — the HBM cost of a zero-host-work training loop).

    Host classes provide :meth:`get_target_keys` and the usual image
    source contract.
    """

    def get_target_keys(self):
        raise NotImplementedError

    def load_data(self):
        tkeys = sorted(self.get_target_keys())
        if len(set(tkeys)) < len(tkeys):
            raise ValueError("%s: duplicate target keys" % self.name)
        if not tkeys:
            raise ValueError("%s: no target images found" % self.name)
        targets = numpy.stack([self._load_one(k) for k in tkeys])
        tlabels = [self.get_image_label(k) for k in tkeys]
        has_tlabels = any(l is not None for l in tlabels)
        # scan ONCE and stash: FullBatchImageLoader.load_data reuses this
        # list, so the target rows stay aligned with the exact sample
        # serving order (a second walk could see filesystem changes)
        self._prescanned_keys_ = [self.get_keys(klass)
                                  for klass in (TEST, VALID, TRAIN)]
        sample_keys = [k for klass_keys in self._prescanned_keys_
                       for k in klass_keys]
        sample_labels = [self.get_image_label(k) for k in sample_keys]
        if any(l is not None for l in sample_labels) and has_tlabels:
            if len(set(tlabels)) < len(tlabels):
                raise ValueError("%s: targets have duplicate labels"
                                 % self.name)
            label_row = {l: i for i, l in enumerate(tlabels)}
            try:
                rows = [label_row[l] for l in sample_labels]
            except KeyError as e:
                raise ValueError("%s: no target image labeled %r"
                                 % (self.name, e.args[0])) from None
        else:
            if len(tkeys) != len(sample_keys):
                raise ValueError(
                    "%s: unlabeled MSE needs one target per sample "
                    "(%d targets, %d samples)"
                    % (self.name, len(tkeys), len(sample_keys)))
            rows = list(range(len(sample_keys)))
        self._provided_targets = targets[rows]
        self.targets_shape = targets.shape[1:]
        super().load_data()


@register_loader("file_image")
class FileImageLoader(FileFilter, FileScannerMixin, FullBatchImageLoader):
    """Images from recursive directory scans with MIME filtering
    (reference ``FileImageLoader``, ``file_image.py:53-177``). Subclasses
    define :meth:`get_label_from_filename`."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("file_type", "image")
        kwargs.setdefault("file_subtypes", ["png", "jpeg", "bmp"])
        FileFilter.__init__(
            self, **{k: kwargs.pop(k) for k in
                     ("ignored_files", "included_files", "file_type",
                      "file_subtypes") if k in kwargs})
        FileScannerMixin.__init__(
            self, **{k: kwargs.pop(k) for k in
                     ("test_paths", "validation_paths", "train_paths")
                     if k in kwargs})
        FullBatchImageLoader.__init__(self, workflow, **kwargs)

    def get_keys(self, klass):
        paths = (self.test_paths, self.validation_paths,
                 self.train_paths)[klass]
        return self.collect_keys(paths)

    def get_image_label(self, key):
        return self.get_label_from_filename(key)


@register_loader("auto_label_file_image")
class AutoLabelFileImageLoader(AutoLabelMixin, FileImageLoader):
    """Directory-scanned images labeled by path regexp, default = parent
    directory name (reference ``FullBatchAutoLabelFileImageLoader``,
    ``fullbatch_image.py:238-245``)."""

    def __init__(self, workflow, **kwargs):
        AutoLabelMixin.__init__(
            self, **{k: kwargs.pop(k) for k in ("label_regexp",)
                     if k in kwargs})
        FileImageLoader.__init__(self, workflow, **kwargs)


class FullBatchImageLoaderMSE(ImageLoaderMSEMixin, FullBatchImageLoader,
                              FullBatchLoaderMSE):
    """Device-resident image dataset with image targets (reference
    ``ImageLoaderMSE``, ``image_mse.py:119-124``). Subclasses provide the
    image source contract plus :meth:`get_target_keys`."""

    hide_from_registry = True


@register_loader("file_image_mse")
class FileImageLoaderMSE(FileFilter, FileScannerMixin,
                         FullBatchImageLoaderMSE):
    """Directory-scanned images with directory-scanned image targets
    (reference ``FileImageLoaderMSE``, ``image_mse.py:126-158``):
    ``target_paths`` roots are scanned with the same MIME filter."""

    def __init__(self, workflow, **kwargs):
        self.target_paths = kwargs.pop("target_paths")
        FileScannerMixin._check_paths(self.target_paths)
        kwargs.setdefault("file_type", "image")
        kwargs.setdefault("file_subtypes", ["png", "jpeg", "bmp"])
        FileFilter.__init__(
            self, **{k: kwargs.pop(k) for k in
                     ("ignored_files", "included_files", "file_type",
                      "file_subtypes") if k in kwargs})
        FileScannerMixin.__init__(
            self, **{k: kwargs.pop(k) for k in
                     ("test_paths", "validation_paths", "train_paths")
                     if k in kwargs})
        FullBatchImageLoaderMSE.__init__(self, workflow, **kwargs)

    def get_keys(self, klass):
        paths = (self.test_paths, self.validation_paths,
                 self.train_paths)[klass]
        return self.collect_keys(paths)

    def get_target_keys(self):
        return self.collect_keys(self.target_paths)

    def get_image_label(self, key):
        try:
            return self.get_label_from_filename(key)
        except NotImplementedError:
            # autoencoder-style unlabeled MSE: i-th sample <-> i-th target
            return None


@register_loader("file_list_image")
class FileListImageLoader(FileListScannerMixin, FullBatchImageLoader):
    """Images enumerated by index files (text ``path label`` lines or a
    JSON map; reference ``FileListImageLoader``, ``file_image.py:53`` +
    ``file_loader.py:150-203``)."""

    def __init__(self, workflow, **kwargs):
        FileListScannerMixin.__init__(
            self, **{k: kwargs.pop(k) for k in
                     ("path_to_test_text_file", "path_to_val_text_file",
                      "path_to_train_text_file", "base_directory")
                     if k in kwargs})
        FullBatchImageLoader.__init__(self, workflow, **kwargs)

    def get_keys(self, klass):
        index = (self.path_to_test_text_file, self.path_to_val_text_file,
                 self.path_to_train_text_file)[klass]
        if not index:
            return []
        return self.scan_files(index)

    def get_image_label(self, key):
        return self.get_label_from_filename(key)
