"""Minibatch stream saver and replay loader.

TPU-native re-design of reference ``veles/loader/saver.py:69-296``
(MinibatchesSaver / MinibatchesLoader): a Unit linked after any Loader
records every served minibatch to a compressed stream file; the companion
loader later replays that file as a dataset — freezing an expensive
preprocessing pipeline (image decode/augment) into a flat fast format.

Format: ``pickle(header) | chunk* | pickle(offset_table) | uint64 tail``
where each chunk is an independently-compressed pickle of
``(klass, valid, data, labels)`` and the tail points at the offset table
(the reference appended the table without a back-pointer and relied on
reading chunks sequentially; the tail makes random access O(1)).
Codecs: raw/gz/bz2/xz (reference also had snappy — not in this image).
"""

import bz2
import gzip
import lzma
import io
import os
import pickle
import struct

import numpy

import jax.numpy as jnp

from veles_tpu.core.config import root
from veles_tpu.core.units import Unit
from veles_tpu.loader.base import Loader, register_loader

CODECS = {
    "raw": (lambda b: b, lambda b: b),
    "gz": (gzip.compress, gzip.decompress),
    "bz2": (bz2.compress, bz2.decompress),
    "xz": (lzma.compress, lzma.decompress),
}


class MinibatchesSaver(Unit):
    """Dump every served minibatch to a stream file (reference
    ``MinibatchesSaver``, ``saver.py:69-174``). Link it after the loader:
    ``saver.link_from(loader)`` + ``saver.link_attrs(loader, ...)``.

    The loader must have shuffling disabled (``shuffle_limit=0``) so the
    recorded epoch is deterministic — same check as the reference."""

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, **kwargs):
        self.file_name = os.path.abspath(kwargs.pop(
            "file_name",
            os.path.join(root.common.dirs.get("cache", "."),
                         "minibatches.dat")))
        self.compression = kwargs.pop("compression", "gz")
        if self.compression not in CODECS:
            raise ValueError("unknown compression %r (have %s)"
                             % (self.compression, sorted(CODECS)))
        super().__init__(workflow, **kwargs)
        self.offset_table = []
        self.demand("minibatch_data", "minibatch_labels", "minibatch_class",
                    "minibatch_valid_size", "class_lengths",
                    "max_minibatch_size")

    def init_unpickled(self):
        super().init_unpickled()
        self._file_ = None

    def initialize(self, **kwargs):
        loader = getattr(self.workflow, "loader", None)
        if loader is not None and loader.shuffle_limit != 0:
            raise ValueError(
                "disable shuffling in the loader (shuffle_limit=0) so the "
                "recorded stream is deterministic")
        self._file_ = open(self.file_name, "wb")
        header = {
            "compression": self.compression,
            "class_lengths": list(self.class_lengths),
            "max_minibatch_size": int(self.max_minibatch_size),
            "data_shape": tuple(self.minibatch_data.shape),
            "labels_shape": (tuple(self.minibatch_labels.shape)
                             if self.minibatch_labels else None),
            "labels_mapping": dict(getattr(
                loader, "labels_mapping", {}) or {}),
        }
        pickle.dump(header, self._file_, protocol=4)

    def run(self):
        data = numpy.asarray(self.minibatch_data.mem)
        labels = (numpy.asarray(self.minibatch_labels.mem)
                  if self.minibatch_labels else None)
        payload = (int(self.minibatch_class),
                   int(self.minibatch_valid_size), data, labels)
        blob = CODECS[self.compression][0](
            pickle.dumps(payload, protocol=4))
        # (class, offset) pairs: replay builds its chunk directory from
        # the table alone, without decompressing any chunk
        self.offset_table.append(
            (int(self.minibatch_class), self._file_.tell()))
        self._file_.write(struct.pack("<Q", len(blob)))
        self._file_.write(blob)

    def stop(self):
        if self._file_ is None or self._file_.closed:
            return
        table_pos = self._file_.tell()
        pickle.dump(self.offset_table, self._file_, protocol=4)
        self._file_.write(struct.pack("<Q", table_pos))
        self._file_.close()
        self.info("wrote %s (%d minibatches)", self.file_name,
                  len(self.offset_table))


@register_loader("minibatches")
class MinibatchesLoader(Loader):
    """Replay a recorded minibatch stream as a dataset (reference
    ``MinibatchesLoader``, ``saver.py:182-296``).

    Serving is index-exact: chunk ``i`` of a class holds rows
    ``[i*mb, (i+1)*mb)`` of that class (shuffling was disabled when
    recording), so any global sample index maps straight to
    (chunk, row). A one-chunk LRU keeps sequential replay cheap."""

    def __init__(self, workflow, **kwargs):
        self.file_name = kwargs.pop("file_name")
        super().__init__(workflow, **kwargs)
        self.shuffle_limit = 0  # replay preserves recorded order

    def init_unpickled(self):
        super().init_unpickled()
        self._file_ = None
        self._chunk_index_ = None
        self._cache_ = (None, None)

    def load_data(self):
        self._file_ = open(self.file_name, "rb")
        self._header = pickle.load(self._file_)
        self.class_lengths = list(self._header["class_lengths"])
        if self.minibatch_size != self._header["max_minibatch_size"]:
            self.info("minibatch_size %d -> %d (recorded)",
                      self.minibatch_size,
                      self._header["max_minibatch_size"])
            self.minibatch_size = self._header["max_minibatch_size"]
        self.labels_mapping.update(self._header.get("labels_mapping", {}))
        self._reversed_labels_mapping = sorted(self.labels_mapping)
        # chunk directory: per class, ordered file offsets
        self._file_.seek(-8, io.SEEK_END)
        table_pos, = struct.unpack("<Q", self._file_.read(8))
        self._file_.seek(table_pos)
        offsets = pickle.load(self._file_)
        self._chunk_index_ = {0: [], 1: [], 2: []}
        for klass, off in offsets:
            self._chunk_index_[klass].append(off)

    def _read_chunk(self, offset):
        self._file_.seek(offset)
        size, = struct.unpack("<Q", self._file_.read(8))
        blob = self._file_.read(size)
        return pickle.loads(
            CODECS[self._header["compression"]][1](blob))

    def _chunk(self, offset):
        if self._cache_[0] != offset:
            self._cache_ = (offset, self._read_chunk(offset))
        return self._cache_[1]

    def create_minibatch_data(self):
        mb = self.max_minibatch_size
        self.minibatch_data.reset(numpy.zeros(
            (mb,) + tuple(self._header["data_shape"][1:]), numpy.float32))
        if self._header["labels_shape"] is not None:
            self.minibatch_labels.reset(numpy.zeros(mb, numpy.int32))
        self.minibatch_indices.reset(numpy.zeros(mb, numpy.int64))
        self.sample_mask.reset(numpy.zeros(mb, numpy.float32))

    def fill_minibatch(self, indices, valid):
        mb = self.max_minibatch_size
        batch = numpy.zeros(self.minibatch_data.shape, numpy.float32)
        labels = numpy.zeros(len(indices), numpy.int32)
        for i, gi in enumerate(indices[:valid]):
            gi = int(gi)
            for klass in (0, 1, 2):
                offset = self.class_offset(klass)
                if gi < offset + self.class_lengths[klass]:
                    local = gi - offset
                    break
            chunk_off = self._chunk_index_[klass][local // mb]
            _, _, data, labs = self._chunk(chunk_off)
            batch[i] = data[local % mb]
            if labs is not None:
                labels[i] = labs[local % mb]
        mask = (numpy.arange(len(indices)) < valid).astype(numpy.float32)
        self.minibatch_data.data = jnp.asarray(batch)
        if self._header["labels_shape"] is not None:
            self.minibatch_labels.data = jnp.asarray(labels)
        self.sample_mask.data = jnp.asarray(mask)
        self.minibatch_indices.data = jnp.asarray(indices)
