"""veles_tpu.loader: the data layer (reference ``veles/loader/``)."""

from veles_tpu.loader.base import (  # noqa: F401
    Loader, TEST, VALID, TRAIN, CLASS_NAMES)
from veles_tpu.loader.fullbatch import FullBatchLoader  # noqa: F401
