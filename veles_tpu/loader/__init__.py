"""veles_tpu.loader: the data layer (reference ``veles/loader/``)."""

from veles_tpu.loader.base import (  # noqa: F401
    Loader, LoaderMSEMixin, TEST, VALID, TRAIN, CLASS_NAMES)
from veles_tpu.loader.fullbatch import (  # noqa: F401
    FullBatchLoader, FullBatchLoaderMSE)
from veles_tpu.loader.normalization import (  # noqa: F401
    make_normalizer, normalizer_registry)
from veles_tpu.loader.image import (  # noqa: F401
    AutoLabelFileImageLoader, FileImageLoader, FileImageLoaderMSE,
    FileListImageLoader, FullBatchImageLoader, FullBatchImageLoaderMSE)
