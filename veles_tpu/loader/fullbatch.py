"""FullBatchLoader: whole dataset resident on device, minibatch by gather.

Reference ``veles/loader/fullbatch.py``: the dataset lives in
``original_data``/``original_labels`` Arrays, optionally device-resident,
and minibatches are gathered by the ``fill_minibatch_data_labels`` kernel
(``cuda/fullbatch_loader.cu``). TPU design: the originals are jax.Arrays in
HBM and the fill is one jitted gather+normalize (``ops.gather_minibatch``) —
for MNIST-scale sets this keeps the whole data path on device; the
graceful OOM fallback (reference ``fullbatch.py:170-242``) keeps originals
in host numpy and gathers there instead.

Subclasses (or callers via ``data=``/``labels=`` kwargs) provide the actual
dataset; class splits come from ``class_lengths`` or the
``validation_ratio`` resplit.
"""

import numpy

import jax
import jax.numpy as jnp

from veles_tpu.loader.base import Loader, TRAIN, VALID, register_loader
from veles_tpu.memory import Array
from veles_tpu.ops.gather import gather_minibatch
from veles_tpu.ops.normalize import mean_disp_normalize


@register_loader("full_batch")
class FullBatchLoader(Loader):
    """Device-resident full-batch loader (reference ``fullbatch.py:79``)."""

    def __init__(self, workflow, **kwargs):
        self.on_device = kwargs.pop("on_device", True)
        self.normalization_type = kwargs.pop("normalization_type", "none")
        self.validation_ratio = kwargs.pop("validation_ratio", None)
        data = kwargs.pop("data", None)
        labels = kwargs.pop("labels", None)
        lengths = kwargs.pop("class_lengths", None)
        super().__init__(workflow, **kwargs)
        self.original_data = Array()
        self.original_labels = Array()
        self._provided_data = data
        self._provided_labels = labels
        self._provided_lengths = lengths
        self.normalizer_state = None

    # -- ILoader --------------------------------------------------------------
    def load_data(self):
        if self._provided_data is None:
            raise NotImplementedError(
                "%s: override load_data() or pass data=" % self.name)
        data = numpy.asarray(self._provided_data, numpy.float32)
        self.original_data.reset(data)
        if self._provided_labels is not None:
            self.original_labels.reset(
                numpy.asarray(self._provided_labels, numpy.int32))
        if self._provided_lengths is not None:
            self.class_lengths = list(self._provided_lengths)
        else:
            self.class_lengths = [0, 0, len(data)]
        if self.validation_ratio:
            self._resplit_validation()
        self._analyze_normalization()
        if self.on_device:
            try:
                self.original_data.to_device()
                if self.original_labels:
                    self.original_labels.to_device()
            except Exception as exc:
                # graceful fallback to host gather (reference OOM path)
                self.warning("keeping dataset on host: %s", exc)
                self.on_device = False

    def _resplit_validation(self):
        """Move the tail of TRAIN into VALID (reference
        ``validation_ratio`` resplit)."""
        n_valid = int(self.class_lengths[TRAIN] * self.validation_ratio)
        # layout is [test | valid | train]; splice the LAST n_valid train
        # rows in after the existing valid block so all three stay contiguous
        valid_end = self.class_offset(TRAIN)
        self.class_lengths[VALID] += n_valid
        self.class_lengths[TRAIN] -= n_valid

        def splice(arr):
            return numpy.concatenate([
                arr[:valid_end], arr[len(arr) - n_valid:],
                arr[valid_end:len(arr) - n_valid]])

        self.original_data.reset(splice(self.original_data.mem))
        if self.original_labels:
            self.original_labels.reset(splice(self.original_labels.mem))

    def _analyze_normalization(self):
        """One pass over the train set for normalizer statistics
        (reference ``loader/base.py:755-802``)."""
        if self.normalization_type == "none":
            return
        start = self.class_offset(TRAIN)
        train = self.original_data.mem[
            start:start + self.class_lengths[TRAIN]]
        if not len(train):  # no train split (e.g. pure evaluation runs)
            train = self.original_data.mem
        if self.normalization_type == "mean_disp":
            # host-side numpy: a device transfer of the whole train split
            # here would defeat the OOM fallback below
            mean = train.mean(axis=0)
            disp = train.max(axis=0) - train.min(axis=0)
            rdisp = 1.0 / numpy.maximum(disp, 1e-8)
            self.normalizer_state = {"mean": mean, "rdisp": rdisp}
        elif self.normalization_type == "linear":
            vmax = float(numpy.max(numpy.abs(train))) or 1.0
            self.normalizer_state = {"scale": 1.0 / vmax}
        else:
            raise ValueError("unknown normalization_type %r"
                             % self.normalization_type)

    def create_minibatch_data(self):
        size = self.max_minibatch_size
        sample_shape = self.original_data.shape[1:]
        self.minibatch_data.reset(
            numpy.zeros((size,) + sample_shape, numpy.float32))
        if self.original_labels:
            self.minibatch_labels.reset(numpy.zeros(size, numpy.int32))
        self.minibatch_indices.reset(numpy.zeros(size, numpy.int64))
        self.sample_mask.reset(numpy.zeros(size, numpy.float32))

    def init_unpickled(self):
        super().init_unpickled()
        self._fill_jit_ = None

    @property
    def _fill_jit(self):
        if self._fill_jit_ is None:
            norm = self.normalizer_state or {}
            norm_type = self.normalization_type

            @jax.jit
            def fill(data, labels, indices, valid):
                batch, lab = gather_minibatch(data, indices, labels)
                if norm_type == "mean_disp":
                    batch = mean_disp_normalize(
                        batch, norm["mean"], norm["rdisp"])
                elif norm_type == "linear":
                    batch = batch * norm["scale"]
                mask = (jnp.arange(indices.shape[0]) < valid).astype(
                    jnp.float32)
                return batch, lab, mask

            self._fill_jit_ = fill
        return self._fill_jit_

    def fill_minibatch(self, indices, valid):
        idx = jnp.asarray(indices)
        data = self.original_data.data
        labels = (self.original_labels.data if self.original_labels
                  else jnp.zeros(len(self.original_data), jnp.int32))
        if not self.on_device and not isinstance(data, jax.Array):
            # host gather path
            batch = numpy.take(numpy.asarray(data), indices, axis=0)
            lab = numpy.take(numpy.asarray(labels), indices, axis=0)
            mask = (numpy.arange(len(indices)) < valid).astype(numpy.float32)
            if self.normalization_type == "mean_disp":
                batch = (batch - numpy.asarray(
                    self.normalizer_state["mean"])) * numpy.asarray(
                    self.normalizer_state["rdisp"])
            elif self.normalization_type == "linear":
                batch = batch * self.normalizer_state["scale"]
            self.minibatch_data.data = jnp.asarray(batch)
            self.minibatch_labels.data = jnp.asarray(lab)
            self.sample_mask.data = jnp.asarray(mask)
        else:
            batch, lab, mask = self._fill_jit(data, labels, idx,
                                              jnp.int32(valid))
            self.minibatch_data.data = batch
            self.minibatch_labels.data = lab
            self.sample_mask.data = mask
        self.minibatch_indices.data = idx
