"""FullBatchLoader: whole dataset resident on device, minibatch by gather.

Reference ``veles/loader/fullbatch.py``: the dataset lives in
``original_data``/``original_labels`` Arrays, optionally device-resident,
and minibatches are gathered by the ``fill_minibatch_data_labels`` kernel
(``cuda/fullbatch_loader.cu``). TPU design: the originals are jax.Arrays in
HBM and the fill is one jitted gather+normalize (``ops.gather_minibatch``) —
for MNIST-scale sets this keeps the whole data path on device; the
graceful OOM fallback (reference ``fullbatch.py:170-242``) keeps originals
in host numpy and gathers there instead.

Subclasses (or callers via ``data=``/``labels=`` kwargs) provide the actual
dataset; class splits come from ``class_lengths`` or the
``validation_ratio`` resplit.
"""

import numpy

import jax
import jax.numpy as jnp

from veles_tpu.loader.base import (Loader, LoaderMSEMixin, TRAIN, VALID,
                                   register_loader)
from veles_tpu.loader.normalization import make_normalizer
from veles_tpu.memory import Array
from veles_tpu.ops.gather import gather_minibatch


@register_loader("full_batch")
class FullBatchLoader(Loader):
    """Device-resident full-batch loader (reference ``fullbatch.py:79``)."""

    def __init__(self, workflow, **kwargs):
        self.on_device = kwargs.pop("on_device", True)
        self.normalization_type = kwargs.pop("normalization_type", "none")
        self.normalization_parameters = kwargs.pop(
            "normalization_parameters", {})
        self.validation_ratio = kwargs.pop("validation_ratio", None)
        #: in-jit TRAIN-minibatch augmentation by name ("mirror",
        #: "shift1" — ops/augment.TRANSFORMS); needs NHWC data. The
        #: reference reached augmentation only through the image-loader
        #: family (mirror/crop offsets, ``loader/image.py``); array
        #: datasets get the same tier here
        self.train_transform = kwargs.pop("train_transform", None)
        data = kwargs.pop("data", None)
        labels = kwargs.pop("labels", None)
        lengths = kwargs.pop("class_lengths", None)
        super().__init__(workflow, **kwargs)
        if self.train_transform is not None:
            from veles_tpu.ops.augment import TRANSFORMS
            if self.train_transform not in TRANSFORMS:
                raise ValueError(
                    "unknown train_transform %r (known: %s)"
                    % (self.train_transform,
                       ", ".join(sorted(TRANSFORMS))))
        self.original_data = Array()
        self.original_labels = Array()
        self._provided_data = data
        self._provided_labels = labels
        self._provided_lengths = lengths
        self._raw_labels = None
        self.normalizer = None

    # -- ILoader --------------------------------------------------------------
    def load_data(self):
        if self._provided_data is None:
            raise NotImplementedError(
                "%s: override load_data() or pass data=" % self.name)
        data = numpy.asarray(self._provided_data, numpy.float32)
        if self.train_transform is not None and data.ndim != 4:
            raise ValueError(
                "train_transform %r needs NHWC data, got shape %s"
                % (self.train_transform, data.shape))
        self.original_data.reset(data)
        if self._provided_labels is not None:
            self._raw_labels = numpy.asarray(self._provided_labels)
        if self._provided_lengths is not None:
            self.class_lengths = list(self._provided_lengths)
        else:
            self.class_lengths = [0, 0, len(data)]
        if self.validation_ratio:
            self._resplit_validation()
        self._analyze_normalization()
        if self.on_device:
            try:
                self.original_data.to_device()
            except Exception as exc:
                # graceful fallback to host gather (reference OOM path)
                self.warning("keeping dataset on host: %s", exc)
                self.on_device = False

    def get_raw_labels(self):
        return self._raw_labels

    def analyze_dataset(self):
        """Label mapping first (base), then materialize the int32 label
        array the device gather uses."""
        super().analyze_dataset()
        if self._raw_labels is not None:
            self.original_labels.reset(self.map_labels(self._raw_labels))
            if self.on_device:
                try:
                    self.original_labels.to_device()
                except Exception as exc:
                    self.warning("keeping labels on host: %s", exc)
                    self.on_device = False

    def _resplit_validation(self):
        """Move the tail of TRAIN into VALID (reference
        ``validation_ratio`` resplit)."""
        n_valid = int(self.class_lengths[TRAIN] * self.validation_ratio)
        # layout is [test | valid | train]; splice the LAST n_valid train
        # rows in after the existing valid block so all three stay contiguous
        valid_end = self.class_offset(TRAIN)
        total = self.total_samples
        self.class_lengths[VALID] += n_valid
        self.class_lengths[TRAIN] -= n_valid
        perm = numpy.concatenate([
            numpy.arange(valid_end),
            numpy.arange(total - n_valid, total),
            numpy.arange(valid_end, total - n_valid)])
        self._apply_resplit(perm)

    def _apply_resplit(self, perm):
        """Apply the resplit permutation to every per-sample array; MSE
        subclasses extend this to keep targets row-aligned."""
        self.original_data.reset(self.original_data.mem[perm])
        if self._raw_labels is not None:
            self._raw_labels = self._raw_labels[perm]

    def _analyze_normalization(self):
        """One pass over the train set accumulating normalizer statistics
        (reference ``loader/base.py:755-802``). Host-side numpy: a device
        transfer of the whole train split here would defeat the OOM
        fallback in load_data."""
        self.normalizer = make_normalizer(self.normalization_type,
                                          **self.normalization_parameters)
        if self.normalizer.STATELESS:
            return
        start = self.class_offset(TRAIN)
        train = self.original_data.mem[
            start:start + self.class_lengths[TRAIN]]
        if not len(train):  # no train split (e.g. pure evaluation runs)
            train = self.original_data.mem
        self.normalizer.analyze(train)

    def create_minibatch_data(self):
        size = self.max_minibatch_size
        sample_shape = self.original_data.shape[1:]
        self.minibatch_data.reset(
            numpy.zeros((size,) + sample_shape, numpy.float32))
        if self.original_labels:
            self.minibatch_labels.reset(numpy.zeros(size, numpy.int32))
        self.minibatch_indices.reset(numpy.zeros(size, numpy.int64))
        self.sample_mask.reset(numpy.zeros(size, numpy.float32))

    #: fused-engine contract (same as the image loaders): fill-time
    #: transforms force graph mode unless the tick replicates them
    @property
    def has_fill_transforms(self):
        return self.train_transform is not None

    @property
    def jit_transform(self):
        return self.train_transform

    def init_unpickled(self):
        super().init_unpickled()
        self._fill_jit_ = None
        self._zero_labels_ = None
        self._transform_jit_ = None

    @property
    def _fill_jit(self):
        if self._fill_jit_ is None:
            normalizer = self.normalizer

            @jax.jit
            def fill(data, labels, indices, valid):
                batch, lab = gather_minibatch(data, indices, labels)
                # normalizer coefficients fold in as XLA constants and the
                # elementwise math fuses into the gather (retires the
                # reference's mean_disp_normalizer kernel)
                batch = normalizer.apply_batch(jnp, batch)
                mask = (jnp.arange(indices.shape[0]) < valid).astype(
                    jnp.float32)
                return batch, lab, mask

            self._fill_jit_ = fill
        return self._fill_jit_

    def labels_for_gather(self):
        """The label lane every in-jit gather consumes (the loader's
        fill, the fused tick, the sweep tier): the device labels, or —
        for label-less (MSE) datasets — a cached dataset-length zeros
        placeholder (a fresh jnp.zeros would be an eager dispatch plus
        a full-length allocation per tick)."""
        if self.original_labels:
            return self.original_labels.data
        if self._zero_labels_ is None \
                or len(self._zero_labels_) != len(self.original_data):
            self._zero_labels_ = jnp.zeros(
                len(self.original_data), jnp.int32)
        return self._zero_labels_

    def fill_minibatch(self, indices, valid):
        data = self.original_data.data
        labels = self.labels_for_gather()
        if not self.on_device and not isinstance(data, jax.Array):
            # host gather path
            batch = numpy.take(numpy.asarray(data), indices, axis=0)
            lab = numpy.take(numpy.asarray(labels), indices, axis=0)
            mask = (numpy.arange(len(indices)) < valid).astype(numpy.float32)
            batch = self.normalizer.apply_batch(numpy, batch)
            self.minibatch_data.data = jnp.asarray(batch)
            self.minibatch_labels.data = jnp.asarray(lab)
            self.sample_mask.data = jnp.asarray(mask)
            self.minibatch_indices.data = jnp.asarray(indices)
            return
        # the host indices and valid count ride the jit dispatch itself —
        # eager jnp.asarray/jnp.int32 here would each be a separate
        # device_put dispatch per tick
        batch, lab, mask = self._fill_jit(data, labels, indices,
                                          numpy.int32(valid))
        if self.train_transform and self.minibatch_class == TRAIN:
            if self._transform_jit_ is None:
                from veles_tpu.ops.augment import TRANSFORMS
                self._transform_jit_ = jax.jit(
                    TRANSFORMS[self.train_transform])
            batch = self._transform_jit_(
                batch, int(self.draw_transform_seeds(1)[0]))
        self.minibatch_data.data = batch
        self.minibatch_labels.data = lab
        self.sample_mask.data = mask
        # host numpy: consumers (fused tick, snapshot replays) feed it
        # back into jit calls, where it rides those dispatches — an
        # eager jnp.asarray here would re-upload it a second time
        self.minibatch_indices.data = indices


@register_loader("full_batch_mse")
class FullBatchLoaderMSE(LoaderMSEMixin, FullBatchLoader):
    """Full-batch loader with regression targets (reference
    ``loader/fullbatch.py`` FullBatchLoaderMSE + ``base.py:1147``).

    Targets live beside the data as a device-resident ``original_targets``
    array; the minibatch target gather rides the same jitted fill. The
    target normalizer accumulates over the train split and its
    ``denormalize()`` maps network output back to target units."""

    def __init__(self, workflow, **kwargs):
        targets = kwargs.pop("targets", None)
        super().__init__(workflow, **kwargs)
        self.original_targets = Array()
        self._provided_targets = targets

    def _apply_resplit(self, perm):
        super()._apply_resplit(perm)
        # targets must stay row-aligned with the respliced data
        self._provided_targets = self._provided_targets[perm]

    def load_data(self):
        if self._provided_targets is None:
            raise NotImplementedError(
                "%s: override load_data() or pass targets=" % self.name)
        self._provided_targets = numpy.asarray(
            self._provided_targets, numpy.float32)
        super().load_data()
        targets = self._provided_targets
        if len(targets) != self.total_samples:
            raise ValueError(
                "targets length %d != total samples %d"
                % (len(targets), self.total_samples))
        self.target_normalizer = make_normalizer(
            self.target_normalization_type,
            **self.target_normalization_parameters)
        start = self.class_offset(TRAIN)
        train = targets[start:start + self.class_lengths[TRAIN]]
        if not self.target_normalizer.STATELESS:
            self.target_normalizer.analyze(
                train if len(train) else targets)
        self.original_targets.reset(
            numpy.asarray(self.target_normalizer.apply_batch(
                numpy, targets), numpy.float32))
        if not self.targets_shape:
            self.targets_shape = targets.shape[1:]
        if self.on_device:
            try:
                self.original_targets.to_device()
            except Exception as exc:
                self.warning("keeping targets on host: %s", exc)
                self.on_device = False

    def create_minibatch_data(self):
        super().create_minibatch_data()
        size = self.max_minibatch_size
        self.minibatch_targets.reset(numpy.zeros(
            (size,) + tuple(self.targets_shape), numpy.float32))

    def fill_minibatch(self, indices, valid):
        super().fill_minibatch(indices, valid)
        targets = self.original_targets.data
        if isinstance(targets, jax.Array):
            gathered = jnp.take(targets, jnp.asarray(indices), axis=0)
        else:
            gathered = jnp.asarray(
                numpy.take(numpy.asarray(targets), indices, axis=0))
        self.minibatch_targets.data = gathered
