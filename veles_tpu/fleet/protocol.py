"""Wire protocol: length-prefixed, HMAC-authenticated pickled frames.

Replaces the reference's two-plane fabric (Twisted JSON-lines control +
ZeroMQ streaming-pickle data, ``network_common.py`` + ``txzmq/``) with one
asyncio stream. Frames:

    [4-byte big-endian length][1-byte codec][32-byte HMAC-SHA256][payload]

codec 0 = raw pickle, 1 = gzip pickle (auto-chosen by size, mirroring the
reference's pluggable chunk compression), 2 = safe (pickle-free, see
``fleet/safecodec.py``), 3 = gzip safe. Messages are dicts with a "type"
key; job/update payloads ride inside them (the units' generate/apply
contracts define their content).

Message schema (master <-> slave, after the hello/welcome handshake):

- ``welcome``: ``id`` (slave id), ``shm`` (shared-memory negotiated),
  ``epoch`` (the master's per-start fencing UUID), ``initial``;
- ``job``: ``job`` (payload list, ``None`` = no more jobs), ``job_id``
  (monotonic lease id, see ``fleet/ledger.py``), ``epoch``, ``paused``;
- ``update``: ``job_id`` + ``epoch`` echoed from the job (the master
  fences mismatches instead of applying them), optional ``chaos``
  (fault-injection tallies, ``fleet/chaos.py``). Payload by wire
  plane (``root.common.fleet.plane``, docs/compiler_fleet.md):
  ``update`` (the data-plane per-unit payload list, weights included)
  or — control plane — ``results`` (scalar metrics list) + ``tick``
  (the slave's local applied-job counter; a control-plane master
  REJECTS frames carrying an ``update`` key). Observability freight
  rides along (observe/fleetscope.py; every field validated + bounded
  at ingestion, the hostile-slave doctrine): ``mono`` ([job-receipt,
  update-send] slave monotonic stamps — the slave half of the
  master's NTP-style clock alignment), ``job_ms`` (the workflow's own
  job wall, so the master can split compute from host residence),
  ``spans`` (completed-span summary rows ``[name, trace_id, span_id,
  parent_id, t0, dur_ms, tid]``, at most SPAN_SHIP_MAX_ROWS per
  frame), ``rollback_ms`` (cumulative rollback-discarded compute —
  wasted-work accounting), plus the pre-existing ``metrics`` /
  ``history`` snapshot piggybacks;
- ``update_ack``: optional ``fenced`` (the rejection verdict — the
  slave must not answer a fenced ack with another job_request);
- ``sync`` (control plane only): ``sync`` (per-unit epoch-fence weight
  payload), ``job_id`` (the accepted fence job it chases), ``epoch``,
  ``tick`` — the only post-handshake frames that carry weights;
  answered by ``sync_ack`` (optional ``fenced``);
- ``job`` additionally carries ``acked`` in control-plane mode (the
  master's highest accepted slave tick — the rollback protocol);
- ``hello`` carries ``plane``; the master fails the handshake on a
  mismatch;
- ``job_request`` / ``power`` / ``bye``: as in the reference.

Security: EVERY frame — including the pre-handshake hello — is
authenticated with a shared-secret HMAC verified *before* any
decompression or deserialization; a peer without the secret cannot reach
``pickle.loads``. The secret comes from (in priority order) an explicit
argument, ``$VELES_TPU_FLEET_SECRET``, ``root.common.fleet.secret``, or
defaults to the workflow checksum — which both sides must share anyway
(the reference's compatibility check, ``workflow.py:847-862``), so
possession of the workflow file is the minimum bar. Masters bind
127.0.0.1 unless an interface is given.

Defense in depth: ``root.common.fleet.codec = "safe"`` (set on EVERY
host — the wire codec is not negotiable, by design: a negotiation could
be downgraded) moves the whole wire to the pickle-free codec and makes
the receiver REJECT pickle frames outright, so even a leaked secret is
no longer remote code execution — at worst bogus data. The default stays
"pickle" for payload-generality parity with the reference's wire.
"""

import gzip
import hashlib
import zlib
import hmac as hmac_lib
import os
import pickle
import struct
import uuid

COMPRESS_THRESHOLD = 64 * 1024
MAX_FRAME = 1 << 30

_HEADER = struct.Struct(">IB")
_MAC_SIZE = hashlib.sha256().digest_size


class ProtocolError(Exception):
    """Malformed or unauthenticated frame."""


def resolve_secret(workflow=None, secret=None, with_source=False):
    """The shared fleet secret as bytes (see module docstring). With
    ``with_source=True`` returns ``(secret, source)`` where source is one
    of "explicit"/"env"/"config"/"checksum"."""
    source = "explicit"
    if secret is None:
        secret = os.environ.get("VELES_TPU_FLEET_SECRET")
        source = "env"
    if secret is None:
        from veles_tpu.core.config import root
        secret = root.common.fleet.get("secret")
        source = "config"
    if secret is None and workflow is not None:
        secret = getattr(workflow, "checksum", None)
        source = "checksum"
    if secret is None:
        raise ProtocolError(
            "no fleet secret: pass secret=, set VELES_TPU_FLEET_SECRET "
            "or root.common.fleet.secret, or give the workflow a checksum")
    if isinstance(secret, str):
        secret = secret.encode()
    return (secret, source) if with_source else secret


def _mac(key, codec, payload):
    return hmac_lib.new(key, bytes([codec]) + payload,
                        hashlib.sha256).digest()


def _wire_codec():
    """The configured serialization family: "pickle" (default) or
    "safe". Read per frame so tests/configs can flip it live."""
    from veles_tpu.core.config import root
    codec = root.common.fleet.get("codec", "pickle")
    if codec not in ("pickle", "safe"):
        raise ProtocolError(
            "root.common.fleet.codec must be 'pickle' or 'safe', got %r"
            % (codec,))
    return codec


def _serialize(message):
    if _wire_codec() == "safe":
        from veles_tpu.fleet import safecodec
        return safecodec.dumps(message), 2
    return pickle.dumps(message, protocol=pickle.HIGHEST_PROTOCOL), 0


def _deserialize(payload, codec):
    if codec in (0, 1):
        if _wire_codec() != "pickle":
            raise ProtocolError(
                "received a pickle frame but this host is configured "
                "with the safe fleet codec — set root.common.fleet."
                "codec identically on every fleet host")
        return pickle.loads(payload)
    from veles_tpu.fleet import safecodec
    try:
        return safecodec.loads(payload)
    except (safecodec.UnsupportedType, KeyError, ValueError, TypeError,
            IndexError, RecursionError, struct.error) as exc:
        # ANY malformed-but-authenticated frame must surface as a
        # protocol violation (the session handlers drop the peer and
        # keep the fleet alive) — never as a raw exception that would
        # kill the client/server loop: safe mode's threat model says a
        # secret holder gets at most bogus data, not a DoS
        raise ProtocolError("bad safe frame: %s: %s"
                            % (type(exc).__name__, exc))


def encode_frame(message, key, shm_threshold=None):
    """``shm_threshold``: when set (same-host connections, negotiated at
    handshake by machine id — reference ``server.py:721-732``), payloads
    at least that large move through a shared-memory segment
    (``fleet/sharedio.py``) and only a descriptor frame hits the wire."""
    payload, codec = _serialize(message)
    if shm_threshold is not None and len(payload) >= shm_threshold:
        from veles_tpu.fleet import sharedio
        desc = sharedio.put(payload, key)
        payload, codec = _serialize({"__shm__": desc})
    if len(payload) > MAX_FRAME:
        # bound the UNCOMPRESSED size too: the receiver enforces the
        # limit on the decompressed payload (_bounded_gunzip), so a
        # compressible >1 GiB payload that fit on the wire would be
        # rejected at the far end — fail here with the clear message
        raise ProtocolError(
            "outgoing %r frame is %d bytes uncompressed (limit %d): "
            "shrink the job/update payload"
            % (message.get("type", "?"), len(payload), MAX_FRAME))
    if len(payload) >= COMPRESS_THRESHOLD:
        compressed = gzip.compress(payload, compresslevel=1)
        if len(compressed) < len(payload):
            payload, codec = compressed, codec + 1
    return (_HEADER.pack(len(payload), codec) + _mac(key, codec, payload)
            + payload)


def _bounded_gunzip(payload, max_frame):
    """Decompress a gzip member with the frame limit applied to the
    DECOMPRESSED size too: MAX_FRAME on the wire length alone would let
    an authenticated peer detonate a ~1000x gzip bomb in memory, which
    contradicts the safe codec's "a leaked secret yields at most bogus
    data, not a DoS" threat model. wbits=31 selects the gzip container
    (the sender uses gzip.compress)."""
    decompressor = zlib.decompressobj(wbits=31)
    try:
        data = decompressor.decompress(payload, max_frame + 1)
    except zlib.error as exc:
        raise ProtocolError("bad gzip frame: %s" % exc)
    if len(data) > max_frame or decompressor.unconsumed_tail:
        raise ProtocolError(
            "decompressed frame exceeds limit %d" % max_frame)
    if not decompressor.eof or decompressor.unused_data:
        # keep gzip.decompress's strictness: a truncated member or
        # trailing garbage is a protocol violation, not partial data
        raise ProtocolError("malformed gzip frame (truncated or "
                            "trailing data)")
    return data


async def read_frame(reader, key, max_frame=MAX_FRAME):
    """``max_frame`` caps the pre-verification buffer: servers read the
    pre-auth hello with a small cap so an unauthenticated peer cannot make
    us buffer a giant bogus payload before the MAC check rejects it."""
    header = await reader.readexactly(_HEADER.size)
    length, codec = _HEADER.unpack(header)
    if length > max_frame:
        raise ProtocolError("frame length %d exceeds limit %d"
                            % (length, max_frame))
    mac = await reader.readexactly(_MAC_SIZE)
    payload = await reader.readexactly(length)
    if not hmac_lib.compare_digest(mac, _mac(key, codec, payload)):
        raise ProtocolError("frame failed HMAC authentication")
    if codec not in (0, 1, 2, 3):
        raise ProtocolError("unknown frame codec %d" % codec)
    if codec in (1, 3):
        payload = _bounded_gunzip(payload, max_frame)
        codec -= 1
    message = _deserialize(payload, codec)
    if isinstance(message, dict) and "__shm__" in message:
        from veles_tpu.fleet import sharedio
        try:
            payload = sharedio.get(message["__shm__"], key)
        except (OSError, ValueError) as exc:
            raise ProtocolError("bad shared-memory frame: %s" % exc)
        message = _deserialize(payload, codec)
    return message


async def write_frame(writer, message, key, shm_threshold=None):
    writer.write(encode_frame(message, key, shm_threshold))
    await writer.drain()


def decode_frame_bytes(data, key, max_frame=MAX_FRAME):
    """Synchronous decode of ONE encoded frame (the buffer twin of
    :func:`read_frame`, same MAC/codec/bounds rules) — for benches and
    tests that hold the whole frame in memory instead of a stream."""
    if len(data) < _HEADER.size + _MAC_SIZE:
        raise ProtocolError("truncated frame")
    length, codec = _HEADER.unpack(data[:_HEADER.size])
    if length > max_frame:
        raise ProtocolError("frame length %d exceeds limit %d"
                            % (length, max_frame))
    mac = data[_HEADER.size:_HEADER.size + _MAC_SIZE]
    payload = data[_HEADER.size + _MAC_SIZE:]
    if len(payload) != length:
        raise ProtocolError("frame length mismatch")
    if not hmac_lib.compare_digest(mac, _mac(key, codec, payload)):
        raise ProtocolError("frame failed HMAC authentication")
    if codec not in (0, 1, 2, 3):
        raise ProtocolError("unknown frame codec %d" % codec)
    if codec in (1, 3):
        payload = _bounded_gunzip(payload, max_frame)
        codec -= 1
    return _deserialize(payload, codec)


def machine_id():
    """Stable per-host identity (reference ``network_common.py:72-118``
    derived it from dbus + MAC; /etc/machine-id is the modern source)."""
    for path in ("/etc/machine-id", "/var/lib/dbus/machine-id"):
        try:
            with open(path) as fin:
                return fin.read().strip()
        except OSError:
            continue
    return hashlib.sha1(uuid.getnode().to_bytes(6, "big")).hexdigest()


def endpoint_id():
    return "%s/%d" % (machine_id(), os.getpid())
