"""Wire protocol: length-prefixed pickled frames over asyncio TCP.

Replaces the reference's two-plane fabric (Twisted JSON-lines control +
ZeroMQ streaming-pickle data, ``network_common.py`` + ``txzmq/``) with one
asyncio stream. Frames:

    [4-byte big-endian length][1-byte codec][payload]

codec 0 = raw pickle, 1 = gzip pickle (auto-chosen by size, mirroring the
reference's pluggable chunk compression). Messages are dicts with a "type"
key; job/update payloads ride inside them as pickled python objects (the
units' generate/apply contracts define their content).
"""

import asyncio
import gzip
import hashlib
import os
import pickle
import struct
import uuid

COMPRESS_THRESHOLD = 64 * 1024

_HEADER = struct.Struct(">IB")


def encode_frame(message):
    payload = pickle.dumps(message, protocol=pickle.HIGHEST_PROTOCOL)
    codec = 0
    if len(payload) >= COMPRESS_THRESHOLD:
        compressed = gzip.compress(payload, compresslevel=1)
        if len(compressed) < len(payload):
            payload, codec = compressed, 1
    return _HEADER.pack(len(payload), codec) + payload


async def read_frame(reader):
    header = await reader.readexactly(_HEADER.size)
    length, codec = _HEADER.unpack(header)
    payload = await reader.readexactly(length)
    if codec == 1:
        payload = gzip.decompress(payload)
    return pickle.loads(payload)


async def write_frame(writer, message):
    writer.write(encode_frame(message))
    await writer.drain()


def machine_id():
    """Stable per-host identity (reference ``network_common.py:72-118``
    derived it from dbus + MAC; /etc/machine-id is the modern source)."""
    for path in ("/etc/machine-id", "/var/lib/dbus/machine-id"):
        try:
            with open(path) as fin:
                return fin.read().strip()
        except OSError:
            continue
    return hashlib.sha1(uuid.getnode().to_bytes(6, "big")).hexdigest()


def endpoint_id():
    return "%s/%d" % (machine_id(), os.getpid())
