"""Master: serves jobs, merges updates, manages the slave fleet.

Reference ``veles/server.py``. Kept semantics:

- handshake validates the workflow checksum and assigns slave ids
  (``server.py:478-529``);
- job pipeline with backpressure: a "not ready" workflow answer queues the
  slave's request, replayed after the next update (``server.py:369-399``);
- update application serialized through the workflow's aggregation lock
  and run off the event loop (``server.py:401-430``);
- hang detection: per-slave job-duration history, timeout =
  max(mean + 3σ, job_timeout) → drop + blacklist
  (``server.py:619-635``);
- elasticity: ``drop_slave`` propagates so the Loader requeues pending
  minibatches; slaves may join/leave at any time;
- per-slave pause/resume and reverse-DNS naming kept as attributes on
  SlaveDescription.
"""

import asyncio
import os
import threading
import time
import uuid

from veles_tpu.core.config import root
from veles_tpu.core.logger import Logger
from veles_tpu.fleet.ledger import FENCE_STALE_EPOCH, JobLedger
from veles_tpu.fleet.protocol import (
    COMPRESS_THRESHOLD, ProtocolError, machine_id, read_frame,
    resolve_secret, write_frame)
from veles_tpu.observe.fleetscope import FleetScope, StepWindow
from veles_tpu.observe.flight import get_flight_recorder
from veles_tpu.observe.metrics import bridge, publish_fleet
from veles_tpu.observe.tracing import get_tracer, parse_trace_field


class SlaveDescription:
    """Fleet-roster entry (reference ``server.py:172``)."""

    #: job-duration history cap: the mean+3sigma hang threshold must track
    #: the slave's RECENT speed, not be skewed by ancient samples (and the
    #: list must not grow unboundedly over long runs)
    JOB_TIMES_KEEP = 100

    def __init__(self, sid, info):
        self.id = sid
        self.mid = info.get("mid", "?")
        # coerce ONCE at ingestion: every consumer (logs, status API,
        # power-weighted retry sort) can then rely on a float
        try:
            self.power = float(info.get("power", 1.0))
        except (TypeError, ValueError):
            self.power = 1.0
        self.pid = info.get("pid", 0)
        self.backend = info.get("backend", "?")
        self.state = "WAIT"
        self.jobs_done = 0
        #: per-slave step-time history: ONE implementation
        #: (observe/fleetscope.py StepWindow) behind the adaptive hang
        #: timeout AND the fleet straggler detector — the server shares
        #: this window with its FleetScope via ``track_window``
        self.window = StepWindow(keep=self.JOB_TIMES_KEEP)
        self.job_started = None
        self.paused = False
        self.chaos_counters = None  # latest fault tallies from the slave
        #: latest counter/gauge snapshot piggybacked on this slave's
        #: update frames (observe/metrics.py snapshot() rows); the
        #: master's /metrics re-exports them with a slave label
        self.metrics_rows = None
        #: latest metric-history summary piggybacked the same way
        #: (observe/history.py fleet_summary() rows) — ingested
        #: slave-labeled into the master's history so its incident
        #: autopsies span the fleet
        self.history_rows = None

    @property
    def job_times(self):
        """The raw step-time samples (compat view of the window)."""
        return self.window.samples

    def record_job_time(self, duration):
        self.window.push(duration)

    def timeout(self, default):
        """mean + 3σ adaptive hang threshold (reference
        ``server.py:619-635``), read from the SAME window the
        straggler detector scores."""
        return self.window.hang_timeout(default)

    def as_dict(self):
        return {"id": self.id, "mid": self.mid, "pid": self.pid,
                "power": self.power, "state": self.state,
                "jobs_done": self.jobs_done, "paused": self.paused}


class Server(Logger):
    """The fleet master (reference ``server.py:659``)."""

    #: per-slave piggybacked-metrics bounds (see :meth:`slave_metrics`)
    METRICS_MAX_ROWS = 512
    METRICS_MAX_LABELS = 8
    METRICS_MAX_VALUE_LEN = 256

    def __init__(self, address, workflow, job_timeout=120.0, secret=None,
                 respawn=False, spawner=None, metrics_port=None,
                 plane=None):
        super().__init__(logger_name="fleet.Server")
        #: wire plane (docs/compiler_fleet.md): "data" (reference
        #: protocol, weights ride every frame) or "control" (batch
        #: assignments + scalar metrics only; weights cross the wire in
        #: the handshake and at epoch-fence ``sync`` frames while the
        #: gradient math lives in XLA collectives on the slave). Both
        #: sides must agree — the handshake rejects mismatches.
        if plane is None:
            from veles_tpu.fleet import fleet_plane
            plane = fleet_plane()
        self.plane = plane
        self.control_plane = plane == "control"
        #: control-plane rollback protocol: last ACCEPTED local-tick
        #: counter and last accepted job per client PROCESS (mid, pid)
        #: — keyed by process, not sid, so the accounting survives
        #: reconnects (a re-joined slave gets a fresh sid)
        self._acked_ticks = {}
        self._accepted_jobs = {}
        #: epoch-fence weight-sync accounting (control plane)
        self._sync_counters = {"applied": 0, "fenced": 0}
        #: accepted results since the last applied fence sync — a
        #: FRESH process joining with this > 0 means mid-epoch
        #: progress lived only on a dead replica (control-plane
        #: process-loss recovery is epoch-granularity; the join warns)
        self._jobs_since_sync = 0
        #: latest in-program-reduce stats per client process (mid, pid)
        #: mined from the piggybacked metric rows — persisted like
        #: _chaos_reports so fleet_status() can still report the reduce
        #: plane after a slave disconnects (the dashboard's proof the
        #: math stayed on the chip)
        self._reduce_reports = {}
        #: frames carrying a data-plane ``update`` payload on a
        #: control-plane wire, rejected (never applied) — see
        #: :meth:`_apply_update`
        self._payload_rejects = 0
        host, _, port = address.rpartition(":")
        # loopback by default: an exposed master means remote code
        # execution for anyone with the secret — opt in explicitly
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.workflow = workflow
        self._secret, source = resolve_secret(workflow, secret,
                                              with_source=True)
        self.secret_source = source
        # --respawn: relaunch dead slaves on their hosts (reference
        # server.py:637-655); see fleet/respawn.py
        self.respawn_manager = None
        if respawn:
            from veles_tpu.fleet.respawn import RespawnManager
            self.respawn_manager = RespawnManager(
                spawner=spawner, extra_env=self.secret_spawn_env())
        if source == "checksum" \
                and self.host not in ("127.0.0.1", "localhost", "::1"):
            self.warning(
                "fleet secret defaulted to the workflow checksum on a "
                "non-loopback bind (%s) — anyone with the workflow source "
                "can compute it; set VELES_TPU_FLEET_SECRET or "
                "root.common.fleet.secret for real deployments", self.host)
        self.job_timeout = job_timeout
        self.slaves = {}
        self.blacklist = set()
        #: job-level accounting: leases, explicit requeue, update fencing
        self.ledger = JobLedger()
        #: master-generation fence, minted at start(); echoed in every
        #: post-welcome frame so updates addressed to a previous master
        #: incarnation are rejected, not applied (see fleet/ledger.py)
        self.epoch = None
        #: latest chaos tallies per client process (mid, pid): counters
        #: are cumulative per process, so keeping the last report per
        #: process survives reconnects without double counting
        self._chaos_reports = {}
        #: the fleet goodput observatory (observe/fleetscope.py):
        #: per-slave step windows + clock alignment + shipped-span
        #: store + goodput decomposition + the straggler detector
        self.scope = FleetScope()
        self._next_id = 0
        self._pending_requests = []  # backpressured (sid, writer)
        self._writers = {}
        self._update_lock = threading.Lock()
        self._loop = None
        self._server = None
        self._thread = None
        self._stopped = threading.Event()
        self.on_finished = None  # callback when the job stream is done
        #: fleet-wide Prometheus sidecar (docs/observability.md): the
        #: fleet wire protocol is custom asyncio frames, so /metrics
        #: needs its own tiny HTTP listener. Off by default (None);
        #: 0 binds an ephemeral port, resolved after start().
        if metrics_port is None:
            metrics_port = root.common.observe.get("fleet_metrics_port",
                                                   None)
        self.metrics_port = metrics_port
        self._metrics_httpd = None

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        """Run the asyncio server in a dedicated thread (the reactor role;
        reference ran Twisted as the main loop, but here jit dispatch owns
        the main thread)."""
        self.epoch = uuid.uuid4().hex
        ready = threading.Event()

        def run_loop():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            coro = asyncio.start_server(self._handle_slave, self.host,
                                        self.port)
            self._server = self._loop.run_until_complete(coro)
            if not self.port:
                self.port = self._server.sockets[0].getsockname()[1]
            ready.set()
            # periodic shm GC: sender-side orphans (peer died between
            # segment creation and descriptor delivery) accumulate in
            # long runs unless someone sweeps mid-run
            self._loop.call_later(900.0, self._periodic_shm_gc)
            self._loop.run_forever()
            self._server.close()
            self._loop.run_until_complete(self._server.wait_closed())
            self._loop.close()

        self._thread = threading.Thread(target=run_loop, daemon=True,
                                        name="fleet-server")
        self._thread.start()
        ready.wait()
        # GC shm segments orphaned by crashed receivers of PREVIOUS runs
        from veles_tpu.fleet import sharedio
        stale = sharedio.cleanup_stale()
        if stale:
            self.info("removed %d stale shared-memory segments", stale)
        self.info("master listening on %s:%d", self.host, self.port)
        if self.metrics_port is not None:
            self._start_metrics_server()
        return self

    def _start_metrics_server(self):
        """The /metrics HTTP sidecar: fleet_status() + every slave's
        piggybacked counters in one Prometheus exposition."""
        from http.server import BaseHTTPRequestHandler
        from veles_tpu.core.httpd import (QuietHandlerMixin,
                                          enable_metrics, reply,
                                          serve_metrics, start_server)

        server = self
        bridge(enable_metrics(), self, publish_fleet)

        class Handler(QuietHandlerMixin, BaseHTTPRequestHandler):
            def do_GET(self):
                if serve_metrics(self):
                    return
                path = self.path.split("?")[0]
                if path == "/healthz":
                    reply(self, server.fleet_status())
                    return
                if path == "/debug/fleet":
                    # the fleet-trace payload (observe/fleetscope.py):
                    # master spans + shipped slave spans + clocks +
                    # goodput, assembled by `observe fleet-trace`
                    reply(self, server.fleet_debug())
                    return
                if path in ("/debug", "/debug/"):
                    # the debug index (core/httpd.serve_debug_index
                    # contract): this sidecar mounts the fleet payload
                    reply(self, {"surfaces": {
                        "/debug/fleet": "fleet goodput observatory: "
                        "master+slave spans, clocks, straggler "
                        "verdict (observe/fleetscope.py; assemble "
                        "with `veles_tpu observe fleet-trace`)"}})
                    return
                self.send_error(404)

        self._metrics_httpd, self.metrics_port = start_server(
            Handler, self.host, int(self.metrics_port),
            name="fleet-metrics")
        self.info("fleet metrics on http://%s:%d/metrics", self.host,
                  self.metrics_port)

    def kick(self):
        """Replay backpressured job requests. The task farm calls this
        after submit() so parked slaves re-request without waiting for
        the next update (which, between GA generations, never comes)."""
        if self._loop is not None and not self._stopped.is_set():
            asyncio.run_coroutine_threadsafe(self._retry_pending(),
                                             self._loop)

    def drain(self, timeout=10.0):
        """Block until every slave has disconnected or gone IDLE (all
        parked requests answered). Call between kick() and stop() so the
        clean 'no more jobs' frames actually reach the slaves before the
        event loop dies."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            # snapshot slaves: the event-loop thread mutates the dict
            slaves = list(self.slaves.values())
            if not self._pending_requests and all(
                    s.state in ("IDLE",) for s in slaves):
                return True
            time.sleep(0.05)
        return False

    def stop(self):
        if self._stopped.is_set():
            return
        self._stopped.set()
        if self._metrics_httpd is not None:
            self._metrics_httpd.shutdown()
            self._metrics_httpd = None
        if self.respawn_manager is not None:
            self.respawn_manager.stop()
        if self._loop is not None:
            def shutdown():
                # close live slave transports BEFORE the loop dies: a
                # stopped loop never runs its suspended handlers again,
                # so an un-closed socket would leave parked slaves
                # waiting forever instead of reconnecting to our
                # successor (the master-restart recovery path)
                for writer in list(self._writers.values()):
                    writer.close()
                self._loop.stop()

            self._loop.call_soon_threadsafe(shutdown)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    @property
    def address(self):
        return "%s:%d" % (self.host, self.port)

    def secret_spawn_env(self):
        """Env vars a spawned slave needs to authenticate. When the
        secret came from the master's environment or an explicit
        ``secret=``, a remote slave cannot re-derive it (config and
        checksum travel with the workflow source; env does not) — every
        frame would fail HMAC and the slave could never join."""
        if self.secret_source not in ("env", "explicit"):
            return {}
        try:
            value = self._secret.decode("utf-8")
        except UnicodeDecodeError:
            self.warning(
                "fleet secret is not UTF-8 text; cannot forward it to "
                "spawned slaves via VELES_TPU_FLEET_SECRET — remote "
                "-n/--respawn slaves will fail to authenticate")
            return {}
        if value != value.strip() or "\n" in value or "\r" in value:
            # the ssh stdin NAME=value line protocol would truncate or
            # corrupt it (and `read` trims IFS whitespace)
            self.warning(
                "fleet secret contains whitespace/newlines; cannot "
                "forward it to spawned slaves — use a single-line "
                "secret for remote -n/--respawn")
            return {}
        return {"VELES_TPU_FLEET_SECRET": value}

    # -- per-slave protocol ---------------------------------------------------
    async def _handle_slave(self, reader, writer):
        sid = None
        try:
            # pre-auth frame: tiny cap (the hello is a small dict) so an
            # unauthenticated peer cannot balloon our memory
            hello = await read_frame(reader, self._secret,
                                     max_frame=1 << 16)
            if hello.get("type") != "hello":
                await write_frame(writer, {"type": "error",
                                           "error": "bad handshake"}, self._secret)
                return
            if hello.get("mid") in self.blacklist:
                await write_frame(writer, {"type": "error",
                                           "error": "blacklisted"}, self._secret)
                return
            checksum = getattr(self.workflow, "checksum", None)
            # REQUIRED equality: a missing checksum is a mismatch too —
            # a slave on different code must never join silently
            if hello.get("checksum") != checksum:
                await write_frame(writer, {
                    "type": "error",
                    "error": "workflow checksum mismatch"}, self._secret)
                self.warning("rejected slave with wrong workflow checksum")
                return
            # both sides must run the SAME wire plane: a data-plane
            # slave joining a control-plane master would ship weight
            # payloads the master rejects (and vice versa would starve
            # the master of weights entirely) — fail the handshake with
            # a message naming the knob instead of stalling later
            peer_plane = hello.get("plane", "data")
            if peer_plane != self.plane:
                await write_frame(writer, {
                    "type": "error",
                    "error": "fleet plane mismatch (master=%s, slave="
                             "%s): set root.common.fleet.plane / "
                             "--fleet-plane identically on every host"
                             % (self.plane, peer_plane)}, self._secret)
                self.warning("rejected slave with mismatched fleet "
                             "plane %r (ours: %r)", peer_plane,
                             self.plane)
                return
            self._next_id += 1
            sid = "slave-%d" % self._next_id
            slave = SlaveDescription(sid, hello)
            slave.respawn_recipe = hello.get("respawn")
            peer = writer.get_extra_info("peername")
            slave.peer_host = peer[0] if peer else "127.0.0.1"
            # same-host fast path (reference SharedIO, server.py:721-732):
            # matching machine ids move big payloads via /dev/shm
            # segments — but only when uid and shm directory match too
            # (0o600 segments are unreadable across users; differing
            # shm_dir fallbacks would 404 every descriptor)
            from veles_tpu.fleet import sharedio
            shm_ok = (slave.mid != "?" and slave.mid == machine_id()
                      and hello.get("uid") == sharedio.owner_uid()
                      and hello.get("shm_dir") == sharedio.shm_dir()
                      and root.common.fleet.get("shm", True))
            slave.shm_threshold = COMPRESS_THRESHOLD if shm_ok else None
            self.slaves[sid] = slave
            self._writers[sid] = writer
            # the hang timeout and the straggler detector read ONE
            # step-time window (observe/fleetscope.py)
            self.scope.track_window(sid, slave.window)
            initial = await self._in_thread(
                self.workflow.generate_initial_data_for_slave, slave)
            await write_frame(writer, {"type": "welcome", "id": sid,
                                       "shm": shm_ok, "epoch": self.epoch,
                                       "initial": initial}, self._secret,
                              shm_threshold=slave.shm_threshold)
            self.info("slave %s connected (mid=%s power=%.1f)", sid,
                      slave.mid, slave.power)
            if self.control_plane and self._jobs_since_sync > 0 \
                    and (slave.mid, slave.pid) not in self._acked_ticks:
                # a FRESH process (not a reconnect of a live replica)
                # joined while settled mid-epoch work exists only on a
                # dead replica: it starts from the last epoch fence —
                # control-plane process-loss recovery is
                # epoch-granularity by design (docs/compiler_fleet.md
                # decision table); say so LOUDLY instead of silently
                # dropping those applications from the trajectory
                self.warning(
                    "control-plane slave %s is a fresh process but %d "
                    "accepted job(s) since the last epoch-fence sync "
                    "lived on a departed replica — it resumes from "
                    "the fence weights; that mid-epoch progress is "
                    "lost to the weight trajectory (use the data "
                    "plane if per-minibatch durability across process "
                    "deaths matters — docs/compiler_fleet.md)",
                    sid, self._jobs_since_sync)
            while not self._stopped.is_set():
                msg = await read_frame(reader, self._secret)
                mtype = msg.get("type")
                if mtype == "job_request":
                    await self._serve_job(slave, writer)
                elif mtype == "update":
                    await self._apply_update(slave, writer, msg)
                elif mtype == "sync":
                    await self._apply_sync(slave, writer, msg)
                elif mtype == "power":
                    try:
                        slave.power = float(msg.get("power"))
                    except (TypeError, ValueError):
                        self.warning("ignoring non-numeric power from %s",
                                     slave.id)
                elif mtype == "bye":
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except ProtocolError as exc:
            peer = writer.get_extra_info("peername")
            self.warning("dropping peer %s: %s", peer, exc)
        except Exception:
            self.exception("slave handler failed")
        finally:
            if sid is not None:
                self._drop(sid)
            writer.close()

    async def _serve_job(self, slave, writer):
        if slave.paused:
            await write_frame(writer, {"type": "job", "job": None,
                                       "paused": True}, self._secret)
            return
        slave.state = "GETTING_JOB"
        job = await self._in_thread(self._locked_generate, slave)
        if job is False:
            # backpressure: some unit not ready — queue the request,
            # replayed after the next update (reference server.py:369-399)
            self._pending_requests.append((slave.id, writer))
            return
        if job is None:
            slave.state = "IDLE"
            await write_frame(writer, {"type": "job", "job": None}, self._secret)
            self._maybe_finished()
            return
        slave.state = "WORK"
        slave.job_started = time.time()
        # lease: deadline from the slave's adaptive timeout; the update
        # must echo the job_id (exactly-once fence) and our epoch
        timeout = slave.timeout(self.job_timeout)
        job_id = self.ledger.issue(slave.id, timeout)
        frame = {"type": "job", "job": job, "job_id": job_id,
                 "epoch": self.epoch}
        if self.control_plane:
            # rollback protocol: the highest local tick we ACCEPTED
            # from this process. A slave holding a higher local tick
            # knows its last application was never accepted (lost
            # update) and must roll it back before applying this job —
            # that is what keeps re-issued work bit-identical without
            # weights on the wire (docs/compiler_fleet.md)
            frame["acked"] = self._acked_ticks.get(
                (slave.mid, slave.pid), 0)
        # trace propagation (docs/observability.md): the issue event
        # roots the job's trace; its context rides the frame, the slave
        # parents its do_job span to it and echoes ITS context in the
        # update, so one fleet job reads master -> slave -> apply
        issue = get_tracer().event("fleet.issue", job_id=job_id,
                                   slave=slave.id)
        if issue.context() is not None:
            frame["trace"] = list(issue.context())
        # clock-alignment t0: the job-send stamp this lease's update
        # round trip is measured against (observe/fleetscope.py)
        self.scope.note_issue(job_id, slave, time.monotonic())
        await write_frame(writer, frame, self._secret,
                          shm_threshold=getattr(slave, "shm_threshold",
                                                None))
        self._watch_hang(slave, job_id, timeout)

    async def _apply_update(self, slave, writer, msg):
        if isinstance(msg.get("chaos"), dict):
            # the slave's fault-injection tallies ride its updates so the
            # dashboard can prove each configured fault actually fired
            slave.chaos_counters = msg["chaos"]
            self._chaos_reports[(slave.mid, slave.pid)] = msg["chaos"]
        if isinstance(msg.get("metrics"), list):
            # counter/gauge snapshot piggybacked on the update frame —
            # the master's /metrics re-exports it under this slave's
            # id; truncated at INGESTION so an oversized hostile list
            # is never retained past the frame
            slave.metrics_rows = msg["metrics"][:self.METRICS_MAX_ROWS]
            entry = self._mine_reduce_rows(slave.metrics_rows)
            if entry:
                self._reduce_reports[(slave.mid, slave.pid)] = \
                    (slave.id, entry)
        if isinstance(msg.get("history"), list):
            # the slave's trend summary (observe/history.py): bounded
            # at ingestion like the metrics rows, then landed
            # slave-labeled in the master's own history — a
            # master-side incident artifact reports the whole fleet's
            # breaching windows (ingest_summary validates the rows)
            from veles_tpu.observe.history import (FLEET_MAX_SERIES,
                                                   get_metric_history)
            slave.history_rows = msg["history"][:FLEET_MAX_SERIES]
            master_history = get_metric_history()
            if master_history is not None:
                master_history.ingest_summary(slave.id,
                                              slave.history_rows)
        # span-summary + clock-stamp ingestion (observe/fleetscope.py;
        # validated + bounded like the metric rows above): runs for
        # every frame — even a frame the fence later rejects carries
        # real spans and a real round trip
        update_mono = time.monotonic()
        stamp_pair = self.scope.note_update(slave, msg, update_mono)
        if self.control_plane and "update" in msg:
            # a data-plane weight payload on the control-plane wire is
            # a protocol violation (zombie or misconfigured peer
            # shipping stale weights a future refactor might apply) —
            # REJECT it loudly BEFORE the fence consumes the lease: the
            # job stays OUTSTANDING, so the hang timer requeues the
            # work and liveness survives the violator
            self._payload_rejects += 1
            self.warning(
                "rejected update from %s: frame carries a data-plane "
                "'update' payload on a control-plane wire (job_id=%r) "
                "— weights never ride updates in this mode", slave.id,
                msg.get("job_id"))
            get_flight_recorder().note("fleet.payload_reject",
                                       slave=slave.id,
                                       job_id=msg.get("job_id"))
            await write_frame(writer, {"type": "update_ack",
                                       "fenced": "payload-rejected"},
                              self._secret)
            slave.state = "WAIT"
            await self._retry_pending()
            return
        results = msg.get("results" if self.control_plane else "update")
        if results is None:
            # a metrics-only keepalive: no completed-work bookkeeping
            # (jobs_done/job timing/respawn budget) AND no lease
            # consumption — settling it would mark work DONE whose
            # results never arrived, silently dropping that minibatch
            # from the run (the hang timer requeues the lease instead)
            self.warning("update from %s carried no results (job_id="
                         "%r) — acked, lease left outstanding, not "
                         "counted as completed work", slave.id,
                         msg.get("job_id"))
            await write_frame(writer, {"type": "update_ack",
                                       "fenced": "no-results"},
                              self._secret)
            slave.state = "WAIT"
            await self._retry_pending()
            return
        verdict = self._fence_update(slave, msg)
        if verdict is not None:
            self.warning("fenced update from %s: %s (job_id=%r)",
                         slave.id, verdict, msg.get("job_id"))
            self._note_fence(verdict, slave.id, msg.get("job_id"))
            # still ack (flagged) so a sync slave doesn't stall — it
            # skips the job_request for fenced acks
            await write_frame(writer, {"type": "update_ack",
                                       "fenced": verdict}, self._secret)
            slave.state = "WAIT"
            await self._retry_pending()
            return
        if slave.job_started is not None:
            slave.record_job_time(time.time() - slave.job_started)
            slave.job_started = None
        slave.jobs_done += 1
        # goodput decomposition: the accepted update's round trip
        # splits into compute/host/wire, the gap since this slave's
        # previous settle into idle (observe/fleetscope.py)
        self.scope.book_update(slave.id, stamp_pair, update_mono)
        if slave.jobs_done == 1 and self.respawn_manager is not None \
                and slave.mid != "?":
            # reset the respawn budget only once the slave proves it
            # can WORK — resetting at handshake would let a
            # crash-on-init loop respawn forever at base delay
            self.respawn_manager.notify_reconnected(slave.mid)
        with get_tracer().span(
                "fleet.apply",
                parent=parse_trace_field(msg.get("trace")),
                job_id=msg.get("job_id"), slave=slave.id):
            await self._in_thread(self._locked_apply, results, slave)
        if self.control_plane:
            key = (slave.mid, slave.pid)
            tick = msg.get("tick")
            if isinstance(tick, int) and not isinstance(tick, bool):
                self._acked_ticks[key] = tick
            if isinstance(msg.get("job_id"), int):
                self._accepted_jobs[key] = msg["job_id"]
            self._jobs_since_sync += 1
        # straggler detection + trend recording + (cooldown-limited)
        # fleet incident artifact — OFF the record path by design
        # (observe/fleetscope.py autopsy_tick may write a file)
        from veles_tpu.observe.history import get_metric_history
        self.scope.autopsy_tick(
            slave.id, get_metric_history(),
            wasted_s=self.ledger.snapshot().get("wasted_s", 0.0))
        await write_frame(writer, {"type": "update_ack"}, self._secret)
        slave.state = "WAIT"
        await self._retry_pending()

    async def _apply_sync(self, slave, writer, msg):
        """Epoch-fence weight sync (control plane): the only frames
        that carry weights after the handshake. Fenced like updates —
        a stale master epoch (zombie from a previous incarnation) or a
        job the ledger never accepted from this process means the
        weights are rejected, never applied. Re-application of the
        SAME accepted fence (the client resends until acked) is an
        idempotent overwrite."""
        verdict = None
        if not self.control_plane:
            verdict = "not-control-plane"
        elif msg.get("epoch") != self.epoch:
            verdict = FENCE_STALE_EPOCH
        elif msg.get("job_id") is None or msg.get("job_id") != \
                self._accepted_jobs.get((slave.mid, slave.pid)):
            # the sync must chase an update WE accepted from THIS
            # process — a zombie's fence payload (its job was requeued
            # and re-run elsewhere) never lands
            verdict = "unsettled-job"
        if verdict is not None:
            self._sync_counters["fenced"] += 1
            self.warning("fenced sync from %s: %s (job_id=%r)",
                         slave.id, verdict, msg.get("job_id"))
            get_flight_recorder().note("fleet.sync_fence",
                                       verdict=verdict, slave=slave.id,
                                       job_id=msg.get("job_id"))
            await write_frame(writer, {"type": "sync_ack",
                                       "fenced": verdict}, self._secret)
            return
        payload = msg.get("sync")
        if payload is not None:
            await self._in_thread(self._locked_apply_sync, payload,
                                  slave)
            self._sync_counters["applied"] += 1
            self._jobs_since_sync = 0
        await write_frame(writer, {"type": "sync_ack"}, self._secret)

    def _locked_apply_sync(self, payload, slave):
        with self._update_lock:
            apply = getattr(self.workflow, "apply_sync_from_slave",
                            None)
            if apply is not None:
                apply(payload, slave)
            else:
                self.warning("workflow has no apply_sync_from_slave — "
                             "fence sync from %s dropped", slave.id)

    def _fence_update(self, slave, msg):
        """Judge an update before it can touch master state. Returns
        ``None`` (apply it) or the fence verdict string (reject): unknown/
        duplicate/requeued/foreign ``job_id`` via the ledger, or a stale
        master ``epoch`` (the update answers a previous incarnation)."""
        if msg.get("epoch") != self.epoch:
            return self.ledger.count_stale_epoch()
        return self.ledger.settle(msg.get("job_id"), slave.id)

    def _note_fence(self, verdict, sid, job_id):
        """Fence verdicts go to the black box; a STALE-EPOCH fence —
        a zombie answering a previous master incarnation — dumps it,
        because by then the interesting history is about to scroll out
        of the ring (docs/observability.md). Dumped ONCE per slave: a
        zombie replaying stale frames must not turn each one into
        synchronous dump I/O on the event loop (later frames still
        note into the ring)."""
        flight = get_flight_recorder()
        flight.note("fleet.fence", verdict=verdict, slave=sid,
                    job_id=job_id)
        if verdict != FENCE_STALE_EPOCH:
            return
        dumped = getattr(self, "_fence_dumped_", None)
        if dumped is None:
            dumped = self._fence_dumped_ = set()
        if sid in dumped:
            return
        dumped.add(sid)
        flight.dump("epoch_fence",
                    extra={"slave": sid, "job_id": job_id,
                           "epoch": self.epoch,
                           "ledger": self.ledger.snapshot()})

    def _locked_apply(self, update, slave):
        with self._update_lock:
            self.workflow.apply_data_from_slave(update, slave)

    def _locked_generate(self, slave):
        # concurrent job requests from 2+ slaves run on different executor
        # threads; the Loader's serve is read-modify-write state
        with self._update_lock:
            return self.workflow.generate_data_for_slave(slave)

    async def _retry_pending(self):
        pending, self._pending_requests = self._pending_requests, []
        # power-weighted balancing (reference workflow.py:613-619 +
        # DeviceBenchmark power): when several slaves are parked, the
        # strongest gets the next job first

        pending.sort(key=lambda item: -getattr(
            self.slaves.get(item[0]), "power", 0.0))
        for sid, writer in pending:
            slave = self.slaves.get(sid)
            if slave is not None:
                await self._serve_job(slave, writer)

    def _watch_hang(self, slave, job_id, timeout):
        def check():
            if self.slaves.get(slave.id) is not slave:
                # the slave already dropped (death/disconnect): a stale
                # timer must NOT blacklist its machine-id posthumously —
                # that would ban every future (e.g. respawned) slave of
                # that host
                return
            # per-lease expiry: only fires when THIS job is still
            # OUTSTANDING past its deadline (the old elapsed-time check
            # could misread a later, faster job); marking it REQUEUED
            # arms the fence against the zombie's eventual late update
            if self.ledger.expire_if_outstanding(job_id):
                self.warning("slave %s hanged on job %d (> %.1fs); "
                             "dropping + blacklisting", slave.id, job_id,
                             timeout)
                if slave.mid != "?":
                    # never blacklist the unknown-mid placeholder: one
                    # anonymous hang would ban every future such slave
                    self.blacklist.add(slave.mid)
                writer = self._writers.get(slave.id)
                if writer is not None:
                    writer.close()

        self._loop.call_later(timeout + 1.0, check)

    def _drop(self, sid):
        slave = self.slaves.pop(sid, None)
        if slave is not None:
            slave.job_started = None  # disarm any in-flight hang timer
        # scoring hygiene: a departed slave leaves the straggler
        # detector's reference pool (observe/fleetscope.py)
        self.scope.drop_slave(sid)
        # explicit job-level requeue: every lease still OUTSTANDING for
        # this slave transitions to REQUEUED (the workflow's drop_slave
        # below requeues the actual minibatch payloads) and its late
        # update, should the peer resurface, is fenced
        requeued = self.ledger.requeue_for_slave(sid)
        if requeued:
            self.info("requeued %d outstanding lease(s) of %s: %s",
                      len(requeued), sid, requeued)
        self._writers.pop(sid, None)
        self._pending_requests = [
            (s, w) for s, w in self._pending_requests if s != sid]
        if slave is not None:
            self.info("slave %s dropped", sid)
            with self._update_lock:
                self.workflow.drop_slave(slave)
            if self.respawn_manager is not None \
                    and not self._stopped.is_set() \
                    and getattr(slave, "respawn_recipe", None) \
                    and slave.mid not in self.blacklist \
                    and self.workflow.has_more_jobs():
                self.respawn_manager.schedule(
                    getattr(slave, "peer_host", "127.0.0.1"),
                    slave.respawn_recipe,
                    key=slave.mid if slave.mid != "?" else sid)
        self._maybe_finished()

    def _maybe_finished(self):
        if not self.workflow.has_more_jobs() \
                and all(s.state == "IDLE" for s in self.slaves.values()):
            if self.on_finished is not None:
                self.on_finished()

    # -- helpers --------------------------------------------------------------
    def _periodic_shm_gc(self):
        if self._stopped.is_set():
            return

        def sweep():
            # off the event loop: a large /dev/shm walk must not stall
            # the frame-serving coroutines
            from veles_tpu.fleet import sharedio
            stale = sharedio.cleanup_stale()
            if stale:
                self.info("removed %d stale shared-memory segments",
                          stale)

        self._loop.run_in_executor(None, sweep)
        self._loop.call_later(900.0, self._periodic_shm_gc)

    async def _in_thread(self, fn, *args):
        return await self._loop.run_in_executor(None, fn, *args)

    def pause_slave(self, sid):
        if sid in self.slaves:
            self.slaves[sid].paused = True

    def resume_slave(self, sid):
        if sid in self.slaves:
            self.slaves[sid].paused = False

    def slave_metrics(self):
        """Per-slave piggybacked metric snapshots, validated: the rows
        came off the wire, so anything not shaped like a snapshot row
        (``[name, kind, [[k, v], ...], number]``) is dropped — metric
        and label NAMES must be valid exposition tokens (label values
        are escaped by the registry), so a hostile or version-skewed
        slave can at most contribute bogus VALUES, never break the
        master's exposition. Volume is bounded too: at most
        ``METRICS_MAX_ROWS`` rows per slave, ``METRICS_MAX_LABELS``
        labels per row, label values truncated — a GiB-sized hostile
        snapshot cannot balloon the master's memory or its scrapes."""
        from veles_tpu.observe.metrics import (LABEL_NAME_RE,
                                               METRIC_NAME_RE)

        out = {}
        for slave in list(self.slaves.values()):
            rows = slave.metrics_rows
            if not isinstance(rows, list):
                continue
            clean = []
            for row in rows[:self.METRICS_MAX_ROWS]:
                try:
                    name, kind, labels, value = row
                    if not isinstance(name, str) \
                            or not METRIC_NAME_RE.match(name) \
                            or kind not in ("counter", "gauge") \
                            or isinstance(value, bool) \
                            or not isinstance(value, (int, float)) \
                            or len(labels) > self.METRICS_MAX_LABELS:
                        continue
                    keys = [str(k) for k, _ in labels]
                    if not all(LABEL_NAME_RE.match(k) and k != "slave"
                               for k in keys):
                        continue
                    clean.append((
                        name, kind,
                        {str(k): str(v)[:self.METRICS_MAX_VALUE_LEN]
                         for k, v in labels}, value))
                except (TypeError, ValueError):
                    continue
            if clean:
                out[slave.id] = clean
        return out

    def fleet_status(self):
        """Observability snapshot consumed by the web-status dashboard
        and the SlaveStats plotter (reference ``web_status.py`` feed).
        Called from the status/plotter threads while the event-loop
        thread mutates the roster — snapshot both containers first (as
        ``drain()`` does) instead of iterating them live."""
        slaves = list(self.slaves.values())
        pending = list(self._pending_requests)
        chaos = {}
        for counters in list(self._chaos_reports.values()):
            for key, value in counters.items():
                if isinstance(value, (int, float)):
                    chaos[key] = chaos.get(key, 0) + value
        ledger_snap = self.ledger.snapshot()
        slave_rows = [s.as_dict() for s in slaves]
        for row in slave_rows:
            # the fleetscope per-slave truth: median step time + the
            # straggler score vs the fleet median (one implementation
            # with the hang timeout — observe/fleetscope.py)
            stats = self.scope.slave_stats(row.get("id"))
            if stats:
                row.update(stats)
        status = {"slaves": slave_rows,
                  # .copy() is a single C-level op (GIL-atomic), unlike
                  # sorted() iterating the live set under a concurrent
                  # hang-check blacklist.add
                  "blacklist": sorted(self.blacklist.copy()),
                  "queued_jobs": len(pending),
                  "epoch": self.epoch,
                  "plane": self.plane,
                  "ledger": ledger_snap,
                  "chaos": chaos}
        goodput = self.scope.goodput_summary(
            wasted_s=ledger_snap.get("wasted_s", 0.0))
        if goodput["jobs"]:
            status["goodput"] = goodput
        straggler = self.scope.straggler_summary()
        if straggler is not None:
            status["straggler"] = straggler
        clocks = self.scope.clock_summary()
        if clocks:
            status["clock"] = clocks
        if self.control_plane:
            status["sync"] = dict(self._sync_counters)
            status["payload_rejects"] = self._payload_rejects
        reduce_rows = {sid: dict(entry) for sid, entry
                       in self._reduce_reports.copy().values()}
        if reduce_rows:
            status["reduce"] = reduce_rows
        return status

    def fleet_debug(self):
        """The ``GET /debug/fleet`` payload (docs/observability.md,
        "Fleet timeline + goodput"): everything ``veles_tpu observe
        fleet-trace`` needs to assemble the merged, clock-aligned
        timeline — this process's flight-ring span events, the shipped
        slave spans mapped onto the master timeline, the per-process
        clock estimates, and the goodput/straggler status."""
        entries = get_flight_recorder().entries()
        return {
            "kind": "fleetscope",
            "schema": 1,
            "now_mono": time.monotonic(),
            "master_pid": os.getpid(),
            "master_mid": machine_id(),
            "status": self.fleet_status(),
            "clocks": self.scope.clock_summary(),
            "slave_spans": self.scope.span_rows(),
            "master_spans": [entry for entry in entries
                             if entry.get("kind") == "span"],
        }

    @staticmethod
    def _mine_reduce_rows(rows):
        """In-program-reduce stats from one piggybacked snapshot
        (``parallel/mapreduce.py`` publishes them into each slave's
        registry) — the web-status fleet column's proof the math
        stayed on the chip."""
        entry = {}
        for row in rows:
            try:
                name, _, _, value = row
            except (TypeError, ValueError):
                continue
            if not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                continue
            if name == "veles_fleet_reduce_steps_total":
                entry["steps"] = entry.get("steps", 0) + value
            elif name == "veles_fleet_reduce_bytes_total":
                entry["bytes"] = entry.get("bytes", 0) + value
            elif name == "veles_fleet_chip_idle_fraction":
                entry["idle"] = value
        return entry
