"""Task farm: distribute generic evaluation commands over the fleet.

The reference distributed genetics chromosome evaluations and ensemble
trainings to slaves through the same master/slave protocol as data-parallel
training (``genetics/optimization_workflow.py:179-279``,
``ensemble/base_workflow.py:101-127``) — each "job" was a full training
run. This module is that capability as a first-class adapter: a
:class:`TaskFarmMaster` speaks the fleet Server's workflow protocol
(generate/apply/drop/has_more_jobs) and serves **subprocess command**
tasks; a :class:`TaskFarmSlave` runs each command with a private
``--result-file`` and returns the parsed JSON as the update.

Lifecycle: ``submit()`` tasks (any time — between GA generations too),
``wait_batch()`` for the outstanding set, ``close()`` when no more will
ever come (lets idle slaves exit). Dropped slaves requeue their in-flight
tasks (same guarantee as the Loader's failed-minibatch path).
"""

import collections
import json
import os
import subprocess
import sys
import tempfile
import threading

from veles_tpu.core.logger import Logger


class TaskFarmMaster(Logger):
    """Fleet-protocol task queue (master side)."""

    def __init__(self, name="task-farm"):
        super().__init__(logger_name="TaskFarmMaster")
        self.name = name
        self.checksum = "taskfarm:" + name
        self._lock = threading.Lock()
        self._pending = collections.deque()
        self._in_flight = {}  # slave_id -> {task_id: payload}
        self._results = {}
        self._outstanding = 0
        self._batch_done = threading.Event()
        self._batch_done.set()
        self._closed = False
        #: called after submit() — wire to Server.kick so backpressured
        #: slaves re-request immediately
        self.on_new_tasks = None

    # -- producer API ---------------------------------------------------------
    def submit(self, task_id, argv):
        with self._lock:
            if self._closed:
                raise RuntimeError("farm is closed")
            self._pending.append((task_id, list(argv)))
            self._outstanding += 1
            self._batch_done.clear()
        if self.on_new_tasks is not None:
            self.on_new_tasks()

    def wait_batch(self, timeout=None):
        """Block until every submitted task has a result. Returns the
        accumulated {task_id: result} map."""
        if not self._batch_done.wait(timeout):
            raise TimeoutError("task farm batch timed out")
        with self._lock:
            return dict(self._results)

    def take_results(self):
        with self._lock:
            results, self._results = self._results, {}
            return results

    def close(self):
        """No more submissions: idle slaves may exit."""
        with self._lock:
            self._closed = True

    # -- fleet workflow protocol ----------------------------------------------
    def generate_initial_data_for_slave(self, slave):
        return None

    def generate_data_for_slave(self, slave):
        with self._lock:
            if self._pending:
                task_id, argv = self._pending.popleft()
                self._in_flight.setdefault(slave.id, {})[task_id] = argv
                return {"task_id": task_id, "argv": argv}
            if self._closed and not self._outstanding:
                return None  # farm drained: slave exits
            return False  # backpressure: parked until kick()/next update

    def apply_data_from_slave(self, update, slave):
        task_id = update["task_id"]
        with self._lock:
            flight = self._in_flight.get(slave.id, {})
            if task_id in flight:
                del flight[task_id]
                self._outstanding -= 1
            self._results[task_id] = update
            if not self._outstanding:
                self._batch_done.set()

    def drop_slave(self, slave=None):
        slave_id = getattr(slave, "id", slave)
        with self._lock:
            flight = self._in_flight.pop(slave_id, {})
            for task_id, argv in flight.items():
                self._pending.appendleft((task_id, argv))
        if flight:
            self.warning("requeued %d tasks from dropped slave %s",
                         len(flight), slave_id)
            if self.on_new_tasks is not None:
                self.on_new_tasks()

    def has_more_jobs(self):
        with self._lock:
            return bool(self._pending or self._outstanding
                        or not self._closed)


class TaskFarmSlave(Logger):
    """Fleet-protocol task executor (slave side): each job is a command
    run as a subprocess with a private ``--result-file``."""

    def __init__(self, name="task-farm", env=None):
        super().__init__(logger_name="TaskFarmSlave")
        self.name = name
        self.checksum = "taskfarm:" + name
        self.env = env

    def apply_initial_data_from_master(self, initial):
        pass

    def do_job(self, job, callback):
        task_id, argv = job["task_id"], list(job["argv"])
        fd, result_file = tempfile.mkstemp(suffix=".json", prefix="farm_")
        os.close(fd)
        argv += ["--result-file", result_file]
        self.info("task %s: %s", task_id, " ".join(argv[:4]) + " ...")
        proc = subprocess.run(
            argv, env=self.env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        update = {"task_id": task_id, "rc": proc.returncode}
        try:
            with open(result_file) as fin:
                update["results"] = json.load(fin)
        except (OSError, ValueError) as exc:
            update["error"] = str(exc)
        finally:
            try:
                os.unlink(result_file)
            except OSError:
                pass
        callback(update)


def farm_worker(master_address, name="task-farm", power=1.0):
    """Run a farm slave against ``master_address`` (blocking). The
    reference slaves ran the same ``veles`` binary; here any host with
    the package can serve evaluations."""
    from veles_tpu.fleet.client import Client
    client = Client(master_address, TaskFarmSlave(name), power=power)
    client.start()
    client.join()
    return client


def main(argv=None):  # pragma: no cover - manual entry point
    import argparse
    from veles_tpu.core.logger import setup_logging
    parser = argparse.ArgumentParser(
        prog="veles_tpu.fleet.farm",
        description="join a task farm as an evaluation slave")
    parser.add_argument("master", help="master HOST:PORT")
    parser.add_argument("--name", default="task-farm")
    parser.add_argument("--power", type=float, default=1.0)
    args = parser.parse_args(argv)
    setup_logging()
    farm_worker(args.master, args.name, args.power)


if __name__ == "__main__":  # pragma: no cover
    main()
