"""Deterministic chaos harness for the fleet (seeded fault injection).

Generalizes the reference's single fault hook (``death_probability``,
``client.py:438-442``) into a seeded, deterministic fault-injection layer
wrapping the slave's ``read_frame``/``write_frame`` calls and job loop:

- **frame delay** — sleep before a frame moves (network jitter);
- **frame drop** — close the transport and raise ``ConnectionResetError``
  (network blip / half-open connection); the client reconnects and the
  master requeues the in-flight lease;
- **slow slave** — stretch ``_do_job`` (straggler; exercises the adaptive
  mean+3sigma hang threshold);
- **duplicate-update replay** — ship the same update frame twice
  (at-least-once delivery); the master's job ledger must fence copy #2;
- **mid-job death** — the reference fault: die after computing the update
  but before shipping it. ``disconnect`` mode (default) severs the
  connection in-process so loopback tests can observe the recovery;
  ``exit`` mode is the reference's ``os._exit(1)`` for real processes.

Every decision comes from one ``random.Random(seed)`` stream, so a given
(seed, workload) pair replays the exact same fault schedule — chaos runs
are debuggable and assertable (the tier-1 chaos tests assert bit-identical
final weights against the fault-free run).

Configuration: ``root.common.fleet.chaos.*`` (see ``from_config``) or the
``--chaos-*`` CLI flags. Handshake frames are exempt by construction: the
client only routes post-welcome traffic through the monkey, so a fault
never masquerades as an authentication failure and the reconnect budget
stays honest.
"""

import asyncio
import os
import random

from veles_tpu.core.logger import Logger

#: chaos config keys that are fault probabilities
PROBABILITY_KEYS = ("frame_delay", "frame_drop", "slow_job",
                    "duplicate_update", "death")


def roll(rng, probability):
    """One seeded fault decision (a probability <= 0 never fires and
    never advances the stream) — the single implementation every chaos
    monkey (fleet and serving) rolls through."""
    return probability > 0.0 and rng.random() < probability


class ChaosConfigBase:
    """Shared validation for seeded fault-probability configs: each
    subclass lists its fault knobs in ``PROBABILITY_KEYS`` and feeds
    them through :meth:`_set_probabilities` (all must lie in [0, 1]);
    ``any_enabled`` is the default-on trigger ``from_config`` uses."""

    PROBABILITY_KEYS = ()

    def _set_probabilities(self, **values):
        for name, value in values.items():
            value = float(value)
            if not 0.0 <= value <= 1.0:
                raise ValueError("chaos %s probability %r outside [0, 1]"
                                 % (name, value))
            setattr(self, name, value)

    @property
    def any_enabled(self):
        return any(getattr(self, key) > 0.0
                   for key in self.PROBABILITY_KEYS)


class ChaosConfig(ChaosConfigBase):
    """Validated fleet chaos knobs (all probabilities in [0, 1])."""

    PROBABILITY_KEYS = PROBABILITY_KEYS

    def __init__(self, seed=1, frame_delay=0.0, frame_delay_ms=20.0,
                 frame_drop=0.0, slow_job=0.0, slow_job_ms=50.0,
                 duplicate_update=0.0, death=0.0, death_mode="disconnect"):
        self._set_probabilities(
            frame_delay=frame_delay, frame_drop=frame_drop,
            slow_job=slow_job, duplicate_update=duplicate_update,
            death=death)
        if death_mode not in ("disconnect", "exit"):
            raise ValueError("chaos death_mode must be 'disconnect' or "
                             "'exit', got %r" % (death_mode,))
        self.seed = int(seed)
        self.frame_delay_ms = float(frame_delay_ms)
        self.slow_job_ms = float(slow_job_ms)
        self.death_mode = death_mode


class ChaosMonkey(Logger):
    """The client-side fault injector (see module docstring)."""

    def __init__(self, config):
        super().__init__(logger_name="fleet.Chaos")
        self.config = config
        self._rng = random.Random(config.seed)
        self.counters = {"frames_delayed": 0, "frames_dropped": 0,
                         "jobs_slowed": 0, "updates_duplicated": 0,
                         "deaths": 0}

    @classmethod
    def from_config(cls):
        """Build from ``root.common.fleet.chaos``; returns ``None`` when
        chaos is disabled (no probability set, or ``enabled = False``)."""
        from veles_tpu.core.config import root
        cfg = root.common.fleet.chaos
        config = ChaosConfig(
            seed=cfg.get("seed", 1),
            frame_delay=cfg.get("frame_delay", 0.0),
            frame_delay_ms=cfg.get("frame_delay_ms", 20.0),
            frame_drop=cfg.get("frame_drop", 0.0),
            slow_job=cfg.get("slow_job", 0.0),
            slow_job_ms=cfg.get("slow_job_ms", 50.0),
            duplicate_update=cfg.get("duplicate_update", 0.0),
            death=cfg.get("death", 0.0),
            death_mode=cfg.get("death_mode", "disconnect"))
        if not cfg.get("enabled", config.any_enabled):
            return None
        monkey = cls(config)
        monkey.info(
            "chaos enabled (seed=%d): %s", config.seed,
            ", ".join("%s=%.3g" % (key, getattr(config, key))
                      for key in PROBABILITY_KEYS
                      if getattr(config, key) > 0.0))
        return monkey

    def _roll(self, probability):
        # one rng stream, always advanced in the same call order ->
        # deterministic fault schedule for a deterministic workload
        return roll(self._rng, probability)

    # -- frame-level faults ---------------------------------------------------
    async def read_frame(self, reader, key, **kwargs):
        from veles_tpu.fleet.protocol import read_frame
        await self._maybe_delay()
        self._maybe_drop(None)
        return await read_frame(reader, key, **kwargs)

    async def write_frame(self, writer, message, key, shm_threshold=None):
        from veles_tpu.fleet.protocol import write_frame
        await self._maybe_delay()
        self._maybe_drop(writer)
        if message.get("type") == "update":
            # stamp the running fault tallies into every update so the
            # master-side dashboard can prove each fault fired
            message["chaos"] = dict(self.counters)
        await write_frame(writer, message, key,
                          shm_threshold=shm_threshold)
        if message.get("type") == "update" \
                and self._roll(self.config.duplicate_update):
            self.counters["updates_duplicated"] += 1
            self.warning("chaos: replaying duplicate update (job_id=%r)",
                         message.get("job_id"))
            message["chaos"] = dict(self.counters)
            await write_frame(writer, message, key,
                              shm_threshold=shm_threshold)
        return None

    async def _maybe_delay(self):
        if self._roll(self.config.frame_delay):
            self.counters["frames_delayed"] += 1
            await asyncio.sleep(self.config.frame_delay_ms / 1000.0)

    def _maybe_drop(self, writer):
        if self._roll(self.config.frame_drop):
            self.counters["frames_dropped"] += 1
            self.warning("chaos: dropping frame (connection reset)")
            if writer is not None:
                writer.close()
            raise ConnectionResetError("chaos: injected frame drop")

    # -- job-level faults -----------------------------------------------------
    async def stretch_job(self):
        """Slow-slave fault: called by the client around ``_do_job``."""
        if self._roll(self.config.slow_job):
            self.counters["jobs_slowed"] += 1
            await asyncio.sleep(self.config.slow_job_ms / 1000.0)

    def maybe_die(self, writer=None):
        """The reference mid-job death, post-compute pre-ship."""
        if not self._roll(self.config.death):
            return
        self.counters["deaths"] += 1
        if self.config.death_mode == "exit":
            self.warning("chaos: dying mid-job (os._exit)")
            os._exit(1)
        self.warning("chaos: dying mid-job (disconnect)")
        if writer is not None:
            writer.close()
        raise ConnectionResetError("chaos: injected mid-job death")
