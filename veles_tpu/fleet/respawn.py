"""Slave respawn: relaunch dead slaves on their hosts.

Reference ``--respawn`` (``server.py:637-655`` + ``launcher.py:617-660``
``launch_remote_progs``): each slave's handshake carries its relaunch
recipe (executable, argv, cwd, PYTHONPATH); when a slave dies and does
not reconnect within a grace window, the master re-executes it — over
SSH for remote hosts, a plain subprocess for local ones — with
exponential backoff and a bounded attempt budget.

The actual process launch is a pluggable ``spawner(host, command, cwd,
env)`` so clusters with non-SSH launchers (k8s, slurm) slot in, and
tests inject a recorder.
"""

import os
import shlex
import subprocess
import threading

from veles_tpu.core.logger import Logger

LOCAL_HOSTS = ("127.0.0.1", "localhost", "::1")

#: env keys that must NEVER ride a remote command line — `ps` on either
#: end of the ssh session would expose them to any local user
SENSITIVE_ENV = ("VELES_TPU_FLEET_SECRET",)


def default_spawner(host, command, cwd=None, env=None):
    """ssh for remote hosts, a detached subprocess for local ones."""
    if host in LOCAL_HOSTS:
        full_env = dict(os.environ)
        full_env.update(env or {})
        return subprocess.Popen(
            command, shell=True, cwd=cwd, env=full_env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True)
    parts = ["ssh", "-o", "BatchMode=yes", host]
    env = dict(env or {})
    secret_items = [(k, env.pop(k)) for k in list(env)
                    if k in SENSITIVE_ENV]
    # env assignments must sit INSIDE the cd && chain — prefixed outside
    # they would scope to the `cd` builtin only
    for key, value in env.items():
        command = "%s=%s %s" % (key, shlex.quote(value), command)
    if cwd:
        command = "cd %s && %s" % (shlex.quote(cwd), command)
    stdin_data = None
    if secret_items:
        # secrets are piped over the (encrypted) ssh stdin and exported
        # by the remote shell before exec — never visible in argv
        command = ('while IFS="=" read -r __k __v; do export '
                   '"$__k"="$__v"; done; ' + command)
        stdin_data = "".join("%s=%s\n" % item
                             for item in secret_items).encode()
    parts.append(command)
    proc = subprocess.Popen(
        parts, stdin=subprocess.PIPE if stdin_data else None,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)
    if stdin_data:
        try:
            proc.stdin.write(stdin_data)
            proc.stdin.close()
        except OSError:
            # ssh died before reading (unreachable host, BatchMode
            # refusal): losing this one slave must not abort the caller
            # (the -n startup path has no catch of its own)
            pass
    return proc


def build_command(executable, argv):
    """One shell-quoted command line — THE quoting/join used by every
    spawn path (respawn and ``-n`` startup launch)."""
    return "%s %s" % (shlex.quote(executable),
                      " ".join(shlex.quote(a) for a in argv))


def spawn_env(pythonpath):
    """Env dict a spawned slave needs, or None when nothing does."""
    return {"PYTHONPATH": pythonpath} if pythonpath else None


def respawn_recipe():
    """The slave-side handshake payload (reference ``client.py:362-373``
    shipped argv/cwd/PYTHONPATH for exactly this). A ``python -m
    veles_tpu`` invocation is re-encoded as ``-m veles_tpu`` (sys.argv[0]
    is the __main__.py path, which in script mode would lose the package
    parent from sys.path)."""
    import sys
    argv = list(sys.argv)
    if argv and argv[0].endswith(os.path.join("veles_tpu",
                                              "__main__.py")):
        argv = ["-m", "veles_tpu"] + argv[1:]
    return {
        "executable": sys.executable,
        "argv": argv,
        "cwd": os.getcwd(),
        "pythonpath": os.environ.get("PYTHONPATH", ""),
    }


class RespawnManager(Logger):
    """Master-side relauncher with per-host backoff + attempt budget."""

    def __init__(self, spawner=None, max_attempts=5, base_delay=2.0,
                 extra_env=None):
        super().__init__()
        self.spawner = spawner or default_spawner
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        #: forwarded to every spawned slave (e.g. the fleet secret when
        #: it came from the master's environment — a slave without it
        #: would fail every HMAC and never join)
        self.extra_env = dict(extra_env or {})
        self._attempts = {}
        self._lock = threading.Lock()
        self._timers = []
        self._stopped = False

    @staticmethod
    def command_of(recipe):
        argv = list(recipe.get("argv") or [])
        executable = recipe.get("executable")
        if not executable or not argv:
            return None
        if "-b" not in argv and "--background" not in argv:
            # detach, like the reference; after the script/module part
            at = 2 if argv[0] == "-m" and len(argv) > 1 else 1
            argv.insert(at, "-b")
        return build_command(executable, argv)

    def schedule(self, host, recipe, key=None):
        """Respawn the slave described by ``recipe`` on ``host`` after the
        backoff delay. Returns False when out of budget / bad recipe."""
        command = self.command_of(recipe or {})
        if command is None:
            self.warning("cannot respawn: recipe incomplete")
            return False
        key = key or host
        with self._lock:
            if self._stopped:
                return False
            attempt = self._attempts.get(key, 0)
            if attempt >= self.max_attempts:
                self.warning("respawn budget exhausted for %s", key)
                return False
            self._attempts[key] = attempt + 1
        delay = self.base_delay * (2 ** attempt)
        self.info("respawning slave on %s in %.0fs (attempt %d/%d)",
                  host, delay, attempt + 1, self.max_attempts)
        env = spawn_env(recipe.get("pythonpath")) or {}
        env.update(self.extra_env)
        timer = threading.Timer(
            delay, self._spawn, (host, command, recipe.get("cwd"), env))
        timer.daemon = True
        with self._lock:
            if self._stopped:
                return False
            # prune fired timers so a long-lived master with flapping
            # slaves doesn't accumulate one Timer per schedule() forever
            self._timers = [t for t in self._timers if t.is_alive()]
            self._timers.append(timer)
        timer.start()
        return True

    def notify_reconnected(self, key):
        """A slave came back on its own: reset its budget."""
        with self._lock:
            self._attempts.pop(key, None)

    def _spawn(self, host, command, cwd, env):
        try:
            self.spawner(host, command, cwd=cwd, env=env)
        except Exception as exc:
            self.warning("respawn on %s failed: %s", host, exc)

    def stop(self):
        with self._lock:
            self._stopped = True
            timers = list(self._timers)
        for timer in timers:
            timer.cancel()
