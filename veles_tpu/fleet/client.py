"""Slave: connects to the master, runs jobs, ships updates.

Reference ``veles/client.py``. Kept semantics:

- handshake uploads computing power, machine id, pid, backend and the
  workflow checksum (``client.py:362-373``);
- job loop: job_received → do_job (on the workflow's thread pool) →
  update → ack → next request (``client.py:278-354``);
- ``--async-slave`` pipelining: request the next job before the update ack
  (``client.py:294-341``);
- auto-reconnect with an attempt budget, then exit
  (``client.py:488-508``);
- fault injection ``death_probability`` — the slave kills itself mid-job
  with the given probability, exercising the master's requeue path
  (``client.py:438-442``).

Robustness additions over the reference:

- every job carries a ``job_id`` lease and the master's ``epoch``
  (minted per ``Server.start()``); the client echoes both in the update
  so the master can fence duplicates, requeued leases and answers to a
  previous master incarnation (see ``fleet/ledger.py``);
- a welcome with a NEW epoch means the master restarted: the client
  re-handshakes cleanly and restores its reconnect budget;
- the single ``death_probability`` hook generalizes to the seeded
  deterministic chaos harness (``fleet/chaos.py``) wrapping the
  post-handshake frame traffic and the job loop.

Control-plane mode (``root.common.fleet.plane = "control"``,
``docs/compiler_fleet.md``): update frames carry ``results`` (scalar
metrics) plus a local ``tick`` counter instead of weight payloads —
the gradient math lives in XLA collectives on this slave's mesh. The
client keeps exactly-once application without weights on the wire via
the *rollback protocol*: every job frame echoes the master's highest
ACCEPTED tick; a local tick ahead of it means our last application was
never accepted (lost update), so the workflow rolls back its one-slot
params stash before re-applying (sync-mode pipelining bounds the gap
to one job — control-plane mode therefore forces ``async_mode`` off).
Weights cross the wire only at epoch fences (``sync`` frames, resent
until acked) and in the handshake's initial payload — which a
REJOINING client (same master epoch, local ticks applied) skips, since
its device-resident replica is ahead of the master's fence copy.
"""

import asyncio
import os
import random
import threading
import time

from veles_tpu.core.logger import Logger
from veles_tpu.fleet.protocol import (
    ProtocolError, machine_id, read_frame, resolve_secret, write_frame)
from veles_tpu.observe.fleetscope import get_span_ring
from veles_tpu.observe.metrics import get_metrics_registry
from veles_tpu.observe.tracing import get_tracer, parse_trace_field


class Client(Logger):
    """The fleet slave (reference ``client.py:405``)."""

    #: paused-poll backoff: first retry after PAUSE_POLL_BASE seconds,
    #: doubling up to PAUSE_POLL_MAX — a long-paused slave must not
    #: generate a steady 2 Hz frame stream
    PAUSE_POLL_BASE = 0.5
    PAUSE_POLL_MAX = 8.0

    def __init__(self, address, workflow, power=1.0, async_mode=False,
                 death_probability=0.0, max_reconnect_attempts=7,
                 secret=None, enable_respawn=False, chaos=None,
                 plane=None):
        super().__init__(logger_name="fleet.Client")
        self.enable_respawn = enable_respawn
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.workflow = workflow
        self._secret = resolve_secret(workflow, secret)
        self.power = power
        if plane is None:
            from veles_tpu.fleet import fleet_plane
            plane = fleet_plane()
        self.plane = plane
        self.control_plane = plane == "control"
        if self.control_plane and async_mode:
            # the one-slot rollback covers exactly ONE in-flight job;
            # pipelined requests would raise the unacknowledged depth
            # past what the stash can replay
            self.warning("control-plane fleet mode is sync-only: "
                         "disabling --async-slave pipelining")
            async_mode = False
        self.async_mode = async_mode
        #: control-plane accounting: locally-applied job count (ships
        #: as ``tick`` in updates; reset when the master epoch changes)
        self._applied_ticks_ = 0
        #: pending epoch-fence weight sync, resent until acked
        self._pending_sync_ = None
        #: rollback-protocol events (re-issued work realigned against
        #: the master's acked tick; the chaos tests assert on this)
        self.rollbacks = 0
        #: the master's handshake-refusal reason, if any (testability)
        self.refusal = None
        self.death_probability = death_probability
        self.max_reconnect_attempts = max_reconnect_attempts
        if chaos is None:
            # default: build from root.common.fleet.chaos (None when no
            # fault is configured); pass chaos=False to force-disable
            from veles_tpu.fleet.chaos import ChaosMonkey
            chaos = ChaosMonkey.from_config()
        self.chaos = chaos or None
        self.sid = None
        self.master_epoch = None
        self.jobs_done = 0
        #: wall ms of the last workflow job run (ships as ``job_ms``
        #: so the master's goodput decomposition can split compute
        #: from host time inside our residence window)
        self._last_job_ms_ = 0.0
        #: cumulative rollback-discarded compute (control plane): work
        #: whose update was lost and re-done bit-identically — ships on
        #: update frames for the master's wasted-work accounting
        self.rollback_ms = 0.0
        # completed-span summaries ride our update frames (observe/
        # fleetscope.py): enable the bounded process ring; it only
        # fills while tracing is on
        get_span_ring().enable()
        self._attempts = 0
        self._loop = None
        self._thread = None
        self._stopped = threading.Event()
        self.on_finished = None

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-client")
        self._thread.start()
        return self

    def _run(self):
        # GC segments a crashed master of a PREVIOUS run never consumed —
        # long-lived clients are senders too (updates ride shm) and must
        # not rely on some future Server.start() to clean /dev/shm
        from veles_tpu.fleet import sharedio
        sharedio.cleanup_stale()
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._session())
        finally:
            self._loop.close()
        if self.on_finished is not None:
            self.on_finished()

    def stop(self):
        self._stopped.set()
        # wake the session coroutine: it is usually parked in read_frame,
        # so close the transport from the loop thread
        if self._loop is not None and self._loop.is_running():
            self._loop.call_soon_threadsafe(self._close_connection)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _close_connection(self):
        writer = getattr(self, "_writer_", None)
        if writer is not None:
            writer.close()

    def join(self, timeout=None):
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    def update_power(self, power):
        """Re-report computing power mid-run (reference periodic power
        re-upload, client.py:308-313; the master rebalances parked job
        requests by it)."""
        self.power = power

        async def send():
            writer = getattr(self, "_writer_", None)
            if writer is not None:
                await write_frame(writer, {"type": "power",
                                           "power": power}, self._secret)

        if self._loop is not None and self._loop.is_running():
            asyncio.run_coroutine_threadsafe(send(), self._loop)

    # -- session with reconnect budget ---------------------------------------
    async def _session(self):
        self._attempts = 0
        while not self._stopped.is_set():
            try:
                reader, writer = await asyncio.open_connection(
                    self.host, self.port)
            except OSError:
                self._attempts += 1
                if self._attempts > self.max_reconnect_attempts:
                    self.error("gave up reconnecting after %d attempts",
                               self._attempts - 1)
                    return
                await asyncio.sleep(min(0.2 * 2 ** self._attempts, 5.0))
                continue
            self._writer_ = writer
            self._handshaked_ = False
            try:
                done = await self._work(reader, writer)
                if done:
                    return
                self._attempts = 0
            except (asyncio.IncompleteReadError, ConnectionError,
                    ProtocolError) as exc:
                if not self._handshaked_:
                    # the master dropped us mid-handshake (secret/checksum
                    # mismatch shows up as a silent close on its side):
                    # this is NOT a transient network loss — burn an
                    # attempt and back off, or we busy-loop forever
                    self._attempts += 1
                    if self._attempts > self.max_reconnect_attempts:
                        self.error(
                            "master refused the handshake %d times "
                            "(wrong fleet secret or workflow checksum?); "
                            "giving up", self._attempts - 1)
                        return
                    self.warning("handshake failed (%s); retrying",
                                 type(exc).__name__)
                    await asyncio.sleep(min(0.2 * 2 ** self._attempts,
                                            5.0))
                else:
                    self._attempts = 0
                    self.warning("connection to master lost; reconnecting")
                    # breathe before reconnecting: a master that welcomes
                    # then consistently drops would otherwise be hammered
                    # by a zero-backoff loop
                    await asyncio.sleep(0.2)
            finally:
                writer.close()

    async def _work(self, reader, writer):
        from veles_tpu.fleet import sharedio
        hello = {
            "type": "hello", "power": self.power, "mid": machine_id(),
            "pid": os.getpid(), "backend": "tpu",
            # shm eligibility facts: the master enables the /dev/shm data
            # plane only when uid and shm directory match too — a
            # same-machine different-user peer cannot read 0o600 segments
            "uid": sharedio.owner_uid(), "shm_dir": sharedio.shm_dir(),
            # wire-plane agreement is checked at the handshake: a mixed
            # data/control fleet must fail loudly, not stall
            "plane": self.plane,
            "checksum": getattr(self.workflow, "checksum", None)}
        if self.enable_respawn:
            # relaunch recipe for the master's --respawn (reference
            # client.py:362-373 shipped argv/cwd/PYTHONPATH)
            from veles_tpu.fleet.respawn import respawn_recipe
            hello["respawn"] = respawn_recipe()
        await write_frame(writer, hello, self._secret)
        welcome = await read_frame(reader, self._secret)
        if welcome.get("type") == "error":
            self.refusal = welcome.get("error")
            self.error("master refused: %s", self.refusal)
            return True
        self._handshaked_ = True
        self.sid = welcome["id"]
        epoch = welcome.get("epoch")
        # control plane: a rejoin under the SAME master epoch with
        # local applications on record means our device-resident
        # replica is AHEAD of the master's last fence copy — the
        # handshake's initial weights must not clobber it (the
        # rollback protocol realigns any lost tick instead)
        rejoining = (self.control_plane
                     and self.master_epoch is not None
                     and epoch == self.master_epoch
                     and self._applied_ticks_ > 0)
        if self.master_epoch is not None and epoch != self.master_epoch:
            # a NEW epoch means the master restarted (not a network
            # blip): this handshake is a clean re-join — restore the
            # reconnect budget burnt while the master was away
            self.info("master epoch changed (%s -> %s): master "
                      "restarted, re-handshaking cleanly",
                      self.master_epoch, epoch)
            self._attempts = 0
            # the successor's accounting starts fresh: its ledger and
            # acked-tick table know nothing of our prior applications,
            # and its initial payload (applied below) re-seeds state
            self._applied_ticks_ = 0
            self._pending_sync_ = None
        self.master_epoch = epoch
        # master confirmed the same-host shared-memory data plane
        from veles_tpu.fleet.protocol import COMPRESS_THRESHOLD
        self._shm_thr_ = (COMPRESS_THRESHOLD if welcome.get("shm")
                          else None)
        initial = welcome.get("initial")
        if initial and not rejoining:
            self.workflow.apply_initial_data_from_master(initial)
        elif initial and rejoining:
            self.info("rejoining the same master epoch with %d local "
                      "tick(s) applied: keeping the device-resident "
                      "replica (handshake weights skipped)",
                      self._applied_ticks_)
        self.info("connected as %s", self.sid)
        # the handshake above never routes through chaos — a fault must
        # not masquerade as an authentication failure; everything below
        # does (self._read/self._write)
        if self.control_plane:
            # an epoch-fence sync the previous connection never got
            # acked goes out FIRST, before any new job can advance the
            # master's accepted-job record past its fence
            await self._flush_sync(writer)
        await self._write(writer, {"type": "job_request"})
        pause_streak = 0
        while not self._stopped.is_set():
            msg = await self._read(reader)
            mtype = msg.get("type")
            if mtype != "job" or not msg.get("paused"):
                pause_streak = 0
            if mtype == "job":
                if msg.get("paused"):
                    # capped exponential backoff: a long-paused slave
                    # must not poll the master at a steady 2 Hz
                    await asyncio.sleep(
                        min(self.PAUSE_POLL_BASE * 2 ** pause_streak,
                            self.PAUSE_POLL_MAX))
                    pause_streak += 1
                    await self._write(writer, {"type": "job_request"})
                    continue
                if msg.get("job") is None:
                    if self.control_plane \
                            and self._pending_sync_ is not None:
                        # belt and braces: never exit with an unacked
                        # fence sync — fire it once more (idempotent
                        # overwrite on the master) so the final
                        # weights cannot stay an epoch stale behind a
                        # lost ack
                        await self._flush_sync(writer)
                    self.info("no more jobs; exiting")
                    return True
                job_id = msg.get("job_id")
                # NTP stamp pair for the master's clock aligner
                # (observe/fleetscope.py): our receive mono now, our
                # send mono stamped just before the update write
                rx_mono = time.monotonic()
                if self.control_plane:
                    self._maybe_rollback(msg)
                # the master's fleet.issue context rides the job frame;
                # our do_job span parents to it and our update echoes
                # OUR context so the master's fleet.apply chains on —
                # one job, one connected trace (docs/observability.md)
                job_span = get_tracer().span(
                    "fleet.do_job",
                    parent=parse_trace_field(msg.get("trace")),
                    job_id=job_id, sid=self.sid)
                with job_span:
                    update = await self._do_job(msg["job"])
                if self.control_plane:
                    # booked the moment the local application exists —
                    # a death between here and the update write leaves
                    # tick > acked, which is exactly what arms the
                    # rollback on the re-issued job
                    self._applied_ticks_ += 1
                if self.chaos is not None:
                    self.chaos.maybe_die(writer)
                if self.death_probability > 0 \
                        and random.random() < self.death_probability:
                    self.warning("fault injection: dying mid-job")
                    os._exit(1)
                shm_thr = getattr(self, "_shm_thr_", None)
                # echo the lease + master epoch: the ledger fences
                # duplicates, requeued leases and stale-epoch answers.
                # Control plane: scalar results + the local tick — the
                # weight payload is omitted ENTIRELY (the master
                # rejects frames that carry one)
                frame = {"type": "update",
                         "job_id": job_id, "epoch": self.master_epoch,
                         # [job-receipt mono, update-send mono]: the
                         # slave half of the clock-alignment exchange;
                         # the send stamp is filled right before write
                         "mono": [rx_mono, 0.0],
                         "job_ms": round(self._last_job_ms_, 3)}
                if self.control_plane:
                    frame["results"] = update
                    frame["tick"] = self._applied_ticks_
                if self.rollback_ms > 0:
                    frame["rollback_ms"] = round(self.rollback_ms, 3)
                if not self.control_plane:
                    frame["update"] = update
                if job_span.context() is not None:
                    frame["trace"] = list(job_span.context())
                ring = get_span_ring()
                if len(ring):
                    # completed-span summaries since the last frame
                    # (bounded rows; the master validates + dedupes)
                    rows = ring.drain()
                    if rows:
                        frame["spans"] = rows
                registry = get_metrics_registry()
                if registry.enabled:
                    # piggyback this slave's counter/gauge snapshot so
                    # the master's /metrics aggregates the whole fleet
                    # without another connection or scrape schedule;
                    # the device-truth collector rides along — the
                    # master re-exports each slave's compile counts
                    # and memory gauges under its slave label. Each row
                    # additionally carries this process's mesh
                    # coordinates (process index + active mesh shape)
                    # so a master scrape distinguishes the SHARDS of a
                    # pod-mode slave, not just the slaves.
                    from veles_tpu.observe.slo import (
                        ensure_slo_registered)
                    from veles_tpu.observe.xla_stats import (
                        ensure_registered)
                    from veles_tpu.parallel.mesh import (
                        mesh_coordinate_labels)
                    ensure_registered(registry)
                    # a serving slave's SLO gauges ride the same
                    # snapshot: the master re-exports its burn rates
                    # slave-labeled, like the mesh/device rows
                    ensure_slo_registered(registry)
                    coords = sorted(mesh_coordinate_labels().items())
                    frame["metrics"] = [
                        [name, kind,
                         [list(kv) for kv in labels]
                         + [[k, v] for k, v in coords
                            if k not in dict(labels)],
                         value]
                        for name, kind, labels, value
                        in registry.snapshot()]
                    # the metric-history summary rides along
                    # (observe/history.py): the master ingests it
                    # slave-labeled into ITS history, so a master-side
                    # incident autopsy spans the fleet's trends, not
                    # just its own
                    from veles_tpu.observe.history import (
                        get_metric_history)
                    history = get_metric_history()
                    if history is not None and history.samples_total:
                        rows = history.fleet_summary()
                        if rows:
                            frame["history"] = rows
                frame["mono"][1] = time.monotonic()
                await self._write(writer, frame, shm_threshold=shm_thr)
                if self.control_plane:
                    # epoch fence? the workflow hands over the bulk
                    # weight sync exactly once per fence; it is resent
                    # on every (re)connection until the master acks it
                    take = getattr(self.workflow, "take_fence_sync",
                                   None)
                    payload = take() if callable(take) else None
                    if payload is not None:
                        self._pending_sync_ = {
                            "job_id": job_id, "sync": payload,
                            "tick": self._applied_ticks_}
                    await self._flush_sync(writer)
                if self.async_mode:
                    # pipelined: next request goes out with the update
                    await self._write(writer, {"type": "job_request"})
            elif mtype == "update_ack":
                if msg.get("fenced"):
                    # the master rejected the (duplicate/stale) update;
                    # this ack is informational — requesting another job
                    # for it would double-feed the pipeline
                    self.warning("master fenced our update: %s",
                                 msg["fenced"])
                elif not self.async_mode:
                    await self._write(writer, {"type": "job_request"})
            elif mtype == "sync_ack":
                if msg.get("fenced"):
                    # the master refused the fence payload (stale epoch
                    # / unaccepted job): a later fence supersedes it —
                    # retrying a refused sync would replay the refusal
                    self.warning("master fenced our sync: %s",
                                 msg["fenced"])
                self._pending_sync_ = None
        return False

    def _maybe_rollback(self, msg):
        """Control-plane rollback protocol: the job frame echoes the
        master's highest ACCEPTED local tick; if we applied more than
        that, our last application's update was lost (death/drop after
        the local math ran) and the incoming job re-issues that work —
        roll the one-slot stash back so the replay is bit-identical."""
        acked = msg.get("acked")
        if not isinstance(acked, int) or isinstance(acked, bool) \
                or self._applied_ticks_ <= acked:
            return
        behind = self._applied_ticks_ - acked
        if behind > 1:
            # cannot happen in sync mode (one job in flight); if it
            # ever does, continuing silently would double-apply work
            self.error(
                "%d unacknowledged local applications but only a "
                "one-slot rollback — local state may have diverged; "
                "re-handshake with a fresh master to re-seed", behind)
            return
        rollback = getattr(self.workflow, "rollback_job", None)
        rolled = bool(rollback()) if callable(rollback) else False
        if rolled:
            # the discarded application's compute is re-done on the
            # replay — book it as wasted work for the master's goodput
            # accounting (ships cumulative as ``rollback_ms``)
            self.rollback_ms += self._last_job_ms_
        self.rollbacks += 1
        self._applied_ticks_ = acked
        self.warning(
            "master re-issued unacknowledged work (local tick %d -> "
            "acked %d): %s", acked + 1, acked,
            "rolled params back one job" if rolled
            else "eval tick, nothing to restore")

    async def _flush_sync(self, writer):
        """Ship the pending epoch-fence weight sync (if any). Kept
        pending until the master's ``sync_ack`` arrives, so a
        connection lost mid-sync resends it on the next handshake —
        the master's final weights cannot silently stay one epoch
        stale because a fence frame hit a chaos drop."""
        if self._pending_sync_ is None:
            return
        frame = dict(self._pending_sync_)
        frame["type"] = "sync"
        frame["epoch"] = self.master_epoch
        await self._write(writer, frame,
                          shm_threshold=getattr(self, "_shm_thr_",
                                                None))

    async def _read(self, reader):
        if self.chaos is not None:
            return await self.chaos.read_frame(reader, self._secret)
        return await read_frame(reader, self._secret)

    async def _write(self, writer, message, shm_threshold=None):
        if self.chaos is not None:
            await self.chaos.write_frame(writer, message, self._secret,
                                         shm_threshold=shm_threshold)
        else:
            await write_frame(writer, message, self._secret,
                              shm_threshold=shm_threshold)

    async def _do_job(self, job):
        """Run the whole workflow locally on the job (reference
        ``workflow.py:554-569``), off the event loop."""
        loop = asyncio.get_event_loop()
        future = loop.create_future()

        def callback(update):
            loop.call_soon_threadsafe(future.set_result, update)

        def launch():
            self.workflow.do_job(job, callback)

        started = time.monotonic()
        await loop.run_in_executor(None, launch)
        update = await future
        # the workflow's own wall only: the chaos slow-slave stretch
        # below is injected residence the goodput decomposition must
        # book as HOST time, not compute
        self._last_job_ms_ = (time.monotonic() - started) * 1000.0
        if self.chaos is not None:
            await self.chaos.stretch_job()  # slow-slave fault
        self.jobs_done += 1
        return update
