"""Slave: connects to the master, runs jobs, ships updates.

Reference ``veles/client.py``. Kept semantics:

- handshake uploads computing power, machine id, pid, backend and the
  workflow checksum (``client.py:362-373``);
- job loop: job_received → do_job (on the workflow's thread pool) →
  update → ack → next request (``client.py:278-354``);
- ``--async-slave`` pipelining: request the next job before the update ack
  (``client.py:294-341``);
- auto-reconnect with an attempt budget, then exit
  (``client.py:488-508``);
- fault injection ``death_probability`` — the slave kills itself mid-job
  with the given probability, exercising the master's requeue path
  (``client.py:438-442``).
"""

import asyncio
import os
import random
import threading

from veles_tpu.core.logger import Logger
from veles_tpu.fleet.protocol import (
    ProtocolError, machine_id, read_frame, resolve_secret, write_frame)


class Client(Logger):
    """The fleet slave (reference ``client.py:405``)."""

    def __init__(self, address, workflow, power=1.0, async_mode=False,
                 death_probability=0.0, max_reconnect_attempts=7,
                 secret=None, enable_respawn=False):
        super().__init__(logger_name="fleet.Client")
        self.enable_respawn = enable_respawn
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.workflow = workflow
        self._secret = resolve_secret(workflow, secret)
        self.power = power
        self.async_mode = async_mode
        self.death_probability = death_probability
        self.max_reconnect_attempts = max_reconnect_attempts
        self.sid = None
        self.jobs_done = 0
        self._loop = None
        self._thread = None
        self._stopped = threading.Event()
        self.on_finished = None

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-client")
        self._thread.start()
        return self

    def _run(self):
        # GC segments a crashed master of a PREVIOUS run never consumed —
        # long-lived clients are senders too (updates ride shm) and must
        # not rely on some future Server.start() to clean /dev/shm
        from veles_tpu.fleet import sharedio
        sharedio.cleanup_stale()
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._session())
        finally:
            self._loop.close()
        if self.on_finished is not None:
            self.on_finished()

    def stop(self):
        self._stopped.set()
        # wake the session coroutine: it is usually parked in read_frame,
        # so close the transport from the loop thread
        if self._loop is not None and self._loop.is_running():
            self._loop.call_soon_threadsafe(self._close_connection)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _close_connection(self):
        writer = getattr(self, "_writer_", None)
        if writer is not None:
            writer.close()

    def join(self, timeout=None):
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    def update_power(self, power):
        """Re-report computing power mid-run (reference periodic power
        re-upload, client.py:308-313; the master rebalances parked job
        requests by it)."""
        self.power = power

        async def send():
            writer = getattr(self, "_writer_", None)
            if writer is not None:
                await write_frame(writer, {"type": "power",
                                           "power": power}, self._secret)

        if self._loop is not None and self._loop.is_running():
            asyncio.run_coroutine_threadsafe(send(), self._loop)

    # -- session with reconnect budget ---------------------------------------
    async def _session(self):
        attempts = 0
        while not self._stopped.is_set():
            try:
                reader, writer = await asyncio.open_connection(
                    self.host, self.port)
            except OSError:
                attempts += 1
                if attempts > self.max_reconnect_attempts:
                    self.error("gave up reconnecting after %d attempts",
                               attempts - 1)
                    return
                await asyncio.sleep(min(0.2 * 2 ** attempts, 5.0))
                continue
            self._writer_ = writer
            self._handshaked_ = False
            try:
                done = await self._work(reader, writer)
                if done:
                    return
                attempts = 0
            except (asyncio.IncompleteReadError, ConnectionError,
                    ProtocolError) as exc:
                if not self._handshaked_:
                    # the master dropped us mid-handshake (secret/checksum
                    # mismatch shows up as a silent close on its side):
                    # this is NOT a transient network loss — burn an
                    # attempt and back off, or we busy-loop forever
                    attempts += 1
                    if attempts > self.max_reconnect_attempts:
                        self.error(
                            "master refused the handshake %d times "
                            "(wrong fleet secret or workflow checksum?); "
                            "giving up", attempts - 1)
                        return
                    self.warning("handshake failed (%s); retrying",
                                 type(exc).__name__)
                    await asyncio.sleep(min(0.2 * 2 ** attempts, 5.0))
                else:
                    attempts = 0
                    self.warning("connection to master lost; reconnecting")
                    # breathe before reconnecting: a master that welcomes
                    # then consistently drops would otherwise be hammered
                    # by a zero-backoff loop
                    await asyncio.sleep(0.2)
            finally:
                writer.close()

    async def _work(self, reader, writer):
        from veles_tpu.fleet import sharedio
        hello = {
            "type": "hello", "power": self.power, "mid": machine_id(),
            "pid": os.getpid(), "backend": "tpu",
            # shm eligibility facts: the master enables the /dev/shm data
            # plane only when uid and shm directory match too — a
            # same-machine different-user peer cannot read 0o600 segments
            "uid": sharedio.owner_uid(), "shm_dir": sharedio.shm_dir(),
            "checksum": getattr(self.workflow, "checksum", None)}
        if self.enable_respawn:
            # relaunch recipe for the master's --respawn (reference
            # client.py:362-373 shipped argv/cwd/PYTHONPATH)
            from veles_tpu.fleet.respawn import respawn_recipe
            hello["respawn"] = respawn_recipe()
        await write_frame(writer, hello, self._secret)
        welcome = await read_frame(reader, self._secret)
        if welcome.get("type") == "error":
            self.error("master refused: %s", welcome.get("error"))
            return True
        self._handshaked_ = True
        self.sid = welcome["id"]
        # master confirmed the same-host shared-memory data plane
        from veles_tpu.fleet.protocol import COMPRESS_THRESHOLD
        self._shm_thr_ = (COMPRESS_THRESHOLD if welcome.get("shm")
                          else None)
        initial = welcome.get("initial")
        if initial:
            self.workflow.apply_initial_data_from_master(initial)
        self.info("connected as %s", self.sid)
        await write_frame(writer, {"type": "job_request"}, self._secret)
        while not self._stopped.is_set():
            msg = await read_frame(reader, self._secret)
            mtype = msg.get("type")
            if mtype == "job":
                if msg.get("paused"):
                    await asyncio.sleep(0.5)
                    await write_frame(writer, {"type": "job_request"}, self._secret)
                    continue
                if msg.get("job") is None:
                    self.info("no more jobs; exiting")
                    return True
                update = await self._do_job(msg["job"])
                if self.death_probability > 0 \
                        and random.random() < self.death_probability:
                    self.warning("fault injection: dying mid-job")
                    os._exit(1)
                shm_thr = getattr(self, "_shm_thr_", None)
                if self.async_mode:
                    # pipelined: next request goes out with the update
                    await write_frame(writer, {"type": "update",
                                               "update": update},
                                      self._secret, shm_threshold=shm_thr)
                    await write_frame(writer, {"type": "job_request"}, self._secret)
                else:
                    await write_frame(writer, {"type": "update",
                                               "update": update},
                                      self._secret, shm_threshold=shm_thr)
            elif mtype == "update_ack":
                if not self.async_mode:
                    await write_frame(writer, {"type": "job_request"}, self._secret)
        return False

    async def _do_job(self, job):
        """Run the whole workflow locally on the job (reference
        ``workflow.py:554-569``), off the event loop."""
        loop = asyncio.get_event_loop()
        future = loop.create_future()

        def callback(update):
            loop.call_soon_threadsafe(future.set_result, update)

        def launch():
            self.workflow.do_job(job, callback)

        await loop.run_in_executor(None, launch)
        update = await future
        self.jobs_done += 1
        return update
