"""veles_tpu.fleet: elastic host-level distribution (master/slave).

The reference's distributed runtime (SURVEY §2.5) is a master/slave
data-parallel protocol: the master owns canonical state and serves *jobs*
(per-unit payloads — for the Loader just minibatch indices); each slave
runs the whole workflow on its job and returns an *update*, merged into
master state. Asynchronous by default (stale updates accepted), elastic
(slaves join/leave any time, their pending work is requeued), with hang
detection and fault injection.

TPU translation: inside one pod slice, synchronous SPMD (``parallel/``) is
the idiomatic path. Fleet mode exists for what collectives can't do —
dynamic/heterogeneous clusters over DCN, genetics/ensemble population
parallelism, and parity with the reference's elasticity semantics. The
transport is asyncio TCP with length-prefixed pickled frames (the modern
stdlib equivalent of the reference's Twisted control plane + ZeroMQ
streaming-pickle data plane, reference ``txzmq/connection.py:395-562``).
"""

from veles_tpu.fleet.server import Server  # noqa: F401
from veles_tpu.fleet.client import Client  # noqa: F401
