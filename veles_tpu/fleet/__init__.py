"""veles_tpu.fleet: elastic host-level distribution (master/slave).

The reference's distributed runtime (SURVEY §2.5) is a master/slave
data-parallel protocol: the master owns canonical state and serves *jobs*
(per-unit payloads — for the Loader just minibatch indices); each slave
runs the whole workflow on its job and returns an *update*, merged into
master state. Asynchronous by default (stale updates accepted), elastic
(slaves join/leave any time, their pending work is requeued), with hang
detection and fault injection.

TPU translation: inside one pod slice, synchronous SPMD (``parallel/``) is
the idiomatic path. Fleet mode exists for what collectives can't do —
dynamic/heterogeneous clusters over DCN, genetics/ensemble population
parallelism, and parity with the reference's elasticity semantics. The
transport is asyncio TCP with length-prefixed pickled frames (the modern
stdlib equivalent of the reference's Twisted control plane + ZeroMQ
streaming-pickle data plane, reference ``txzmq/connection.py:395-562``).

Fault tolerance (docs/fleet_robustness.md): every served job is a
*leased* ledger entry (``ledger.py``) — expired or dropped leases are
requeued explicitly and duplicate/stale/foreign updates are fenced,
with the master's per-start ``epoch`` UUID fencing across restarts. The
deterministic chaos harness (``chaos.py``) injects frame delay/drop,
stragglers, duplicate replay and mid-job death from one seeded RNG
stream so recovery is testable bit-for-bit.
"""

from veles_tpu.fleet.server import Server  # noqa: F401
from veles_tpu.fleet.client import Client  # noqa: F401
from veles_tpu.fleet.ledger import JobLedger  # noqa: F401
from veles_tpu.fleet.chaos import ChaosConfig, ChaosMonkey  # noqa: F401
