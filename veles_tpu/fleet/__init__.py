"""veles_tpu.fleet: elastic host-level distribution (master/slave).

The reference's distributed runtime (SURVEY §2.5) is a master/slave
data-parallel protocol: the master owns canonical state and serves *jobs*
(per-unit payloads — for the Loader just minibatch indices); each slave
runs the whole workflow on its job and returns an *update*, merged into
master state. Asynchronous by default (stale updates accepted), elastic
(slaves join/leave any time, their pending work is requeued), with hang
detection and fault injection.

TPU translation: inside one pod slice, synchronous SPMD (``parallel/``) is
the idiomatic path. Fleet mode exists for what collectives can't do —
dynamic/heterogeneous clusters over DCN, genetics/ensemble population
parallelism, and parity with the reference's elasticity semantics. The
transport is asyncio TCP with length-prefixed pickled frames (the modern
stdlib equivalent of the reference's Twisted control plane + ZeroMQ
streaming-pickle data plane, reference ``txzmq/connection.py:395-562``).

Fault tolerance (docs/fleet_robustness.md): every served job is a
*leased* ledger entry (``ledger.py``) — expired or dropped leases are
requeued explicitly and duplicate/stale/foreign updates are fenced,
with the master's per-start ``epoch`` UUID fencing across restarts. The
deterministic chaos harness (``chaos.py``) injects frame delay/drop,
stragglers, duplicate replay and mid-job death from one seeded RNG
stream so recovery is testable bit-for-bit.

Wire planes (``root.common.fleet.plane``, docs/compiler_fleet.md):

- ``data`` (default) — the reference protocol: jobs carry master
  weights, updates carry trained weights, the master merges host-side.
  Per-minibatch durability; the chip idles through every reduce.
- ``control`` — the compiler-visible refit: jobs carry batch
  *assignments* + epoch fences (plus learning rates), updates carry
  scalar metrics, and the parameter math lives entirely in XLA
  collectives on the slave's mesh (``parallel/mapreduce.py``). Weights
  cross the wire only in the handshake (initial state) and at epoch
  fences (the ``sync`` frame). The ledger/lease/fencing/chaos/respawn
  machinery is identical in both planes.
"""


def fleet_control_plane():
    """True when the fleet runs the control-plane-only wire protocol
    (``root.common.fleet.plane = "control"``). Validates the knob."""
    from veles_tpu.core.config import root
    plane = root.common.fleet.get("plane", "data")
    if plane not in ("data", "control"):
        raise ValueError(
            "root.common.fleet.plane / --fleet-plane must be 'data' or "
            "'control', got %r" % (plane,))
    return plane == "control"


def fleet_plane():
    """The configured plane name ("data"/"control"), validated."""
    return "control" if fleet_control_plane() else "data"


from veles_tpu.fleet.server import Server  # noqa: F401,E402
from veles_tpu.fleet.client import Client  # noqa: F401,E402
from veles_tpu.fleet.ledger import JobLedger  # noqa: F401,E402
from veles_tpu.fleet.chaos import ChaosConfig, ChaosMonkey  # noqa: F401,E402
