"""Pickle-free job/update serialization for the fleet wire.

The default fleet codec is pickle (the reference shipped pickles on its
ZeroMQ data plane too, ``network_common.py``), authenticated by the
frame HMAC — but a *leaked secret* then means remote code execution in
both directions. Setting ``root.common.fleet.codec = "safe"`` on every
host switches the wire to THIS codec: a closed, data-only format whose
decoder can execute nothing — a compromised secret is then worth at most
bogus training data.

Format: ``[4-byte big-endian header length][JSON header][raw blobs...]``
where the header describes a tree of supported values and arrays refer
to contiguous byte ranges in the blob section. Supported: ``None``,
``bool``, ``int``, ``float``, ``str``, ``bytes``, ``list``, ``tuple``,
``dict`` (any encodable keys), numpy scalars and arrays, and JAX arrays
(decoded as numpy — units convert on assignment anyway). Anything else
raises at ENCODE time with the offending type, so a workflow whose
job/update payloads need richer objects fails loudly on the sender and
can stay on the pickle codec deliberately.
"""

import json
import struct

import numpy

_LEN = struct.Struct(">I")


class UnsupportedType(TypeError):
    """Payload contains an object the safe codec refuses to carry."""


def _dtype_tag(dtype):
    if dtype == object:
        raise UnsupportedType(
            "object-dtype arrays cannot ride the safe fleet codec")
    if dtype.kind == "V":
        # ml_dtypes scalars (bfloat16, fp8...) present as anonymous
        # void in .str; their registered NAME round-trips. True
        # structured dtypes have fields and are refused.
        if dtype.fields is not None:
            raise UnsupportedType(
                "structured arrays cannot ride the safe fleet codec")
        return dtype.name
    return dtype.str


def _coerce_key(key):
    """Dict keys must round-trip hashable: numpy scalars become their
    python equivalents (same hash/equality, so lookups behave), tuples
    recurse, everything else simple — or fail at ENCODE time."""
    if isinstance(key, numpy.generic):
        key = key.item()
    if isinstance(key, tuple):
        return tuple(_coerce_key(k) for k in key)
    if key is None or isinstance(key, (bool, int, float, str, bytes)):
        return key
    raise UnsupportedType(
        "dict key of type %s cannot ride the safe fleet codec"
        % type(key).__name__)


def _encode(obj, blobs, offset):
    """Returns (header_node, new_offset)."""
    if obj is None or isinstance(obj, (bool, str)):
        return obj, offset
    if isinstance(obj, (int, float)) and not isinstance(obj, bool):
        # JSON carries them natively; NaN/inf are handled by json's
        # default (non-strict) encoder and parsed back by float()
        return obj, offset
    if isinstance(obj, bytes):
        blobs.append(obj)
        node = {"t": "b", "o": offset, "n": len(obj)}
        return node, offset + len(obj)
    if isinstance(obj, numpy.generic):  # numpy scalar: own tag — the
        # receiver rebuilds the SAME scalar type, not a 0-d array
        arr = numpy.asarray(obj)
        data = arr.tobytes()
        blobs.append(data)
        node = {"t": "s", "d": _dtype_tag(arr.dtype),
                "o": offset, "n": len(data)}
        return node, offset + len(data)
    try:
        import jax
        if isinstance(obj, jax.Array):
            obj = numpy.asarray(obj)
    except ImportError:  # pragma: no cover - jax is always present here
        pass
    if isinstance(obj, numpy.ndarray):
        data = numpy.ascontiguousarray(obj).tobytes()
        blobs.append(data)
        node = {"t": "a", "d": _dtype_tag(obj.dtype),
                "s": list(obj.shape), "o": offset, "n": len(data)}
        return node, offset + len(data)
    if isinstance(obj, (list, tuple)):
        items = []
        for item in obj:
            node, offset = _encode(item, blobs, offset)
            items.append(node)
        return {"t": "l" if isinstance(obj, list) else "u",
                "v": items}, offset
    if isinstance(obj, dict):
        items = []
        for key, value in obj.items():
            # fail-loudly-at-the-sender contract: keys are validated
            # (and numpy scalars coerced) HERE, so nothing encodes that
            # the receiver would have to reject
            knode, offset = _encode(_coerce_key(key), blobs, offset)
            vnode, offset = _encode(value, blobs, offset)
            items.append([knode, vnode])
        return {"t": "d", "v": items}, offset
    raise UnsupportedType(
        "%s cannot ride the safe fleet codec (supported: None/bool/int/"
        "float/str/bytes/list/tuple/dict/numpy/jax arrays); set "
        "root.common.fleet.codec = 'pickle' if this payload is "
        "intentional" % type(obj).__name__)


def _decode(node, blob, memo_tuple=tuple):
    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    kind = node["t"]
    if kind == "b":
        return bytes(blob[node["o"]:node["o"] + node["n"]])
    if kind == "a":
        dtype = _dtype_of(node["d"])
        if dtype.hasobject:  # defense in depth: never trust the header
            raise UnsupportedType("object dtype in safe frame")
        raw = blob[node["o"]:node["o"] + node["n"]]
        return numpy.frombuffer(raw, dtype=dtype).reshape(
            node["s"]).copy()
    if kind == "s":  # numpy scalar, exact type restored
        dtype = _dtype_of(node["d"])
        if dtype.hasobject:
            raise UnsupportedType("object dtype in safe frame")
        raw = blob[node["o"]:node["o"] + node["n"]]
        return numpy.frombuffer(raw, dtype=dtype)[0]
    if kind == "l":
        return [_decode(v, blob) for v in node["v"]]
    if kind == "u":
        return memo_tuple(_decode(v, blob) for v in node["v"])
    if kind == "d":
        return {_hashable(_decode(k, blob)): _decode(v, blob)
                for k, v in node["v"]}
    raise UnsupportedType("unknown safe-codec node %r" % kind)


def _dtype_of(tag):
    if not isinstance(tag, str):
        raise UnsupportedType("bad dtype tag %r" % (tag,))
    try:
        return numpy.dtype(tag)
    except TypeError:
        pass
    # ml_dtypes names (bfloat16, float8_*) resolve via the package
    import ml_dtypes
    scalar = getattr(ml_dtypes, tag, None)
    if scalar is None:
        raise UnsupportedType("unknown dtype %r in safe frame" % tag)
    return numpy.dtype(scalar)


def _hashable(key):
    # decoded lists (from tuple-typed keys they are already tuples) —
    # JSON round-trips only these key kinds anyway
    if isinstance(key, numpy.ndarray):
        raise UnsupportedType("array dict keys in safe frame")
    return key


def dumps(message):
    blobs = []
    header, _ = _encode(message, blobs, 0)
    head = json.dumps(header, separators=(",", ":")).encode()
    return _LEN.pack(len(head)) + head + b"".join(blobs)


def loads(data):
    if len(data) < _LEN.size:
        raise UnsupportedType("truncated safe frame")
    (head_len,) = _LEN.unpack_from(data)
    head_end = _LEN.size + head_len
    if head_end > len(data):
        raise UnsupportedType("truncated safe frame header")
    try:
        header = json.loads(data[_LEN.size:head_end].decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise UnsupportedType("bad safe frame header: %s" % exc)
    return _decode(header, memoryview(data)[head_end:])
