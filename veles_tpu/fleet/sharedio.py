"""Same-host shared-memory data plane (reference ``txzmq/sharedio.py:44-105``).

When master and slave share a machine, large job/update payloads skip the
TCP socket: the sender writes the pickled payload into a one-shot segment
under ``/dev/shm`` (POSIX shared memory — tmpfs, a memory copy, never
disk) and ships only a tiny descriptor frame; the receiver maps the
segment, verifies its HMAC, and unlinks it. The reference negotiated the
same optimization by machine-id/pid at handshake and moved payloads over
``SharedIO`` (posix_ipc + mmap) instead of the ZMQ socket
(``server.py:721-732``).

Security model: the descriptor arrives inside an authenticated frame, but
a compromised authenticated peer must still not be able to point us at an
arbitrary filesystem path — segments live in one directory, carry a
mandatory name prefix, and the content MAC (keyed by the fleet secret) is
verified before the segment is consumed; the unlink happens only after
every check passes.
"""

import hashlib
import hmac as hmac_lib
import os
import uuid

#: tmpfs on every Linux; the tempdir fallback keeps macOS/tests working
#: (payloads then ride the page cache — still no socket serialization)
_SHM_DIRS = ("/dev/shm", None)
_PREFIX = "veles-shm-"


def shm_dir():
    for d in _SHM_DIRS:
        if d is None:
            import tempfile
            return tempfile.gettempdir()
        if os.path.isdir(d) and os.access(d, os.W_OK):
            return d


def owner_uid():
    """Segments are 0o600: peers running as different users on the same
    host cannot read each other's segments, so the handshake negotiates
    shm only between same-uid peers."""
    return os.getuid() if hasattr(os, "getuid") else -1


def _mac(key, payload):
    return hmac_lib.new(key, payload, hashlib.sha256).hexdigest()


def put(payload, key):
    """Write one payload into a fresh private segment; returns the
    descriptor dict to ship over the wire."""
    name = _PREFIX + uuid.uuid4().hex
    path = os.path.join(shm_dir(), name)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    try:
        os.write(fd, payload)
    finally:
        os.close(fd)
    return {"name": name, "size": len(payload), "mac": _mac(key, payload)}


def get(desc, key):
    """Read, verify and unlink a segment by descriptor. Raises
    ``ValueError`` on any containment or authenticity failure (the
    segment is left in place unless it verified)."""
    name = desc.get("name", "")
    if os.path.basename(name) != name or not name.startswith(_PREFIX):
        raise ValueError("shm descriptor name %r escapes the segment "
                         "namespace" % name)
    path = os.path.join(shm_dir(), name)
    with open(path, "rb") as fin:
        payload = fin.read()
    if len(payload) != desc.get("size") \
            or not hmac_lib.compare_digest(_mac(key, payload),
                                           str(desc.get("mac"))):
        raise ValueError("shm segment %s failed verification" % name)
    os.unlink(path)
    return payload


def cleanup_stale(max_age=3600.0):
    """Best-effort GC of segments orphaned by a crashed receiver."""
    import time
    base = shm_dir()
    removed = 0
    try:
        names = os.listdir(base)
    except OSError:
        return 0
    for name in names:
        if not name.startswith(_PREFIX):
            continue
        path = os.path.join(base, name)
        try:
            if time.time() - os.stat(path).st_mtime > max_age:
                os.unlink(path)
                removed += 1
        except OSError:
            continue
    return removed
