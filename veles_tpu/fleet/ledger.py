"""Job ledger: per-job leases, explicit requeue, exactly-once fencing.

The reference fleet recovered a dead slave's in-flight minibatch only as a
side effect of ``drop_slave`` (``server.py:619-655``) and applied whatever
update a slave shipped, unfenced. The ledger makes job-level accounting
explicit:

- every job served gets a monotonically increasing ``job_id`` and a
  *lease* whose deadline derives from the slave's adaptive timeout
  (mean + 3 sigma of its job history, ``SlaveDescription.timeout``);
- the master records every transition — OUTSTANDING -> DONE (update
  applied) or OUTSTANDING -> REQUEUED (lease expired, or the slave
  dropped with the job in flight);
- an incoming update is *fenced* (rejected with a warning, never applied)
  when its ``job_id`` is unknown, already applied (duplicate replay),
  already requeued (a hung slave's late answer — the work was re-served
  to someone else), owned by a different slave, or stamped with a
  previous master *epoch* (master restart; see ``Server.epoch``).

This is the master/slave analogue of DrJAX's point (PAPERS.md) that
data-parallel aggregation needs well-specified semantics: the ledger pins
``apply_data_from_slave`` to exactly-once-per-lease.

Thread safety: the asyncio event-loop thread issues/settles leases while
the status thread (web dashboard, SlaveStats plotter) reads ``snapshot()``
— every public method takes the internal lock.
"""

import collections
import threading
import time

OUTSTANDING = "OUTSTANDING"
DONE = "DONE"
REQUEUED = "REQUEUED"

#: settle() verdicts that mean "reject, do not apply"
FENCE_UNKNOWN = "unknown-job"
FENCE_DUPLICATE = "duplicate"
FENCE_REQUEUED = "requeued"
FENCE_FOREIGN = "foreign-slave"
FENCE_STALE_EPOCH = "stale-epoch"


class JobLease:
    """One served job's accounting record."""

    __slots__ = ("job_id", "sid", "issued_at", "deadline", "state")

    def __init__(self, job_id, sid, deadline, now):
        self.job_id = job_id
        self.sid = sid
        self.issued_at = now
        self.deadline = deadline
        self.state = OUTSTANDING


class JobLedger:
    """The master's job-accounting table.

    Settled (DONE/REQUEUED) leases are garbage-collected beyond
    ``keep_settled`` entries; a ``job_id`` below the GC watermark that is
    no longer in the table is by construction settled, so its update is
    fenced as a duplicate — never misread as unknown-and-applicable.
    """

    def __init__(self, keep_settled=10000):
        self._lock = threading.Lock()
        self._leases = {}
        self._next_id = 0
        self._watermark = 0  # ids <= watermark and absent => settled+GC'd
        self._keep_settled = keep_settled
        self._settled_order = collections.deque()  # GC queue, oldest left
        self.counters = {
            "issued": 0, "done": 0,
            "requeued_dropped": 0, "requeued_expired": 0,
        }
        #: wasted-work accounting (observe/fleetscope.py goodput): the
        #: in-flight seconds of every lease that was REQUEUED — work a
        #: slave (probably) did whose result was discarded and re-run
        #: elsewhere (requeued-after-death / hang-expired)
        self.wasted_seconds = 0.0
        self.fenced = {
            FENCE_UNKNOWN: 0, FENCE_DUPLICATE: 0, FENCE_REQUEUED: 0,
            FENCE_FOREIGN: 0, FENCE_STALE_EPOCH: 0,
        }

    # -- lease lifecycle ------------------------------------------------------
    def issue(self, sid, timeout, now=None):
        """Record a new OUTSTANDING lease; returns its ``job_id``."""
        now = time.time() if now is None else now
        with self._lock:
            self._next_id += 1
            job_id = self._next_id
            self._leases[job_id] = JobLease(job_id, sid, now + timeout, now)
            self.counters["issued"] += 1
            return job_id

    def settle(self, job_id, sid):
        """Judge an incoming update. Returns ``None`` when the update must
        be applied (lease was OUTSTANDING for this slave -> now DONE), or a
        FENCE_* verdict string when it must be rejected."""
        with self._lock:
            if not isinstance(job_id, int):
                self.fenced[FENCE_UNKNOWN] += 1
                return FENCE_UNKNOWN
            lease = self._leases.get(job_id)
            if lease is None:
                verdict = (FENCE_DUPLICATE
                           if 0 < job_id <= self._watermark
                           else FENCE_UNKNOWN)
                self.fenced[verdict] += 1
                return verdict
            if lease.sid != sid:
                self.fenced[FENCE_FOREIGN] += 1
                return FENCE_FOREIGN
            if lease.state == DONE:
                self.fenced[FENCE_DUPLICATE] += 1
                return FENCE_DUPLICATE
            if lease.state == REQUEUED:
                self.fenced[FENCE_REQUEUED] += 1
                return FENCE_REQUEUED
            lease.state = DONE
            self.counters["done"] += 1
            self._retire(job_id)
            return None

    def count_stale_epoch(self):
        with self._lock:
            self.fenced[FENCE_STALE_EPOCH] += 1
        return FENCE_STALE_EPOCH

    def requeue_for_slave(self, sid, now=None):
        """Mark every OUTSTANDING lease of a dropped slave REQUEUED (the
        Loader requeues the actual minibatches via ``drop_slave``; this
        records the transition and arms the fence against a zombie's late
        updates). Returns the requeued job ids."""
        now = time.time() if now is None else now
        with self._lock:
            requeued = []
            # snapshot: _retire's GC pops settled leases from the same
            # dict once the backlog passes keep_settled
            for lease in list(self._leases.values()):
                if lease.sid == sid and lease.state == OUTSTANDING:
                    lease.state = REQUEUED
                    self.counters["requeued_dropped"] += 1
                    self.wasted_seconds += max(0.0,
                                               now - lease.issued_at)
                    self._retire(lease.job_id)
                    requeued.append(lease.job_id)
            return requeued

    def expire_if_outstanding(self, job_id, now=None):
        """Hang check: when the lease is still OUTSTANDING past its
        deadline, mark it REQUEUED and return True (the caller drops the
        slave, which requeues the minibatch)."""
        now = time.time() if now is None else now
        with self._lock:
            lease = self._leases.get(job_id)
            if lease is None or lease.state != OUTSTANDING \
                    or now <= lease.deadline:
                return False
            lease.state = REQUEUED
            self.counters["requeued_expired"] += 1
            self.wasted_seconds += max(0.0, now - lease.issued_at)
            self._retire(job_id)
            return True

    def outstanding(self, sid=None):
        with self._lock:
            return [lease.job_id for lease in self._leases.values()
                    if lease.state == OUTSTANDING
                    and (sid is None or lease.sid == sid)]

    def state_of(self, job_id):
        with self._lock:
            lease = self._leases.get(job_id)
            if lease is not None:
                return lease.state
            # tolerate wire garbage like settle() does
            if isinstance(job_id, int) and 0 < job_id <= self._watermark:
                return DONE
            return None

    # -- observability --------------------------------------------------------
    def snapshot(self):
        """Status dict for ``fleet_status()`` / the web dashboard."""
        with self._lock:
            outstanding = sum(1 for lease in self._leases.values()
                              if lease.state == OUTSTANDING)
            return {
                "issued": self.counters["issued"],
                "done": self.counters["done"],
                "outstanding": outstanding,
                "requeued": (self.counters["requeued_dropped"]
                             + self.counters["requeued_expired"]),
                "requeued_dropped": self.counters["requeued_dropped"],
                "requeued_expired": self.counters["requeued_expired"],
                "wasted_s": round(self.wasted_seconds, 3),
                "fenced": dict(self.fenced),
                "fenced_total": sum(self.fenced.values()),
            }

    # -- internals ------------------------------------------------------------
    def _retire(self, job_id):
        """Queue a settled lease for GC; advance the watermark once the
        settled backlog exceeds ``keep_settled``. Lock held by caller."""
        self._settled_order.append(job_id)
        while len(self._settled_order) > self._keep_settled:
            old = self._settled_order.popleft()
            self._leases.pop(old, None)
            if old > self._watermark:
                self._watermark = old
