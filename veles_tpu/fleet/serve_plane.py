"""Serving control plane: one logical endpoint over N self-healing
replicas.

VELES scales training by putting a fault-tolerant master in front of
expendable slaves (PAPER.md §master/slave; ``fleet/ledger.py``); this
module is the same doctrine pointed at SERVING (ROADMAP item 6,
docs/elastic_serving.md). A :class:`ServePlane` owns the replica
registry behind :class:`~veles_tpu.router.ElasticRouter`: it polls each
replica's ``/healthz`` (the same snapshot the fleet piggyback ships as
slave metric rows — ``veles_serve_goodput_fraction``, pool gauges, SLO
burn), derives a per-replica **goodput** and **pressure** reading, and
runs two control loops on them:

- **the leave-one-out collapse detector** (the
  ``observe/fleetscope.py`` straggler idiom generalized from training
  slaves to serving replicas): a replica whose goodput falls below
  ``retire_ratio`` x the median of the REST of the fleet for
  ``retire_polls`` consecutive polls is named — relative scoring, so a
  fleet-wide brownout (every replica slow) never scapegoats one
  replica. A replica whose ``/healthz`` stops answering scores 0.0 and
  is named by the same math — the kill -9 acceptance's detector
  contract;
- **health-gated lifecycle as governor actuations** (the
  ``observe/governor.py`` ledger discipline): ``replica_drain`` (stop
  routing new work, let leases finish), ``replica_retire`` (drained),
  ``replica_dead`` (consecutive poll/request failures past
  ``fail_threshold``), ``replica_adopt`` (a standby joins under
  sustained fleet pressure), and the suppressed variants — every
  actuation lands in the bounded ``transitions`` ledger AND the flight
  ring under the governor's own kind, with hysteresis (consecutive-poll
  streaks) and a cooldown (at most one lifecycle actuation per
  ``cooldown_s``) so a flapping replica cannot thrash the fleet.

Detector firings ride the metric-history plane exactly like rollout
regressions (``veles_tpu/rollout.py``): the per-replica goodput is
recorded as the ``veles_ctrl_replica_goodput`` control series, the
``router_replica_collapse`` rule is detector-owned (``external=True`` —
the sampler never evaluates it), and a retire/dead actuation triggers
the cooldown-limited incident artifact whose labels NAME the replica.

Threading: the plane's state machine is single-writer — every lifecycle
decision runs on the router's poller thread (``poll``). Router handler
threads only feed :class:`Replica` counters (lease tallies, request
failures) under the replica's own lock; the poller converts threshold
crossings into actuations on its next pass. The router's routing check
(:meth:`Replica.routable`) reads GIL-atomic scalars, so a kill -9 stops
attracting traffic at the first failed REQUEST, before the next poll.

Configuration: ``root.common.serve.router.*`` (see
:meth:`ServePlaneConfig.from_spec`).
"""

import collections
import json
import threading
import time
import urllib.request

from veles_tpu.core.logger import Logger

#: per-replica control series (labels: (("replica", name),))
REPLICA_GOODPUT_SERIES = "veles_ctrl_replica_goodput"
#: fleet-pressure control series (the adopt loop's sensor)
FLEET_PRESSURE_SERIES = "veles_ctrl_fleet_pressure"

#: detector-owned anomaly rule: fired by the plane, never the sampler
COLLAPSE_RULE = "router_replica_collapse"

#: bounded actuation ledger length (the governor's TRANSITION_CAP)
TRANSITION_CAP = 64

#: replica lifecycle states
STATES = ("active", "standby", "draining", "retired", "dead")


class ServePlaneConfig:
    """Validated control-plane knobs.

    - ``poll_interval_s``: health-scrape cadence;
    - ``fail_threshold``: consecutive request/poll failures before a
      replica is DEAD (routing already skips it at the threshold);
    - ``retire_ratio`` / ``retire_polls``: the leave-one-out band — a
      replica's goodput below ``retire_ratio`` x the rest-of-fleet
      median for ``retire_polls`` consecutive polls drains it;
    - ``goodput_floor``: the median floor, so an idle fleet (goodput
      ~0 everywhere) never divides by silence;
    - ``adopt_pressure`` / ``adopt_polls``: mean fleet pressure at or
      above ``adopt_pressure`` for ``adopt_polls`` polls adopts one
      standby;
    - ``cooldown_s``: at most one lifecycle actuation per window;
    - ``min_active``: a retire that would drop the active set below
      this is suppressed (ledger-visibly) unless a standby backfills.
    """

    KEYS = ("poll_interval_s", "fail_threshold", "retire_ratio",
            "retire_polls", "goodput_floor", "adopt_pressure",
            "adopt_polls", "cooldown_s", "min_active")

    def __init__(self, poll_interval_s=1.0, fail_threshold=3,
                 retire_ratio=0.5, retire_polls=3, goodput_floor=0.05,
                 adopt_pressure=0.85, adopt_polls=3, cooldown_s=10.0,
                 min_active=1, flag="root.common.serve.router"):
        self.poll_interval_s = float(poll_interval_s)
        if self.poll_interval_s <= 0:
            raise ValueError("%s: poll_interval_s must be > 0" % flag)
        self.fail_threshold = int(fail_threshold)
        if self.fail_threshold < 1:
            raise ValueError("%s: fail_threshold must be >= 1" % flag)
        self.retire_ratio = float(retire_ratio)
        if not 0 < self.retire_ratio < 1:
            raise ValueError(
                "%s: retire_ratio must be in (0, 1) — it compares a "
                "replica AGAINST the rest of the fleet" % flag)
        self.retire_polls = int(retire_polls)
        if self.retire_polls < 1:
            raise ValueError("%s: retire_polls must be >= 1" % flag)
        self.goodput_floor = float(goodput_floor)
        if self.goodput_floor <= 0:
            raise ValueError("%s: goodput_floor must be > 0" % flag)
        self.adopt_pressure = float(adopt_pressure)
        if not 0 < self.adopt_pressure <= 1:
            raise ValueError("%s: adopt_pressure must be in (0, 1]"
                             % flag)
        self.adopt_polls = int(adopt_polls)
        if self.adopt_polls < 1:
            raise ValueError("%s: adopt_polls must be >= 1" % flag)
        self.cooldown_s = float(cooldown_s)
        if self.cooldown_s < 0:
            raise ValueError("%s: cooldown_s must be >= 0" % flag)
        self.min_active = int(min_active)
        if self.min_active < 1:
            raise ValueError("%s: min_active must be >= 1" % flag)

    @classmethod
    def from_spec(cls, spec, flag="root.common.serve.router"):
        """Build from a config subtree dict or ``key=value,...``
        string (the governor's spelling); None/"" -> defaults. Unknown
        keys raise naming ``flag`` — plus the router-front keys
        (host/port/path/replicas/...) the ROUTER consumes, which are
        skipped here."""
        if spec is None or spec == "":
            return cls(flag=flag)
        if hasattr(spec, "__content__"):
            spec = spec.__content__()
        if isinstance(spec, str):
            parsed = {}
            for part in spec.split(","):
                part = part.strip()
                if not part:
                    continue
                key, sep, value = part.partition("=")
                if not sep:
                    raise ValueError("%s: %r is not key=value"
                                     % (flag, part))
                parsed[key.strip()] = value.strip()
            spec = parsed
        if not isinstance(spec, dict):
            raise ValueError(
                "%s must be a dict or 'key=value,...' string, got %r"
                % (flag, type(spec).__name__))
        from veles_tpu.router import RouterConfig
        kwargs = {}
        for key, value in spec.items():
            if key in RouterConfig.KEYS:
                continue  # the router front's keys, not the plane's
            if key not in cls.KEYS:
                raise ValueError(
                    "%s: unknown key %r (supported: %s)"
                    % (flag, key,
                       ", ".join(cls.KEYS + RouterConfig.KEYS)))
            kwargs[key] = value
        for key in ("fail_threshold", "retire_polls", "adopt_polls",
                    "min_active"):
            if key in kwargs:
                kwargs[key] = int(kwargs[key])
        for key in ("poll_interval_s", "retire_ratio", "goodput_floor",
                    "adopt_pressure", "cooldown_s"):
            if key in kwargs:
                kwargs[key] = float(kwargs[key])
        return cls(flag=flag, **kwargs)


class Replica:
    """One replica endpoint's shared record. Router handler threads
    bump lease/failure tallies; the plane's poller thread owns the
    lifecycle state — every cross-thread mutation sits under
    ``_lock`` (the ``shared.rmw`` doctrine, analyze/registry.py)."""

    def __init__(self, url, name=None, state="active"):
        url = str(url).rstrip("/")
        if "://" not in url:
            url = "http://" + url
        self.url = url
        self.name = name or url.split("://", 1)[1]
        if state not in STATES:
            raise ValueError("unknown replica state %r" % state)
        self.state = state
        self._lock = threading.Lock()
        self._leases = 0
        self._failures = 0
        #: last /healthz snapshot (poller thread writes, others read)
        self.stats = None
        #: derived readings (None until the first successful poll)
        self.goodput = None
        self.pressure = None
        #: leave-one-out breach streak (poller thread only)
        self.collapse_streak = 0
        #: resolved-counter baseline for the goodput delta
        self._resolved_seen = None
        self._completed_seen = None

    # -- handler-thread feeds ---------------------------------------------
    def note_dispatch(self):
        with self._lock:
            self._leases += 1

    def note_done(self, ok):
        with self._lock:
            self._leases = max(0, self._leases - 1)
            if ok:
                self._failures = 0
            else:
                self._failures += 1

    def note_poll(self, ok):
        """Poller-thread feed: one health scrape's verdict."""
        with self._lock:
            if ok:
                self._failures = 0
            else:
                self._failures += 1

    @property
    def leases(self):
        with self._lock:
            return self._leases

    @property
    def failures(self):
        with self._lock:
            return self._failures

    def routable(self, fail_threshold):
        """Whether the router may send NEW work here: active AND not
        past the failure threshold (a kill -9 stops attracting traffic
        at the first failed request, before the poller's next pass)."""
        return self.state == "active" and self.failures < fail_threshold

    def snapshot(self):
        return {"name": self.name, "url": self.url, "state": self.state,
                "leases": self.leases, "failures": self.failures,
                "goodput": self.goodput, "pressure": self.pressure}


def _median(values):
    ordered = sorted(values)
    n = len(ordered)
    if not n:
        return None
    if n % 2:
        return ordered[n // 2]
    return 0.5 * (ordered[n // 2 - 1] + ordered[n // 2])


def _http_healthz(url, timeout=2.0):
    """Default health fetch: GET ``/healthz``; raises on any failure."""
    with urllib.request.urlopen(url + "/healthz",
                                timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def ensure_router_rules(history):
    """Register the detector-owned replica anomaly rule (idempotent by
    name; the rollout.py idiom — ``external=True`` so the plane syncs
    state and decides firing, never the sampler)."""
    from veles_tpu.observe.history import AnomalyRule

    have = {rule.name for rule in history.rules}
    if COLLAPSE_RULE not in have:
        rule = AnomalyRule(COLLAPSE_RULE, REPLICA_GOODPUT_SERIES,
                           kind="threshold", op="<=", threshold=0.0,
                           for_samples=1, cooldown_s=5.0,
                           exclude_labels=())
        rule.external = True
        history.add_rule(rule)
    return next(r for r in history.rules if r.name == COLLAPSE_RULE)


class ServePlane(Logger):
    """The replica control plane (see module docstring). Single-writer:
    every method below except the :class:`Replica` feeds runs on ONE
    poller thread (or the test harness driving ``poll`` with an
    explicit clock)."""

    def __init__(self, replicas, standby=(), config=None,
                 clock=time.monotonic, fetch=None):
        super().__init__(logger_name="serve.Plane")
        self.config = config if config is not None else \
            ServePlaneConfig()
        self._clock = clock
        self._fetch = fetch if fetch is not None else _http_healthz
        self.replicas = []
        for rep in replicas:
            self.replicas.append(rep if isinstance(rep, Replica)
                                 else Replica(rep))
        for rep in standby:
            rep = rep if isinstance(rep, Replica) \
                else Replica(rep, state="standby")
            rep.state = "standby"
            self.replicas.append(rep)
        names = [rep.name for rep in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError("duplicate replica names: %s" % names)
        self.counters = {"polls": 0, "replica_drain": 0,
                         "replica_retire": 0, "replica_dead": 0,
                         "replica_adopt": 0,
                         "replica_retire_suppressed": 0}
        #: bounded actuation ledger (the governor's /healthz payload)
        self.transitions = collections.deque(maxlen=TRANSITION_CAP)
        self._last_actuation = None
        self._pressure_streak = 0

    # -- registry views ----------------------------------------------------
    def active(self):
        return [r for r in self.replicas if r.state == "active"]

    def standby(self):
        return [r for r in self.replicas if r.state == "standby"]

    def find(self, name):
        for rep in self.replicas:
            if rep.name == name:
                return rep
        return None

    def add_standby(self, url):
        """Register a fresh standby at runtime (the adopt loop's
        supply side)."""
        rep = Replica(url, state="standby")
        if self.find(rep.name) is not None:
            raise ValueError("replica %s already registered" % rep.name)
        self.replicas.append(rep)
        return rep

    def drop_replica(self, name):
        """Remove a DEPARTED replica from the scoring pool entirely
        (the fleetscope ``drop_slave`` idiom): its goodput must not
        keep skewing the leave-one-out medians."""
        rep = self.find(name)
        if rep is not None:
            self.replicas.remove(rep)
        return rep

    # -- the poll loop (poller thread) -------------------------------------
    def poll(self, now=None):
        """One control pass: scrape every living replica's /healthz,
        derive goodput/pressure, run the leave-one-out detector and
        the lifecycle actuators. Returns the number of replicas that
        answered."""
        if now is None:
            now = self._clock()
        self.counters["polls"] += 1
        answered = 0
        for rep in self.replicas:
            if rep.state in ("retired", "dead"):
                continue
            try:
                snap = self._fetch(rep.url)
            except Exception:
                snap = None
            if snap is not None:
                answered += 1
            self.observe(rep, snap, now)
        self._detect(now)
        self._lifecycle(now)
        return answered

    def observe(self, rep, snap, now):
        """Feed one replica's health verdict (the testable seam —
        harnesses call this directly with synthetic snapshots)."""
        rep.note_poll(snap is not None)
        if snap is None:
            rep.stats = None
            rep.goodput = 0.0
            rep.pressure = None
        else:
            rep.stats = snap
            rep.goodput = self._goodput(rep, snap)
            rep.pressure = self._pressure(snap)
        self._record_control(REPLICA_GOODPUT_SERIES, rep.goodput,
                             (("replica", rep.name),), now)

    def _goodput(self, rep, snap):
        """The replica's goodput reading: the serving goodput
        observatory's fraction when the snapshot carries one (the
        piggybacked ``veles_serve_goodput_fraction``), else the
        completed share of resolved requests over the poll delta
        (availability — the same 0..1 scale), else 1.0 (an idle,
        healthy replica is not a collapse candidate)."""
        scope = snap.get("servescope") or {}
        fraction = scope.get("goodput_fraction")
        if fraction is not None:
            return float(fraction)
        counters = snap.get("counters") or {}
        completed = int(counters.get("completed", 0))
        resolved = completed + sum(
            int(counters.get(key, 0))
            for key in ("errors", "shed", "expired"))
        if rep._resolved_seen is None:
            rep._resolved_seen = resolved
            rep._completed_seen = completed
            return 1.0
        d_resolved = resolved - rep._resolved_seen
        d_completed = completed - rep._completed_seen
        rep._resolved_seen = resolved
        rep._completed_seen = completed
        if d_resolved <= 0:
            return 1.0
        return max(0.0, min(1.0, d_completed / float(d_resolved)))

    @staticmethod
    def _pressure(snap):
        """The replica's load pressure in [0, 1]: the worst of its
        queue occupancy (inflight against the governor's effective
        admission bound when one is exposed) and its KV page-pool
        occupancy — the same two planes the single-process governor
        resizes against."""
        parts = []
        inflight = snap.get("inflight")
        governor = snap.get("governor") or {}
        limit = governor.get("effective_limit")
        if inflight is not None and limit:
            parts.append(min(1.0, float(inflight) / float(limit)))
        pool = snap.get("pool") or {}
        total = pool.get("pages_total")
        if total:
            used = max(int(pool.get("pages_used", 0)),
                       int(pool.get("reserved_pages", 0)))
            parts.append(min(1.0, used / float(total)))
        if not parts and inflight is not None:
            # no bound exposed: saturate softly against the inflight
            # count alone so a flooded bound-less replica still reads
            # as pressured
            parts.append(min(1.0, float(inflight) / 8.0))
        return max(parts) if parts else 0.0

    # -- leave-one-out collapse detector -----------------------------------
    def _detect(self, now):
        """The fleetscope straggler idiom on goodput: score each
        active replica against the median of the REST. Needs >= 2
        scored replicas — with one replica there is no 'rest of the
        fleet' to be worse than."""
        cfg = self.config
        scored = [r for r in self.active() if r.goodput is not None]
        if len(scored) < 2:
            for rep in scored:
                rep.collapse_streak = 0
            return
        for rep in scored:
            others = _median([r.goodput for r in scored if r is not rep])
            bar = cfg.retire_ratio * max(others, cfg.goodput_floor)
            if rep.goodput < bar:
                rep.collapse_streak += 1
            else:
                rep.collapse_streak = 0
            if rep.collapse_streak >= cfg.retire_polls:
                detail = ("goodput %.3f < %.2f x rest-median %.3f "
                          "for %d polls"
                          % (rep.goodput, cfg.retire_ratio, others,
                             rep.collapse_streak))
                self._drain(rep, now, detail)

    # -- lifecycle actuators -----------------------------------------------
    def _cooled(self, now):
        return self._last_actuation is None \
            or now - self._last_actuation >= self.config.cooldown_s

    def _drain(self, rep, now, reason):
        """Drain-and-retire: stop routing new work, let leases finish
        (the retire lands when they do). Suppressed — ledger-visibly —
        when the active set would fall below ``min_active`` with no
        standby to backfill."""
        if rep.state != "active" or not self._cooled(now):
            return
        backfill = self.standby()
        if len(self.active()) - 1 < self.config.min_active \
                and not backfill:
            self.counters["replica_retire_suppressed"] += 1
            self._note("replica_retire_suppressed", rep, now,
                       reason="would drop below min_active=%d with no "
                       "standby; %s" % (self.config.min_active, reason))
            rep.collapse_streak = 0
            return
        rep.state = "draining"
        rep.collapse_streak = 0
        self.counters["replica_drain"] += 1
        self._last_actuation = now
        self._note("replica_drain", rep, now, reason=reason)
        self._fire_collapse(rep, now, reason)
        if backfill:
            self._adopt(backfill[0], now,
                        reason="backfill for draining %s" % rep.name)

    def _mark_dead(self, rep, now):
        reason = ("%d consecutive request/poll failures >= %d"
                  % (rep.failures, self.config.fail_threshold))
        rep.state = "dead"
        self.counters["replica_dead"] += 1
        self._last_actuation = now
        self._note("replica_dead", rep, now, reason=reason)
        self._fire_collapse(rep, now, reason)
        backfill = self.standby()
        if backfill and len(self.active()) < self.config.min_active:
            self._adopt(backfill[0], now,
                        reason="backfill for dead %s" % rep.name)

    def _adopt(self, rep, now, reason):
        rep.state = "active"
        rep.collapse_streak = 0
        self.counters["replica_adopt"] += 1
        self._last_actuation = now
        self._note("replica_adopt", rep, now, reason=reason)

    def _lifecycle(self, now):
        """Per-poll lifecycle sweep: promote finished drains to
        retired, convert failure-threshold crossings into DEAD
        actuations, adopt a standby under sustained fleet pressure."""
        cfg = self.config
        for rep in list(self.replicas):
            if rep.state in ("active", "draining") \
                    and rep.failures >= cfg.fail_threshold:
                self._mark_dead(rep, now)
        for rep in self.replicas:
            if rep.state == "draining" and rep.leases == 0:
                rep.state = "retired"
                self.counters["replica_retire"] += 1
                self._note("replica_retire", rep, now,
                           reason="drained (0 leases)")
        active = self.active()
        pressures = [r.pressure for r in active
                     if r.pressure is not None]
        pressure = max(pressures) if pressures else 0.0
        self._record_control(FLEET_PRESSURE_SERIES, pressure, (), now)
        if pressure >= cfg.adopt_pressure:
            self._pressure_streak += 1
        else:
            self._pressure_streak = 0
        if self._pressure_streak >= cfg.adopt_polls:
            backfill = self.standby()
            if backfill and self._cooled(now):
                self._pressure_streak = 0
                self._adopt(backfill[0], now,
                            reason="fleet pressure %.2f >= %.2f for "
                            "%d polls" % (pressure, cfg.adopt_pressure,
                                          cfg.adopt_polls))

    # -- observability plumbing --------------------------------------------
    def _history(self):
        try:
            from veles_tpu.observe.history import get_metric_history
            return get_metric_history()
        except Exception:
            return None

    def _record_control(self, series, value, labels, now):
        history = self._history()
        if history is None or value is None:
            return
        try:
            history.record_control(series, float(value), labels=labels,
                                   now=now)
        except Exception:
            pass

    def _fire_collapse(self, rep, now, reason):
        """Fire the detector-owned rule so the cooldown-limited
        incident artifact names the replica (the rollout.py firing
        idiom). Never raises — a broken autopsy must not mask the
        (already actuated) lifecycle decision."""
        history = self._history()
        if history is None:
            return None
        try:
            from veles_tpu.rollout import _fire_rule
            rule = ensure_router_rules(history)
            labels = (("replica", rep.name),)
            path = _fire_rule(history, rule, rep.goodput or 0.0,
                              labels, now, reason)
            # one replica's collapse is a one-shot event against that
            # replica — clear the breach so a LATER incident's
            # leading-indicator ordering starts fresh
            rule.streak = 0
            rule.breach_since = None
            return path
        except Exception:
            self.exception("collapse incident bookkeeping failed "
                           "(swallowed)")
            return None

    def _note(self, action, rep, now, reason=""):
        """One ledger-visible actuation: bounded transition history +
        the flight ring under the governor kind (the single-process
        governor's discipline, fleet-level)."""
        entry = {"action": action, "replica": rep.name,
                 "state": rep.state, "reason": reason,
                 "t": time.time(), "mono": now}
        self.transitions.append(entry)
        try:
            from veles_tpu.observe.flight import get_flight_recorder
            get_flight_recorder().note(
                "governor", action=action, replica=rep.name,
                state=rep.state, reason=reason)
        except Exception:
            pass
        self.info("plane %s %s%s", action, rep.name,
                  (": " + reason) if reason else "")

    def snapshot(self):
        """The router's /healthz fleet view."""
        return {"replicas": [rep.snapshot() for rep in self.replicas],
                "active": len(self.active()),
                "standby": len(self.standby()),
                "counters": dict(self.counters),
                "transitions": list(self.transitions)[-8:]}
