"""Elastic replicated serving: a fault-tolerant HTTP front over N
``GenerateAPI`` replicas (``veles_tpu route --replicas ...``).

VELES's master/slave doctrine, pointed at serving (ROADMAP item 6,
docs/elastic_serving.md): one logical ``POST /generate`` endpoint whose
death-of-a-replica is a retry, not an outage. The router

- **admits once** at the fleet level — its own
  :class:`~veles_tpu.serving.ServingHealth` runs the same
  ``try_admit`` gate every replica runs per-process, so a burst is
  shed at the front with a priced ``Retry-After`` instead of being
  sprayed across N already-full replicas;
- **routes by affinity, spills by pressure** — the request's
  page-aligned prefix key is consistent-hashed onto the replica ring
  (:class:`HashRing`), so shared-prefix requests land on the replica
  whose prefix cache already holds their pages (the hit rate survives
  the spread); a primary owner above ``spill_pressure`` (live pool +
  queue occupancy from the control plane's /healthz polls) spills to
  the next owner on the ring, and requests with no reusable prefix go
  to the least-pressured replica outright;
- **holds a lease per request with an exactly-once fence**
  (:class:`Lease`) — a replica that dies mid-stream (connection drop,
  kill -9, breaker trip) fails its attempt and the request is
  transparently re-dispatched to the next healthy replica with
  ``Retry-After``-priced backoff; a slow-then-recovered replica's late
  response is DISCARDED by the fence (first terminal offer wins),
  never double-delivered. A replica that is merely slow past
  ``hedge_after_s`` gets hedged: the next replica races it, the fence
  keeps delivery exactly-once either way;
- **runs the replica lifecycle** on a poller thread —
  :class:`~veles_tpu.fleet.serve_plane.ServePlane` scrapes each
  replica's ``/healthz`` (goodput fraction, pool gauges, SLO burn —
  the same rows the fleet piggyback ships), names collapsed replicas
  with the leave-one-out detector, and drains/retires/adopts as
  ledger-visible governor actuations.

Failure honesty: when every replica is down the front answers 503 with
a ``Retry-After`` priced from the replicas' own most recent prices (or
the control plane's detection horizon) — never a dead-air hang, never
a bare 500. Non-retryable replica verdicts (400/413) pass through
untouched: a bad request does not deserve a failover tour.

Configuration: ``root.common.serve.router.*`` — the router-front keys
(:attr:`RouterConfig.KEYS`) and the control-plane keys
(:attr:`~veles_tpu.fleet.serve_plane.ServePlaneConfig.KEYS`) share the
one subtree, each side skipping the other's keys.

Observability (docs/observability.md): ``veles_router_requests_total``
{outcome}, ``veles_router_retries_total``,
``veles_router_failovers_total``,
``veles_router_affinity_{hits,misses}_total``,
``veles_router_late_discards_total``, the
``veles_router_failover_seconds`` histogram, and per-replica
``veles_router_replica_{goodput,pressure,leases}`` gauge families
published at scrape time via the weak-bridge collector.
"""

import argparse
import bisect
import hashlib
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler

from veles_tpu.core.httpd import (BodyTooLarge, QuietHandlerMixin,
                                  enable_metrics, read_body, reply,
                                  retry_after_headers, serve_health,
                                  serve_metrics, start_server)
from veles_tpu.core.logger import Logger
from veles_tpu.fleet.serve_plane import (ServePlane, ServePlaneConfig)

#: bounded windows: failover-latency samples / replica Retry-After
#: prices the all-down 503 consults
FAILOVER_WINDOW = 256
PRICE_WINDOW = 32

#: failover-latency histogram buckets (seconds)
FAILOVER_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class RouterConfig:
    """The router-front knobs (the control-plane knobs live in
    :class:`~veles_tpu.fleet.serve_plane.ServePlaneConfig`; both read
    the one ``root.common.serve.router`` subtree).

    - ``max_inflight``: the fleet-level admission bound (None/0 =
      unbounded);
    - ``attempt_timeout_s``: per-attempt socket budget;
    - ``hedge_after_s``: how long a single attempt may stay silent
      before the next replica races it (the fence keeps delivery
      exactly-once);
    - ``max_attempts``: distinct replicas tried per request;
    - ``backoff_s``: base backoff between attempts when the failed
      replica supplied no ``Retry-After`` price;
    - ``page_size``: the prefix key's alignment quantum — MUST match
      the replicas' KV page size or affinity decays to random;
    - ``vnodes``: ring points per replica (affinity smoothness);
    - ``spill_pressure``: primary-owner pressure at which affinity
      yields to load.
    """

    KEYS = ("host", "port", "replicas", "standby", "max_inflight",
            "attempt_timeout_s", "hedge_after_s", "max_attempts",
            "backoff_s", "page_size", "vnodes", "spill_pressure")

    def __init__(self, host="127.0.0.1", port=0, replicas="",
                 standby="", max_inflight=64, attempt_timeout_s=30.0,
                 hedge_after_s=2.0, max_attempts=3, backoff_s=0.05,
                 page_size=16, vnodes=64, spill_pressure=0.9,
                 flag="root.common.serve.router"):
        self.host = str(host)
        self.port = int(port)
        self.replicas = replicas
        self.standby = standby
        self.max_inflight = None if max_inflight in (None, "", 0) \
            else int(max_inflight)
        if self.max_inflight is not None and self.max_inflight < 1:
            raise ValueError("%s: max_inflight must be >= 1 (or 0 for "
                             "unbounded)" % flag)
        self.attempt_timeout_s = float(attempt_timeout_s)
        if self.attempt_timeout_s <= 0:
            raise ValueError("%s: attempt_timeout_s must be > 0" % flag)
        self.hedge_after_s = float(hedge_after_s)
        if self.hedge_after_s <= 0:
            raise ValueError("%s: hedge_after_s must be > 0" % flag)
        self.max_attempts = int(max_attempts)
        if self.max_attempts < 1:
            raise ValueError("%s: max_attempts must be >= 1" % flag)
        self.backoff_s = float(backoff_s)
        if self.backoff_s < 0:
            raise ValueError("%s: backoff_s must be >= 0" % flag)
        self.page_size = int(page_size)
        if self.page_size < 1:
            raise ValueError("%s: page_size must be >= 1" % flag)
        self.vnodes = int(vnodes)
        if self.vnodes < 1:
            raise ValueError("%s: vnodes must be >= 1" % flag)
        self.spill_pressure = float(spill_pressure)
        if not 0 < self.spill_pressure <= 1:
            raise ValueError("%s: spill_pressure must be in (0, 1]"
                             % flag)

    @classmethod
    def from_spec(cls, spec, flag="root.common.serve.router"):
        """Build from a config subtree dict or ``key=value,...``
        string; control-plane keys are skipped (the plane consumes
        them). None/"" -> defaults."""
        if spec is None or spec == "":
            return cls(flag=flag)
        if hasattr(spec, "__content__"):
            spec = spec.__content__()
        if isinstance(spec, str):
            parsed = {}
            for part in spec.split(","):
                part = part.strip()
                if not part:
                    continue
                key, sep, value = part.partition("=")
                if not sep:
                    raise ValueError("%s: %r is not key=value"
                                     % (flag, part))
                parsed[key.strip()] = value.strip()
            spec = parsed
        if not isinstance(spec, dict):
            raise ValueError(
                "%s must be a dict or 'key=value,...' string, got %r"
                % (flag, type(spec).__name__))
        kwargs = {}
        for key, value in spec.items():
            if key in ServePlaneConfig.KEYS:
                continue  # the control plane's keys, not the front's
            if key not in cls.KEYS:
                raise ValueError(
                    "%s: unknown key %r (supported: %s)"
                    % (flag, key,
                       ", ".join(cls.KEYS + ServePlaneConfig.KEYS)))
            kwargs[key] = value
        for key in ("port", "max_inflight", "max_attempts",
                    "page_size", "vnodes"):
            if key in kwargs:
                kwargs[key] = int(kwargs[key])
        for key in ("attempt_timeout_s", "hedge_after_s", "backoff_s",
                    "spill_pressure"):
            if key in kwargs:
                kwargs[key] = float(kwargs[key])
        return cls(flag=flag, **kwargs)

    @classmethod
    def from_config(cls, flag="root.common.serve.router"):
        """Build from the live ``root.common.serve.router`` subtree."""
        from veles_tpu.core.config import root
        cfg = root.common.serve.router
        kwargs = {}
        for key in cls.KEYS:
            value = cfg.get(key, None)
            if value is not None:
                kwargs[key] = value
        return cls(flag=flag, **kwargs)


class HashRing:
    """Consistent-hash ring over replica NAMES: each replica owns
    ``vnodes`` pseudo-random points; a key's owners are the distinct
    replicas met walking clockwise from the key's point. Adding or
    removing one replica remaps only the keys whose nearest points
    belonged to it — every other prefix keeps its owner, which is the
    whole reason affinity survives replica churn."""

    def __init__(self, names, vnodes=64):
        points = []
        for name in sorted(names):
            for i in range(vnodes):
                digest = hashlib.sha1(
                    ("%s#%d" % (name, i)).encode()).digest()
                points.append((int.from_bytes(digest[:8], "big"), name))
        points.sort()
        self._points = points
        self._keys = [point for point, _ in points]

    def owners(self, key):
        """Replica names in ring order from ``key``'s successor point,
        deduplicated — ``owners(k)[0]`` is the affinity primary, the
        rest are the spill order."""
        if not self._points:
            return []
        digest = hashlib.sha1(key).digest()
        start = bisect.bisect_right(
            self._keys, int.from_bytes(digest[:8], "big"))
        seen, order = set(), []
        n = len(self._points)
        for i in range(n):
            name = self._points[(start + i) % n][1]
            if name not in seen:
                seen.add(name)
                order.append(name)
        return order


def prefix_key(tokens, page_size):
    """The affinity key: the request's prompt truncated to the KV page
    boundary (only WHOLE pages are reusable across requests —
    ``kv_pool.PrefixCache`` keys the same way), hashed. None when the
    prompt has no complete page: nothing is reusable, so the request
    should chase load, not affinity."""
    aligned = (len(tokens) // page_size) * page_size
    if aligned <= 0:
        return None
    return hashlib.sha1(
        ",".join(str(int(t)) for t in tokens[:aligned]).encode()
    ).digest()


class Lease:
    """One routed request's delivery fence: attempts (original,
    failover, hedge) race to resolve it, and the FIRST terminal offer
    wins — every later one is counted and dropped, so a
    slow-then-recovered replica can never double-deliver. All state
    transitions sit under ``_lock`` (attempt threads + the dispatch
    loop share this object; ``shared.rmw`` doctrine)."""

    def __init__(self, key):
        self.key = key
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._resolved = False
        self._outstanding = 0
        #: (status, payload_bytes, replica_name) — the winning offer
        self.outcome = None
        self.winner = None
        #: late terminal offers discarded by the fence
        self.late = 0
        #: (replica, kind, retry_after_s|None) per failed attempt
        self.failures = []
        #: monotonic instant of the first attempt failure (failover
        #: latency = winner's arrival minus this)
        self.first_failure_at = None

    def launch(self):
        with self._lock:
            self._outstanding += 1

    def offer(self, replica, status, payload):
        """A terminal verdict (2xx success or a non-retryable
        pass-through). Returns True when this offer won the fence."""
        with self._lock:
            self._outstanding -= 1
            if self._resolved:
                self.late += 1
                self._cond.notify_all()
                return False
            self._resolved = True
            self.winner = replica
            self.outcome = (status, payload, replica)
            self._cond.notify_all()
            return True

    def fail(self, replica, kind, retry_after=None, now=None):
        """A retryable attempt failure (connection drop, timeout,
        replica 429/503/5xx)."""
        with self._lock:
            self._outstanding -= 1
            if not self._resolved:
                self.failures.append((replica, kind, retry_after))
                if self.first_failure_at is None:
                    self.first_failure_at = now if now is not None \
                        else time.monotonic()
            self._cond.notify_all()

    def wait(self, timeout):
        """Block until resolved, or until no attempt is outstanding,
        or ``timeout``. Returns (resolved, outstanding)."""
        with self._lock:
            self._cond.wait_for(
                lambda: self._resolved or self._outstanding == 0,
                timeout=timeout)
            return self._resolved, self._outstanding

    @property
    def resolved(self):
        with self._lock:
            return self._resolved

    def failure_count(self):
        with self._lock:
            return len(self.failures)

    def last_price(self):
        """The most recent failure's replica-supplied Retry-After
        price (None when the failure carried none)."""
        with self._lock:
            for _, _, price in reversed(self.failures):
                if price is not None:
                    return price
            return None


def _parse_retry_after(headers):
    try:
        value = headers.get("Retry-After")
        return float(value) if value is not None else None
    except (TypeError, ValueError):
        return None


def _http_post(url, body, headers, timeout):
    """Default attempt transport: POST ``body`` to ``url``; returns
    (status, headers_dict, payload_bytes). HTTP error statuses return
    normally (they are replica VERDICTS); only transport failures
    (connection refused/reset, timeout, half-stream EOF) raise."""
    request = urllib.request.Request(url, data=body, headers=headers,
                                     method="POST")
    try:
        with urllib.request.urlopen(request, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as err:
        with err:
            return err.code, dict(err.headers or {}), err.read()


class RouterHealth:
    """The fleet-level admission gate: delegates every counter to a
    real :class:`~veles_tpu.serving.ServingHealth` (the SAME
    ``try_admit`` semantics each replica runs per-process) and extends
    the snapshot/readiness with the control plane's fleet view —
    ``/readyz`` is True only while at least one replica is routable."""

    def __init__(self, plane):
        import weakref

        from veles_tpu.serving import ServingHealth
        self._health = ServingHealth(name="router")
        self._health.set_ready(True)
        self._plane_ref = weakref.ref(plane)

    def __getattr__(self, name):
        return getattr(self._health, name)

    @property
    def ready(self):
        plane = self._plane_ref()
        if plane is None or not self._health.ready:
            return False
        threshold = plane.config.fail_threshold
        return any(rep.routable(threshold) for rep in plane.replicas)

    def snapshot(self):
        snap = self._health.snapshot()
        plane = self._plane_ref()
        if plane is not None:
            snap["plane"] = plane.snapshot()
        return snap


class ElasticRouter(Logger):
    """The router front (see module docstring). Handler threads call
    :meth:`handle_generate`; one poller thread runs the control
    plane's lifecycle; attempt threads race inside each request's
    :class:`Lease`. Cross-thread tallies (counters, failover samples,
    replica prices) sit under ``self._lock``."""

    def __init__(self, plane, config=None, transport=None,
                 clock=time.monotonic, sleep=time.sleep):
        super().__init__(logger_name="serve.Router")
        self.config = config if config is not None else RouterConfig()
        self.plane = plane
        self.health = RouterHealth(plane)
        self._transport = transport if transport is not None \
            else _http_post
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._counters = {"requests": 0, "retries": 0, "failovers": 0,
                          "affinity_hits": 0, "affinity_misses": 0,
                          "late_discards": 0, "all_down": 0}
        import collections
        self._failover_s = collections.deque(maxlen=FAILOVER_WINDOW)
        self._prices = collections.deque(maxlen=PRICE_WINDOW)
        self._ring = HashRing((), vnodes=self.config.vnodes)
        self._ring_names = frozenset()
        self._httpd = None
        self.port = None
        self._stop = threading.Event()
        self._poller = None
        from veles_tpu.observe.metrics import (bridge,
                                               get_metrics_registry)
        self._registry = get_metrics_registry()
        bridge(self._registry, self, _publish_router)

    # -- counters (handler + attempt threads) -----------------------------
    def _count(self, key, n=1):
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def counter(self, key):
        with self._lock:
            return self._counters.get(key, 0)

    def _note_failover_s(self, seconds):
        with self._lock:
            self._failover_s.append(float(seconds))
        self._registry.observe(
            "veles_router_failover_seconds", float(seconds),
            buckets=FAILOVER_BUCKETS,
            help="failed-attempt instant to winning failover response "
                 "(router.py)")

    def _note_price(self, seconds):
        if seconds is None:
            return
        with self._lock:
            self._prices.append(float(seconds))

    def failover_ms_samples(self):
        with self._lock:
            return [s * 1000.0 for s in self._failover_s]

    # -- ring + pick -------------------------------------------------------
    def _ring_for(self, names):
        """The current active set's ring, rebuilt only on membership
        change (so every unchanged prefix keeps its owner)."""
        names = frozenset(names)
        with self._lock:
            if names != self._ring_names:
                self._ring = HashRing(names,
                                      vnodes=self.config.vnodes)
                self._ring_names = names
            return self._ring

    def _pick(self, key, exclude):
        """One routing decision: (replica, affinity_primary) or
        (None, False) when no routable replica remains outside
        ``exclude``. Affinity first — the key's ring owners in order,
        skipping excluded/unroutable/over-pressure replicas — then
        least-pressure among the routable rest."""
        threshold = self.plane.config.fail_threshold
        active = [rep for rep in self.plane.replicas
                  if rep.state == "active"]
        routable = [rep for rep in active
                    if rep.routable(threshold)
                    and rep.name not in exclude]
        if not routable:
            return None, False
        if key is not None:
            ring = self._ring_for(rep.name for rep in active)
            by_name = {rep.name: rep for rep in routable}
            order = ring.owners(key)
            for rank, name in enumerate(order):
                rep = by_name.get(name)
                if rep is None:
                    continue
                pressure = rep.pressure
                if pressure is not None \
                        and pressure >= self.config.spill_pressure \
                        and len(routable) > 1:
                    continue
                return rep, rank == 0
            # every owner over-pressured: fall through to load
        rep = min(routable,
                  key=lambda r: ((r.pressure if r.pressure is not None
                                  else 0.0), r.leases, r.name))
        return rep, False

    # -- the lease/attempt machinery ---------------------------------------
    def _attempt(self, lease, rep, body, headers, deadline):
        """One replica attempt (runs on its own thread so a slow
        replica can be hedged). Terminal verdicts (2xx, 400/413) offer
        into the fence; busy verdicts (429/503) and transport failures
        fail the lease as retryable."""
        now = self._clock()
        timeout = min(self.config.attempt_timeout_s,
                      max(0.05, deadline - now))
        rep.note_dispatch()
        try:
            status, resp_headers, payload = self._transport(
                rep.url + "/generate", body, headers, timeout)
        except Exception as err:
            rep.note_done(False)
            self._count("failovers")
            lease.fail(rep.name, "transport:%s" % type(err).__name__,
                       now=self._clock())
            return
        if status in (429, 503):
            rep.note_done(True)  # the replica ANSWERED; it is busy,
            # not broken — its failure counter must not trip
            price = _parse_retry_after(resp_headers)
            self._note_price(price)
            self._count("retries")
            lease.fail(rep.name, "busy:%d" % status, retry_after=price,
                       now=self._clock())
            return
        if status >= 500:
            rep.note_done(False)
            self._count("failovers")
            lease.fail(rep.name, "status:%d" % status,
                       now=self._clock())
            return
        rep.note_done(True)
        won = lease.offer(rep.name, status, payload)
        if not won:
            self._count("late_discards")
        elif lease.first_failure_at is not None:
            self._note_failover_s(self._clock()
                                  - lease.first_failure_at)

    def dispatch(self, tokens, body, headers, deadline):
        """Route one admitted request: affinity pick, lease, failover
        and hedging until a terminal verdict or the replica set /
        deadline is exhausted. Returns the :class:`Lease`."""
        cfg = self.config
        key = prefix_key(tokens, cfg.page_size)
        lease = Lease(key)
        tried = set()
        attempts = 0
        while not lease.resolved:
            now = self._clock()
            if now >= deadline:
                break
            rep, primary = (None, False)
            if attempts < cfg.max_attempts:
                rep, primary = self._pick(key, tried)
            if rep is None:
                # nothing new to try: ride out any outstanding attempt
                resolved, outstanding = lease.wait(
                    min(1.0, max(0.05, deadline - now)))
                if resolved or outstanding == 0:
                    break
                continue
            if attempts > 0:
                # Retry-After-priced backoff: the failed replica's own
                # price when it gave one, else the base backoff —
                # never past the deadline
                pause = lease.last_price()
                if pause is None:
                    pause = cfg.backoff_s * attempts
                pause = min(pause, max(0.0, deadline - self._clock()))
                if pause > 0:
                    self._sleep(min(pause, 5.0))
            if key is not None:
                self._count("affinity_hits" if primary
                            else "affinity_misses")
            tried.add(rep.name)
            attempts += 1
            lease.launch()
            thread = threading.Thread(
                target=self._attempt,
                args=(lease, rep, body, headers, deadline),
                name="router-attempt-%s" % rep.name, daemon=True)
            thread.start()
            lease.wait(cfg.hedge_after_s)
        return lease

    # -- the HTTP surface --------------------------------------------------
    def handle_generate(self, handler, raw):
        """The routed ``POST /generate``: validate -> admit once ->
        dispatch -> relay the winning verdict (or the honest all-down
        503)."""
        self._count("requests")
        try:
            body = json.loads(raw.decode() or "{}")
        except (UnicodeDecodeError, ValueError):
            body = None
        tokens = body.get("tokens") if isinstance(body, dict) else None
        if not isinstance(tokens, list) or not tokens \
                or not all(isinstance(t, int) and not isinstance(t, bool)
                           for t in tokens):
            reply(handler, {"error": "body must be JSON with a "
                                     "non-empty integer 'tokens' "
                                     "list"}, code=400)
            self._registry.incr(
                "veles_router_requests_total",
                labels={"outcome": "bad_request"},
                help="routed requests by outcome (router.py)")
            return
        verdict = self.health.try_admit(self.config.max_inflight)
        if verdict is not None:
            kind = verdict[0] if isinstance(verdict, tuple) else verdict
            code = 503 if kind == "unready" else 429
            reply(handler, {"error": "router %s" % kind}, code=code,
                  headers=retry_after_headers(self.health))
            self._registry.incr("veles_router_requests_total",
                                labels={"outcome": "rejected"})
            return
        trace = handler.headers.get("X-Veles-Trace") \
            if handler.headers else None
        fwd_headers = {"Content-Type": "application/json"}
        for name in ("X-Veles-Trace", "X-Veles-Tenant"):
            value = handler.headers.get(name) if handler.headers \
                else None
            if value:
                fwd_headers[name] = value
        deadline_s = 30.0
        if isinstance(body, dict):
            try:
                deadline_s = float(body.get("deadline_s", deadline_s))
            except (TypeError, ValueError):
                pass
        deadline = self._clock() + max(0.05, min(deadline_s, 86400.0))
        lease = self.dispatch(tokens, raw, fwd_headers, deadline)
        echo = {"X-Veles-Trace": trace} if trace else {}
        if lease.outcome is not None:
            status, payload, replica = lease.outcome
            self.health.release("completed" if status < 400
                                else "errors")
            self._registry.incr(
                "veles_router_requests_total",
                labels={"outcome": "completed" if status < 400
                        else "passthrough_%d" % status})
            reply(handler, payload, code=status,
                  headers=dict(echo, **{"X-Veles-Replica": replica}))
            return
        # no terminal verdict: every routable replica is down or busy
        self._count("all_down")
        self.health.release("shed")
        self._registry.incr("veles_router_requests_total",
                            labels={"outcome": "unavailable"})
        reply(handler,
              {"error": "no replica available",
               "failures": [{"replica": name, "kind": kind}
                            for name, kind, _ in lease.failures]},
              code=503,
              headers=dict(echo, **self._down_retry_headers()))

    def _down_retry_headers(self):
        """The all-down 503's honest price: the replicas' own most
        recent Retry-After quotes when any exist, else the control
        plane's detection horizon (a dead replica is noticed within
        ``fail_threshold`` polls)."""
        with self._lock:
            prices = list(self._prices)
        if prices:
            seconds = max(prices)
        else:
            plane_cfg = self.plane.config
            seconds = plane_cfg.poll_interval_s \
                * plane_cfg.fail_threshold
        return {"Retry-After": "%d" % int(min(60, max(1,
                                                      round(seconds))))}

    def snapshot(self):
        with self._lock:
            counters = dict(self._counters)
            failover_ms = [s * 1000.0 for s in self._failover_s]
        return {"counters": counters, "failover_ms": failover_ms,
                "config": {key: getattr(self.config, key)
                           for key in ("max_inflight", "hedge_after_s",
                                       "max_attempts", "page_size",
                                       "spill_pressure")},
                "plane": self.plane.snapshot()}

    # -- lifecycle ---------------------------------------------------------
    def _poll_loop(self):
        while not self._stop.wait(self.plane.config.poll_interval_s):
            try:
                self.plane.poll()
            except Exception:
                self.exception("control-plane poll failed (swallowed)")

    def start(self):
        """Bind the HTTP front and start the control-plane poller.
        Returns self; ``router.port`` is the resolved port."""
        enable_metrics()
        router = self

        class Handler(QuietHandlerMixin, BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?")[0]
                if serve_metrics(self):
                    return
                if path == "/debug/router":
                    reply(self, router.snapshot())
                    return
                if serve_health(self, router.health):
                    return
                reply(self, {"error": "unknown path %s" % path},
                      code=404)

            def do_POST(self):
                if self.path.split("?")[0] != "/generate":
                    reply(self, {"error": "unknown path"}, code=404)
                    return
                try:
                    raw = read_body(self)
                except BodyTooLarge:
                    return
                router.handle_generate(self, raw)

        self._httpd, self.port = start_server(
            Handler, self.config.host, self.config.port, name="router")
        self._stop.clear()
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="router-poller",
                                        daemon=True)
        self._poller.start()
        self.info("router listening on %s:%d over %d replicas",
                  self.config.host, self.port,
                  len(self.plane.replicas))
        return self

    def stop(self):
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=5.0)
            self._poller = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def _publish_router(registry, router):
    """Scrape-time bridge: the router's cumulative tallies and the
    fleet's per-replica gauges."""
    with router._lock:
        counters = dict(router._counters)
    for key, metric in (("retries", "veles_router_retries_total"),
                        ("failovers", "veles_router_failovers_total"),
                        ("affinity_hits",
                         "veles_router_affinity_hits_total"),
                        ("affinity_misses",
                         "veles_router_affinity_misses_total"),
                        ("late_discards",
                         "veles_router_late_discards_total")):
        registry.counter_set(metric, counters.get(key, 0),
                             help="router %s (router.py)"
                                  % key.replace("_", " "))
    goodput, pressure, leases = [], [], []
    for rep in router.plane.replicas:
        labels = {"replica": rep.name, "state": rep.state}
        if rep.goodput is not None:
            goodput.append((labels, rep.goodput))
        if rep.pressure is not None:
            pressure.append((labels, rep.pressure))
        leases.append((labels, rep.leases))
    registry.set_gauge_family(
        "veles_router_replica_goodput", goodput,
        help="per-replica goodput the control plane scored "
             "(fleet/serve_plane.py)")
    registry.set_gauge_family(
        "veles_router_replica_pressure", pressure,
        help="per-replica queue/pool pressure (fleet/serve_plane.py)")
    registry.set_gauge_family(
        "veles_router_replica_leases", leases,
        help="in-flight router leases per replica (router.py)")


def build_router(replicas, standby=(), spec=None):
    """Construct (plane, router) from replica URL lists + an optional
    shared spec (dict or ``key=value,...``) covering both key sets."""
    plane_cfg = ServePlaneConfig.from_spec(spec)
    router_cfg = RouterConfig.from_spec(spec)
    plane = ServePlane(replicas, standby=standby, config=plane_cfg)
    return plane, ElasticRouter(plane, config=router_cfg)


def main(argv=None):
    """``veles_tpu route --replicas URL,URL [...]`` — run the elastic
    front in the foreground."""
    parser = argparse.ArgumentParser(
        prog="veles_tpu route",
        description="fault-tolerant router over N GenerateAPI "
                    "replicas (docs/elastic_serving.md)")
    parser.add_argument("--replicas", required=True,
                        help="comma-separated replica base URLs")
    parser.add_argument("--standby", default="",
                        help="comma-separated standby replica URLs "
                             "(adopted under sustained pressure)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8800)
    parser.add_argument(
        "--spec", default=None,
        help="key=value,... overrides for RouterConfig + "
             "ServePlaneConfig (e.g. 'hedge_after_s=1,retire_polls=5')")
    args = parser.parse_args(argv)
    replicas = [u.strip() for u in args.replicas.split(",")
                if u.strip()]
    standby = [u.strip() for u in args.standby.split(",") if u.strip()]
    plane, router = build_router(replicas, standby=standby,
                                 spec=args.spec)
    router.config.host = args.host
    router.config.port = args.port
    router.start()
    print("router listening on http://%s:%d (%d replicas, %d standby)"
          % (args.host, router.port, len(replicas), len(standby)))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        router.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
