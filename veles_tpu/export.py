"""Workflow package export for the native inference runtime.

Reference ``Workflow.package_export`` (``workflow.py:864-971``) serialized
exported units + numpy arrays into a zip/tgz consumed by libVeles
(``contents.json`` + ``.npy`` members, ``libVeles/src/main_file_loader.cc``).
Here the package is an **uncompressed ustar tar** — trivially parseable by
the dependency-free C++ runtime (``native/``) — containing:

- ``contents.json``: workflow name/checksum + the forward-unit chain with
  per-unit type, config and array refs (``@name.npy``);
- one ``.npy`` per parameter array (float32 or — ``precision=16`` —
  float16, C-order; the native loader's dtype conversion matrix widens
  f2/f8/i1..i8 to f32 at load, mirroring the reference's
  ``numpy_array_loader.h:66-116``).

Only ForwardUnits are exported (inference graph), in control-chain order,
exactly like the reference exported its forward chain; ``precision``
mirrors the reference ``package_export(precision=16|32)``
(``workflow.py:864-975``) — half-size embedded packages are half the
point of a native inference runtime.
"""

import io
import json
import os
import tarfile
import time

import numpy

from veles_tpu.memory import Array


def _export_stamp():
    """Deterministic export timestamp: epoch 0 unless the operator
    sets ``SOURCE_DATE_EPOCH`` (the reproducible-builds convention).
    Two exports of identical state must produce byte-identical
    packages — the sha-addressed artifact store (forge uploads, the
    AOT bundle sidecars) dedupes by content, and a wall-clock stamp
    made every repack hash differently. Tar member mtimes are already
    fixed (``TarInfo`` defaults to 0); this pins the one remaining
    wall-clock leak, the ``contents.json`` stamp."""
    try:
        epoch = int(os.environ.get("SOURCE_DATE_EPOCH", "0"))
    except ValueError:
        epoch = 0
    return time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(epoch))


def _npy_bytes(array, dtype=numpy.float32):
    buf = io.BytesIO()
    numpy.save(buf, numpy.ascontiguousarray(array, dtype))
    return buf.getvalue()


def _unit_spec(unit, arrays):
    """Describe one forward unit; register its arrays."""
    from veles_tpu.nn.all2all import All2All
    from veles_tpu.nn.attention import LayerNorm, SelfAttention, TokenFFN
    from veles_tpu.nn.conv import Conv
    from veles_tpu.nn.pooling import AvgPooling, MaxPooling, Pooling

    spec = {"name": unit.name, "type": None, "config": {}, "arrays": {}}

    def ref(label, value):
        key = "%s_%s" % (unit.name, label)
        arrays[key] = numpy.asarray(value.mem if isinstance(value, Array)
                                    else value)
        spec["arrays"][label] = "@%s.npy" % key

    if isinstance(unit, All2All):
        spec["type"] = "all2all"
        spec["config"] = {"activation": unit.ACTIVATION,
                          "out_features": unit.neurons_number}
        ref("weights", unit.weights)
        ref("bias", unit.bias)
    elif isinstance(unit, Conv):
        spec["type"] = "conv"
        spec["config"] = {"activation": unit.ACTIVATION,
                          "n_kernels": unit.n_kernels,
                          "kx": unit.kx, "ky": unit.ky,
                          "stride_y": unit.sliding[0],
                          "stride_x": unit.sliding[1],
                          "padding": unit.padding}
        ref("weights", unit.weights)
        ref("bias", unit.bias)
    elif isinstance(unit, Pooling):
        from veles_tpu.nn.pooling import MaxAbsPooling
        if isinstance(unit, MaxAbsPooling):
            spec["type"] = "maxabs_pooling"
        elif isinstance(unit, AvgPooling):
            spec["type"] = "avg_pooling"
        elif isinstance(unit, MaxPooling):
            spec["type"] = "max_pooling"
        else:
            raise ValueError("cannot export pooling %r (%s)"
                             % (unit.name, type(unit).__name__))
        spec["config"] = {"kx": unit.kx, "ky": unit.ky,
                          "stride_y": unit.sliding[0],
                          "stride_x": unit.sliding[1]}
    elif isinstance(unit, SelfAttention):
        spec["type"] = "self_attention"
        # causal/residual as 0/1: the runtime's mini JSON reader is numeric
        spec["config"] = {"heads": unit.heads,
                          "causal": int(unit.causal),
                          "residual": int(getattr(unit, "residual",
                                                  False))}
        ref("weights", unit.weights)
        ref("bias", unit.bias)
        ref("out_weights", unit.out_weights)
        ref("out_bias", unit.out_bias)
    elif isinstance(unit, TokenFFN):
        spec["type"] = "ffn"
        spec["config"] = {"activation": unit.activation,
                          "residual": int(unit.residual)}
        ref("weights", unit.weights)
        ref("bias", unit.bias)
        ref("out_weights", unit.out_weights)
        ref("out_bias", unit.out_bias)
    elif isinstance(unit, LayerNorm):
        spec["type"] = "layer_norm"
        spec["config"] = {"eps": unit.eps}
        ref("weights", unit.weights)
        ref("bias", unit.bias)
    else:
        raise ValueError("cannot export unit %r (%s)"
                         % (unit.name, type(unit).__name__))
    return spec


def package_export(workflow, path, precision=32):
    """Export ``workflow``'s forward chain to a tar package at ``path``.

    ``precision``: 32 (float32 arrays) or 16 (float16 — ~half the
    package size; the native runtime widens back to f32 at load, so
    inference costs one rounding of the parameters)."""
    from veles_tpu.nn.all2all import All2AllSoftmax

    if precision not in (16, 32):
        raise ValueError("only 16- and 32-bit float export is supported "
                         "(got %r)" % (precision,))
    dtype = numpy.float16 if precision == 16 else numpy.float32
    arrays = {}
    units = []
    for unit in workflow.forwards:
        units.append(_unit_spec(unit, arrays))
    if workflow.forwards and isinstance(workflow.forwards[-1],
                                        All2AllSoftmax):
        units[-1]["config"]["activation"] = "softmax"
    contents = {
        "workflow": workflow.name,
        "checksum": workflow.checksum,
        "exported": _export_stamp(),
        "precision": precision,
        "input_shape": list(workflow.loader.minibatch_data.shape[1:]),
        "units": units,
    }
    payload = json.dumps(contents, indent=1).encode()
    with tarfile.open(path, "w") as tar:  # uncompressed ustar
        info = tarfile.TarInfo("contents.json")
        info.size = len(payload)
        tar.addfile(info, io.BytesIO(payload))
        for key, value in arrays.items():
            blob = _npy_bytes(value, dtype)
            info = tarfile.TarInfo("%s.npy" % key)
            info.size = len(blob)
            tar.addfile(info, io.BytesIO(blob))
    return path
