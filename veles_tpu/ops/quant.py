"""Int8 weight-only quantization with a dequant-fused Pallas matvec.

The serving decode loop is memory-bound: every token reads every weight
matrix out of HBM (``parallel/decode.py``; the bf16 tier already bought
+~50% tokens/sec by halving that traffic). This module halves it AGAIN:
weights live in HBM as int8 with one f32 scale per output channel, and
the Pallas kernel dequantizes inside the matvec — the bf16/f32 weights
never exist in HBM at all.

Measured on TPU v5e (two-length scan timing, m=8 decode rows): the
kernel beats XLA's fused-convert dot 6x on the qkv projection shape
(k1024 x n3072 — XLA handles the non-power-of-two N badly) and ~1.3x
on the 32k vocab head, and ties within noise on the square shapes —
WHEN the in-kernel dequant matches the activation dtype (bf16 serving)
and the lane block suits the shape. Those two knobs are what this
module tunes; the decision persists in the same autotune cache as the
Pallas GEMM blocks (``ops/gemm.py`` — the ``device_infos.json``
descendant, reference ``backends.py:623-731``), and the runtime gate
auto-engages the kernel only where it measured faster (the
flash-attention >=4096 doctrine, VERDICT r4 #5).

Quantization scheme: symmetric per-output-channel absmax
(``q = round(w / scale)`` with ``scale = absmax / 127``), the standard
W8A16 serving recipe — activations stay bf16/f32, so the only numeric
change is the weight rounding (|error| <= scale/2 per element,
``tests/test_quant.py``).

No reference counterpart: VELES ships fp16 export precision at most
(``workflow.py:864-971``); this is an additive serving tier.
"""

import functools

import jax
import jax.numpy as jnp

#: the Pallas path auto-engages below this many rows of x: the decode
#: regime (M = batch) where the matvec is HBM-bound and the x block
#: (M x K) stays a sliver of VMEM. Above it (prefill, training) the
#: MXU-bound XLA dequant path wins and engages instead.
PALLAS_MAX_ROWS = 256

#: lane-block candidates per grid step (N must divide by the choice)
BLOCK_N_CANDIDATES = (2048, 1024, 512)

#: None = auto (tuned decision); True/False pin the kernel on/off for
#: every auto-gated call — the bench's interleaved on/off comparison
#: and emergency opt-out knob
FORCE_PALLAS = None


def quantize_int8(w):
    """Symmetric per-output-channel int8 quantization of ``w`` (K, N):
    returns ``(q int8 (K, N), scale f32 (N,))`` with
    ``w ~= q * scale``. Zero columns get scale 1 (q = 0)."""
    w = jnp.asarray(w)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def _matvec_kernel(x_ref, q_ref, s_ref, o_ref):
    # x (M, K) | q (K, BN) int8 | s (1, BN) f32 -> o (M, BN) f32.
    # The int8 block widens to x's dtype in VMEM only (HBM saw one byte
    # per weight); the MXU accumulates in f32 either way. bf16 x keeps
    # the MXU on its native input width — measured faster than f32 at
    # every shape that matters (see module docstring).
    w = q_ref[:].astype(x_ref.dtype)
    o_ref[:] = jnp.dot(x_ref[:], w,
                       preferred_element_type=jnp.float32) * s_ref[:]


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def _pallas_int8_matmul(x, q, scale, block_n, interpret=False):
    from jax.experimental import pallas as pl

    m, k = x.shape
    n = q.shape[1]
    return pl.pallas_call(
        _matvec_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((m, k), lambda j: (0, 0)),
            pl.BlockSpec((k, block_n), lambda j: (0, j)),
            pl.BlockSpec((1, block_n), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((m, block_n), lambda j: (0, j)),
        interpret=interpret,
    )(x, q, scale.reshape(1, -1))


def _default_block_n(k, n):
    """Lane block when the shape has no tuned cache entry. From the
    v5e sweep: the 32k vocab head wants 2048; mid-width projections
    want 1024; 512 is the floor that still always fits VMEM."""
    for candidate in BLOCK_N_CANDIDATES:
        if n % candidate == 0 and (candidate < 2048 or n >= 16384):
            return candidate
    return 512 if n % 512 == 0 else None


def _tuned_decision(m, k, n):
    """(use_pallas, block_n) for this shape — the persisted autotune
    verdict when one exists, else the measured-defaults heuristic.
    The decode-regime row bound applies EITHER way: tuned entries are
    measured at decode m, and a prefill/training call (m up to B x T)
    would blow the kernel's whole-x VMEM block."""
    if m > PALLAS_MAX_ROWS:
        return False, None
    from veles_tpu.ops import gemm

    entry = gemm._load_cache().get("int8:%dx%d" % (k, n))
    if entry:
        return bool(entry.get("use_pallas")), entry.get("block_n")
    block_n = _default_block_n(k, n)
    ok = block_n is not None and k % 32 == 0
    return ok, block_n


def int8_matmul(x, q, scale, use_pallas=None, interpret=False):
    """``x @ (q * scale)`` with the dequantization fused into the
    product. ``x`` (M, K) float; ``q`` (K, N) int8; ``scale`` (N,) f32.
    Returns (M, N) in ``x``'s dtype.

    ``use_pallas=None`` auto-engages the Pallas kernel on TPU in the
    decode regime per the tuned decision (persisted by
    ``autotune_int8`` / heuristic defaults) — the measured-win gate.
    Everywhere else the XLA formulation runs: dequant-to-x.dtype
    feeding dot_general (prefill/training sizes are MXU-bound, where
    XLA wins)."""
    m, k = x.shape
    n = q.shape[1]
    block_n = None
    if use_pallas is None and FORCE_PALLAS is not None:
        use_pallas = FORCE_PALLAS
    if use_pallas is None:
        if jax.default_backend() in ("tpu", "axon"):
            use_pallas, block_n = _tuned_decision(m, k, n)
        else:
            use_pallas = False
    if use_pallas:
        if block_n is None:
            block_n = _default_block_n(k, n)
        if block_n is not None and k % 32 == 0:
            out = _pallas_int8_matmul(x, q, scale, block_n,
                                      interpret=interpret)
            return out.astype(x.dtype)
    compute = x.dtype if x.dtype != jnp.float64 else jnp.float32
    out = jnp.dot(x, q.astype(compute),
                  preferred_element_type=jnp.float32)
    return (out * scale).astype(x.dtype)


#: None = auto; True/False pin the dequant-fused attend kernel (the
#: bench's on/off comparison)
FORCE_ATTEND_PALLAS = None


def _attend_kernel(q_ref, kq_ref, ks_ref, vq_ref, vs_ref, m_ref,
                   o_ref):
    # per-BATCH cell: q (H,D) f32 | K,V (H,D,T) int8 | scales (H,T) +
    # mask (1,T) f32 -> out (H,D) f32, with a static unrolled loop
    # over heads (one grid cell per batch row keeps the cell count —
    # and its dispatch overhead — tiny). The int8 payloads feed the
    # MXU straight from VMEM — the bf16-widened cache XLA materializes
    # in every jnp formulation (measured 4-8x slower) never exists.
    heads = kq_ref.shape[1]
    d = q_ref.shape[-1]
    t = kq_ref.shape[-1]
    mask = m_ref[...]
    for h in range(heads):
        q = q_ref[0, h].reshape(1, d).astype(jnp.float32)
        k = kq_ref[0, h].astype(jnp.float32)              # (D, T)
        s = jnp.dot(q, k, preferred_element_type=jnp.float32)
        s = s * ks_ref[0, h].reshape(1, t) + mask
        p = jax.nn.softmax(s, axis=-1)
        pv = p * vs_ref[0, h].reshape(1, t)
        v = vq_ref[0, h].astype(jnp.float32)              # (D, T)
        out = jax.lax.dot_general(
            pv, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # (1, D)
        o_ref[0, h] = out[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_cache_attend(q, k_q, k_scale, v_q, v_scale, mask_addend,
                         interpret=False):
    from jax.experimental import pallas as pl

    batch, _, heads, d = q.shape
    t = k_q.shape[-1]
    # q rides as (B,H,D): the (1,H,D) block's trailing dims fill the
    # array axes; K/V blocks (1,H,D,T) and scales (1,H,T) likewise.
    # The mask is (1, T) shared or (B, T) per row (the slot engine's
    # per-slot lengths) — per-row masks index their own block.
    qh = q[:, 0].astype(jnp.float32)
    mask2d = (mask_addend.reshape(1, -1) if mask_addend.ndim == 1
              else mask_addend)
    mask_index = ((lambda b: (b, 0)) if mask2d.shape[0] == batch
                  and batch > 1 else (lambda b: (0, 0)))
    out = pl.pallas_call(
        _attend_kernel,
        out_shape=jax.ShapeDtypeStruct((batch, heads, d), jnp.float32),
        grid=(batch,),
        in_specs=[
            pl.BlockSpec((1, heads, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, heads, d, t), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec((1, heads, t), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, heads, d, t), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec((1, heads, t), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, t), mask_index),
        ],
        out_specs=pl.BlockSpec((1, heads, d), lambda b: (b, 0, 0)),
        interpret=interpret,
    )(qh, k_q, k_scale, v_q, v_scale, mask2d)
    return out[:, None]  # (B,1,H,D)


def int8_cache_attend(q, k_q, k_scale, v_q, v_scale, mask_addend,
                      use_pallas=None, interpret=False):
    """Decode attention of one query token against an int8 KV cache in
    the head-major (B, H, D, T) layout, dequantization fused into the
    dots. ``q`` (B, 1, H, D) float (already 1/sqrt(D)-scaled by the
    caller); per-(position, head) ``k_scale``/``v_scale`` (B, H, T)
    f32; ``mask_addend`` f32 (0 = visible, -1e30 = masked) — shape
    (T,) for one shared mask, or (B, T) for per-row masks (the slot
    engine's per-slot lengths). Returns (B, 1, H, D) f32.

    Default: the XLA formulation — on THIS head-major layout XLA
    keeps the int8 payloads narrow all the way into the dots (the
    positions-major layouts were what forced the materialized bf16
    widening), and it measured FASTER than the kernel on the decode
    composite (0.547 vs 0.678 ms/step at b8/T1152; ~tie at T4096).
    The kernel stays opt-in (``use_pallas=True`` / FORCE), needing T
    on whole 128-lane tiles and D %% 32 — same measured-win doctrine
    as every other kernel here."""
    batch, _, heads, d = q.shape
    t = k_q.shape[-1]
    if use_pallas is None and FORCE_ATTEND_PALLAS is not None:
        use_pallas = FORCE_ATTEND_PALLAS
    if use_pallas is None:
        use_pallas = False
    if use_pallas and t % 128 == 0 and d % 32 == 0:
        return _pallas_cache_attend(q, k_q, k_scale, v_q, v_scale,
                                    mask_addend, interpret=interpret)
    compute = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
    qh = q[:, 0].astype(compute)                        # (B,H,D)
    s = jnp.einsum("bhd,bhdt->bht", qh, k_q.astype(compute),
                   preferred_element_type=jnp.float32)
    addend = (mask_addend if mask_addend.ndim == 1
              else mask_addend[:, None, :])             # (B,1,T)
    s = s * k_scale + addend
    p = jax.nn.softmax(s, axis=-1)
    pv = (p * v_scale).astype(compute)
    out = jnp.einsum("bhdt,bht->bhd", v_q.astype(compute), pv,
                     preferred_element_type=jnp.float32)
    return out[:, None]


def matmul_any(x, w):
    """``x @ w`` where ``w`` is a dense array OR the quantized
    ``{"q8", "scale"}`` dict — the single dispatch point the shared
    transformer sublayer math routes through, so one code path serves
    the fp32, bf16 and int8 tiers (leading dims of ``x`` are
    flattened for the product)."""
    if isinstance(w, dict):
        lead = x.shape[:-1]
        y = int8_matmul(x.reshape(-1, x.shape[-1]), w["q8"], w["scale"])
        return y.reshape(lead + (w["q8"].shape[1],))
    return x @ w


def autotune_int8(m, k, n, dtype=jnp.bfloat16, repeats=4):
    """Measure XLA vs the Pallas kernel over the lane-block candidates
    for one (m, k, n) matvec on the current device, persist the winner
    in the shared tuning cache, and return the decision dict.

    Timing: a length-L ``lax.scan`` of the product at two L values —
    the difference cancels dispatch and transfer constants (the same
    tunnel-proof protocol as ``bench.py``)."""
    import numpy
    from veles_tpu.ops import gemm

    rng = numpy.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, k), dtype)
    q = jnp.asarray(rng.randint(-127, 128, (k, n)), jnp.int8)
    scale = jnp.asarray(rng.rand(n).astype(numpy.float32))

    # ONE copy of the tunnel-proof serialized-scan timing protocol
    # (gemm._matmul_scan_time) serves the GEMM and int8 autotuners
    def measure(fn):
        return gemm._matmul_scan_time(fn, x, lengths=(200, 1400),
                                      repeats=repeats)

    results = {"xla": measure(
        lambda v: int8_matmul(v, q, scale, use_pallas=False))}
    for block_n in BLOCK_N_CANDIDATES:
        if n % block_n:
            continue
        try:
            results["pallas_%d" % block_n] = measure(
                lambda v, b=block_n: _pallas_int8_matmul(
                    v, q, scale, b).astype(v.dtype))
        except Exception:
            continue
    winner = min(results, key=results.get)
    decision = {
        "use_pallas": winner != "xla",
        "block_n": (int(winner.split("_")[1])
                    if winner != "xla" else None),
        "seconds": results[winner],
        "measured": {key: round(val * 1e6, 2)
                     for key, val in results.items()},
    }
    cache = gemm._load_cache()
    cache["int8:%dx%d" % (k, n)] = decision
    gemm._persist_cache(cache)
    return decision
