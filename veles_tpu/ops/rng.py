"""Device random number generation.

Replaces the reference's xorshift1024* device kernels (``ocl/random.cl``,
``cuda/random.cu``) and the ``Uniform`` unit's device-resident state. JAX's
counter-based threefry keys are the TPU-native equivalent — splittable,
reproducible across shardings, and jit-safe — so there is no mutable device
state to manage; units carry a key and split per use (see
``veles_tpu.core.prng.RandomGenerator`` for the host-side keyed registry).
"""

import jax
import jax.numpy as jnp


def uniform(key, shape, dtype=jnp.float32, low=-1.0, high=1.0):
    return jax.random.uniform(key, shape, dtype, minval=low, maxval=high)


def normal(key, shape, dtype=jnp.float32, mean=0.0, stddev=1.0):
    return mean + stddev * jax.random.normal(key, shape, dtype)


def fill_uniform(key, shape, vle, dtype=jnp.float32):
    """Znicz-style symmetric init: U(-vle, vle) (the reference fills weight
    matrices this way with magnitude ``1/sqrt(fan_in)``-ish constants)."""
    return jax.random.uniform(key, shape, dtype, minval=-vle, maxval=vle)
