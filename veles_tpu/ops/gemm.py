"""Matrix multiplication for the MXU, with reference precision levels.

Replaces the reference's hand-tuned OpenCL/CUDA GEMM family
(``ocl/matrix_multiplication_precise.cl``, ``ocl/gemm.cl``) and its
per-device block-size autotuner (``backends.py:623-731`` +
``devices/device_infos.json``). On TPU the design inverts: XLA's
``dot_general`` already emits optimal MXU schedules for standard shapes, so
that is the default path; the Pallas kernel below exists for the fused /
blocked cases XLA can't express (and as the substrate for later fused
epilogues), with a tiny autotune cache mirroring ``device_infos.json``.

Precision levels (reference ``config.py:244-247`` documented plain sum /
Kahan (+9%) / multi-partial (+90%) summation tiers):

- 0 → bfloat16 MXU passes, float32 accumulation (fast path),
- 1 → float32 operands, ``Precision.HIGH`` (≈ the Kahan tier),
- 2 → float32 operands, ``Precision.HIGHEST`` (≈ the multi-partial tier).
"""

import functools
import json
import logging
import math
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.core.config import root
from veles_tpu.observe.xla_stats import instrument

_PRECISIONS = {
    0: lax.Precision.DEFAULT,
    1: lax.Precision.HIGH,
    2: lax.Precision.HIGHEST,
}


def matmul(a, b, precision_level=None, out_dtype=None, use_pallas=None):
    """``a @ b`` tuned for the MXU.

    precision_level mirrors the reference's GEMM summation tiers (see
    module docstring); ``None`` reads
    ``root.common.engine.precision_level``.

    ``use_pallas``: True/False force the path; None reads
    ``root.common.engine.use_pallas``, whose default ``"tuned"`` engages
    the Pallas blocked kernel exactly where a persisted autotune verdict
    says it MEASURED faster than XLA on this device (``autotune_matmul``
    stores ``beats_xla`` per shape bucket — the reference's per-device
    GEMM autotune semantics, ``backends.py:623-731``: tuned result used
    automatically, XLA otherwise)."""
    if precision_level is None:
        precision_level = root.common.engine.get("precision_level", 0)
    if out_dtype is None:
        out_dtype = a.dtype
    if use_pallas is None:
        use_pallas = root.common.engine.get("use_pallas", "tuned")
    (a, b), precision = compute_operands(
        a, b, precision_level=precision_level)
    if use_pallas and _pallas_eligible(a, b):
        if use_pallas != "tuned" or _tuned_beats_xla(a, b):
            return pallas_matmul(a, b, out_dtype=out_dtype)
    return lax.dot_general(
        a, b, (((a.ndim - 1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32,
    ).astype(out_dtype)


def compute_operands(*arrays, precision_level=None):
    """Apply the engine compute-dtype policy to MXU operands: returns
    ``(cast_arrays, lax_precision)``. Level 0 casts to
    ``root.common.engine.compute_dtype`` (bf16 — halves the HBM bytes of
    every materialized operand feeding the MXU); levels 1/2 keep float32
    with HIGH/HIGHEST passes. The dense path (``matmul``/``dense_layer``)
    and the conv paths (``nn/conv.py``, ``parallel/fused.py``) all route
    through this one policy."""
    if precision_level is None:
        precision_level = root.common.engine.get("precision_level", 0)
    if precision_level == 0:
        compute_dtype = jnp.dtype(
            root.common.engine.get("compute_dtype", "bfloat16"))
    else:
        compute_dtype = jnp.float32
    return (tuple(a.astype(compute_dtype) for a in arrays),
            _PRECISIONS[precision_level])


def conv2d(x, w, sliding, padding, precision_level=None):
    """NHWC x HWIO convolution under the engine precision policy, f32
    result. Level 0 casts the operands to ``compute_dtype`` and runs the
    conv in that dtype end-to-end (the transpose rule under ``jax.vjp``
    requires uniform operand dtypes, so a mixed bf16-operand /
    f32-accumulator conv is not reverse-differentiable — the MXU still
    accumulates f32 internally; only the materialized output rounds
    through bf16), then casts the result back to f32 for the bias +
    activation epilogue. Levels 1/2 keep f32 operands with HIGH/HIGHEST
    passes and a f32 accumulator type. Both the graph conv unit
    (``nn/conv.py``) and the fused engine (``parallel/fused.py``) call
    THIS function, so the two modes stay bit-identical."""
    if precision_level is None:
        precision_level = root.common.engine.get("precision_level", 0)
    (xc, wc), precision = compute_operands(
        x, w, precision_level=precision_level)
    kwargs = {}
    if precision_level != 0:
        kwargs["preferred_element_type"] = jnp.float32
    out = lax.conv_general_dilated(
        xc, wc, window_strides=tuple(sliding), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=precision, **kwargs)
    return out.astype(jnp.float32)


def _pallas_eligible(a, b):
    """Pallas pays off for large 2-D matmuls on a real TPU backend; small or
    ragged shapes go to XLA which handles padding better."""
    if a.ndim != 2 or b.ndim != 2:
        return False
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    m, k = a.shape
    _, n = b.shape
    return m >= 512 and n >= 512 and k >= 512


# -- Pallas blocked matmul ---------------------------------------------------

def _mm_kernel(a_ref, b_ref, o_ref, acc_ref):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # f32 operands need HIGHEST or the dot truncates to bf16 passes; bf16
    # operands must keep DEFAULT (Mosaic rejects fp32 contract precision on
    # a bf16 lhs) and already accumulate in f32 on the MXU
    precision = (lax.Precision.HIGHEST if a_ref.dtype == jnp.float32
                 else lax.Precision.DEFAULT)
    acc_ref[...] += jnp.dot(a_ref[...], b_ref[...],
                            preferred_element_type=jnp.float32,
                            precision=precision)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("out_dtype", "bm", "bn", "bk",
                                    "interpret"))
def pallas_matmul(a, b, out_dtype=jnp.float32, bm=None, bn=None, bk=None,
                  interpret=False):
    """Blocked MXU matmul: grid (M/bm, N/bn, K/bk), float32 VMEM accumulator,
    K innermost so each (i, j) output tile is revisited sequentially
    (``dimension_semantics``: parallel, parallel, arbitrary)."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    if bm is None or bn is None or bk is None:
        bm, bn, bk = _tuned_blocks(m, n, k, str(jnp.dtype(a.dtype)))
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    # pad to block multiples; zero padding is sum-neutral
    pm, pn, pk = (-m) % bm, (-n) % bn, (-k) % bk
    if pm or pk:
        a = jnp.pad(a, ((0, pm), (0, pk)))
    if pk or pn:
        b = jnp.pad(b, ((0, pk), (0, pn)))
    mm, nn, kk = m + pm, n + pn, k + pk
    out = pl.pallas_call(
        _mm_kernel,
        grid=(mm // bm, nn // bn, kk // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mm, nn), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b)
    if pm or pn:
        out = out[:m, :n]
    return out


# compile/hit telemetry for the blocked kernel (observe/xla_stats.py);
# delegates after one attribute check while device telemetry is off
pallas_matmul = instrument("gemm.pallas_matmul", pallas_matmul)


# -- fused dense epilogue -----------------------------------------------------

def _mm_epilogue_kernel(activation):
    from veles_tpu.ops import activations as act_lib
    act = act_lib.ACTIVATIONS[activation][0]

    def kernel(a_ref, b_ref, bias_ref, o_ref, acc_ref):
        @pl.when(pl.program_id(2) == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        precision = (lax.Precision.HIGHEST
                     if a_ref.dtype == jnp.float32
                     else lax.Precision.DEFAULT)
        acc_ref[...] += jnp.dot(a_ref[...], b_ref[...],
                                preferred_element_type=jnp.float32,
                                precision=precision)

        @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
        def _flush():
            # THE epilogue: bias add + activation on the f32 VMEM
            # accumulator tile, before it ever leaves for HBM
            o_ref[...] = act(acc_ref[...]
                             + bias_ref[...]).astype(o_ref.dtype)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("activation", "out_dtype", "bm",
                                    "bn", "bk", "interpret"))
def pallas_dense(a, b, bias, activation="linear", out_dtype=jnp.float32,
                 bm=None, bn=None, bk=None, interpret=False):
    """act(a @ b + bias) as ONE blocked kernel (matmul + epilogue)."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    if bm is None or bn is None or bk is None:
        bm, bn, bk = _tuned_blocks(m, n, k, str(jnp.dtype(a.dtype)))
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    pm, pn, pk = (-m) % bm, (-n) % bn, (-k) % bk
    if pm or pk:
        a = jnp.pad(a, ((0, pm), (0, pk)))
    if pk or pn:
        b = jnp.pad(b, ((0, pk), (0, pn)))
    bias2 = bias.reshape(1, -1).astype(jnp.float32)
    if pn:
        bias2 = jnp.pad(bias2, ((0, 0), (0, pn)))
    mm, nn, kk = m + pm, n + pn, k + pk
    out = pl.pallas_call(
        _mm_epilogue_kernel(activation),
        grid=(mm // bm, nn // bn, kk // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
            pl.BlockSpec((1, bn), lambda i, j, s: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mm, nn), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b, bias2)
    if pm or pn:
        out = out[:m, :n]
    return out


pallas_dense = instrument("gemm.pallas_dense", pallas_dense)


@functools.lru_cache(maxsize=None)
def _dense_with_vjp(activation):
    """The Pallas epilogue forward with a hand-written VJP —
    ``pallas_call`` has no automatic reverse rule, and the fused tick
    differentiates straight through the layer. The backward is the
    SAME math the graph-mode GD units run (activation derivative off
    the saved OUTPUT, two transposed matmuls, bias row-sum) — with one
    caveat: ``grad_w`` accumulates in f32 and is then cast to
    ``w.dtype`` (bf16 on the Pallas path), one extra bf16 rounding of
    the weight gradient that graph-mode GD (f32 matmul output) does not
    apply. CPU tests can't observe it (``_pallas_eligible`` is false
    off-TPU); on TPU the fused-vs-graph weight comparison needs the
    looser TPU-tier bound."""
    from veles_tpu.ops import activations as act_lib
    deriv = act_lib.ACTIVATIONS[activation][1]

    @jax.custom_vjp
    def fn(x, w, b):
        return pallas_dense(x, w, b, activation=activation,
                            out_dtype=jnp.float32)

    def fwd(x, w, b):
        y = fn(x, w, b)
        return y, (x, w, y)

    def bwd(res, g):
        x, w, y = res
        err = g * deriv(y)
        grad_x = matmul(err, w.T, out_dtype=x.dtype)
        grad_w = matmul(x.T, err, out_dtype=jnp.float32).astype(w.dtype)
        return grad_x, grad_w, jnp.sum(err, axis=0)

    fn.defvjp(fwd, bwd)
    return fn


def dense_layer(x, w, bias, activation="linear", precision_level=None,
                out_dtype=jnp.float32, use_pallas=None):
    """The product dense-layer forward: ``act(x @ w + b)``.

    Default path: XLA dot + its own epilogue fusion — MEASURED faster
    than the Pallas kernels on the train composite (fwd+bwd+update,
    mb 4096: 0.40 vs 0.73 ms/step; docs/performance.md "Pallas +
    autotune" has the full table). Opt in to the fused Pallas epilogue
    kernel (``root.common.engine.use_pallas`` + ``pallas_epilogue``,
    or ``use_pallas=True`` here) for the shapes where it wins —
    forward-only tall-skinny (m=512, n=k=4096 measured 2.6x faster
    than XLA) — with the autotune cache's block sizes applied (the
    role the reference's per-device GEMM autotune played for every
    All2All, ``backends.py:623-731``)."""
    if use_pallas is None:
        use_pallas = root.common.engine.get("use_pallas", False) \
            and root.common.engine.get("pallas_epilogue", False)
    (xc, wc), precision = compute_operands(
        x, w, precision_level=precision_level)
    if use_pallas and _pallas_eligible(xc, wc):
        return _dense_with_vjp(activation)(xc, wc, bias).astype(
            out_dtype)
    from veles_tpu.ops import activations as act_lib
    act = act_lib.ACTIVATIONS[activation][0]
    # same dtype contract as the Pallas path: bias add + activation on
    # the f32 accumulator, ONE final cast to out_dtype
    out = lax.dot_general(
        xc, wc, (((xc.ndim - 1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32)
    return act(out + bias).astype(out_dtype)


# -- autotune cache (the device_infos.json descendant) ------------------------

_DEFAULT_BLOCKS = (256, 256, 512)
_CANDIDATES = ((128, 128, 512), (256, 256, 512), (512, 512, 512),
               (256, 512, 512), (512, 256, 512), (256, 256, 1024))
_tuning_cache = None


def _cache_path():
    return root.common.engine.get(
        "pallas_autotune_cache",
        os.path.expanduser("~/.veles_tpu/cache/pallas_tuning.json"))


#: the timing fields every autotune entry may carry; all must be
#: positive finite seconds — a negative "measurement" is the two-length
#: slope estimator going underwater on tunnel jitter, not physics
_TIMING_KEYS = ("seconds", "xla_seconds")
_insane_warned = False


def _sane_entry(entry):
    """True when an autotune row is physically possible: a dict whose
    timing fields (if present) are positive finite numbers. The
    VERDICT r5 artifact — a persisted NEGATIVE xla_seconds — gated a
    product matmul on a measurement that never happened."""
    if not isinstance(entry, dict):
        return False
    for key in _TIMING_KEYS:
        if key in entry:
            value = entry[key]
            if isinstance(value, bool) \
                    or not isinstance(value, (int, float)) \
                    or not math.isfinite(value) or value <= 0:
                return False
    return True


def _drop_insane(cache, where):
    """Remove physically impossible rows in place (warn once); the
    dropped bucket simply re-tunes on its next autotune run — default
    blocks and the XLA path serve it meanwhile."""
    global _insane_warned
    bad = [key for key, entry in cache.items()
           if not _sane_entry(entry)]
    for key in bad:
        del cache[key]
    if bad and not _insane_warned:
        _insane_warned = True
        logging.getLogger("gemm.autotune").warning(
            "dropped %d physically impossible autotune entr%s %s "
            "(non-positive or non-finite timing — the slope estimator "
            "went underwater on jitter): %s; affected buckets re-tune "
            "on next use (reported once)",
            len(bad), "y" if len(bad) == 1 else "ies", where,
            ", ".join(sorted(bad)))
    return bad


def _load_cache():
    global _tuning_cache
    if _tuning_cache is None:
        try:
            with open(_cache_path(), "r") as fin:
                _tuning_cache = json.load(fin)
        except (OSError, ValueError):
            _tuning_cache = {}
        if not isinstance(_tuning_cache, dict):
            _tuning_cache = {}
        # hygiene at load: poisoned rows from older rounds are dropped
        # AND the cleaned cache is persisted back so the artifact on
        # disk stops advertising the impossible measurement
        if _drop_insane(_tuning_cache, "at load"):
            _persist_cache(_tuning_cache)
    return _tuning_cache


def _persist_cache(cache):
    """Write the (already-updated) tuning cache to disk; shared by the
    GEMM and int8-matvec autotuners. Insane rows (non-positive /
    non-finite timings) are rejected here too, so no caller can
    re-poison the artifact."""
    global _tuning_cache
    _drop_insane(cache, "at persist")
    _tuning_cache = cache
    path = _cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fout:
            json.dump(cache, fout, indent=1)
    except OSError:
        pass


def _tuned_blocks(m, n, k, dtype):
    key = "%s:%d" % (dtype, _size_bucket(m, n, k))
    entry = _load_cache().get(key)
    if entry:
        return tuple(entry["blocks"])
    return _DEFAULT_BLOCKS


def _tuned_beats_xla(a, b):
    """The "tuned" gate: engage Pallas only where an autotune run on
    this device recorded the kernel beating XLA for the shape bucket
    (absent/old entries without the verdict stay on XLA)."""
    m, k = a.shape
    n = b.shape[1]
    key = "%s:%d" % (str(jnp.dtype(a.dtype)), _size_bucket(m, n, k))
    entry = _load_cache().get(key)
    return bool(entry and entry.get("beats_xla"))


def _size_bucket(m, n, k):
    size = m * n * k
    bucket = 0
    while size > 1:
        size >>= 3  # buckets by order of magnitude in each dim
        bucket += 1
    return bucket


def autotune_main(argv=None):
    """``python -m veles_tpu autotune MxNxK[,MxNxK...]`` — benchmark the
    Pallas GEMM block candidates for each shape on the current device and
    persist the winners (the role of the reference's per-device GEMM
    autotune + ``devices/device_infos.json``)."""
    import argparse
    parser = argparse.ArgumentParser(prog="veles_tpu autotune")
    parser.add_argument("shapes",
                        help="comma-separated MxNxK matmul shapes")
    parser.add_argument("--dtype", default="bfloat16",
                        choices=("bfloat16", "float32"))
    parser.add_argument("--iters", type=int, default=3)
    parser.add_argument("--int8", action="store_true",
                        help="tune the int8 dequant-fused matvec "
                             "(ops/quant.py) instead of the GEMM: "
                             "shapes are MxKxN")
    parser.add_argument("--paged-attention", action="store_true",
                        help="tune the fused paged-attention kernel's "
                             "head-block size "
                             "(ops/paged_attention.py) instead of the "
                             "GEMM: shapes are PSxD (page size x head "
                             "dim)")
    args = parser.parse_args(argv)
    dtype = getattr(jnp, args.dtype)
    failed = 0
    if args.paged_attention:
        from veles_tpu.ops.paged_attention import (
            autotune_paged_attention)
        for spec in args.shapes.split(","):
            ps, d = (int(x) for x in spec.lower().split("x"))
            block_h = autotune_paged_attention(ps, d, iters=args.iters)
            key = "pgatt:%dx%d" % (ps, d)
            try:
                with open(_cache_path()) as fin:
                    persisted = key in json.load(fin)
            except (OSError, ValueError):
                persisted = False
            if not persisted:
                failed += 1
            print(json.dumps({"shape": [ps, d],
                              "block_h": int(block_h),
                              "persisted": persisted,
                              "cache": _cache_path()}))
        return 1 if failed else 0
    if args.int8:
        from veles_tpu.ops.quant import autotune_int8
        for spec in args.shapes.split(","):
            m, k, n = (int(x) for x in spec.lower().split("x"))
            decision = autotune_int8(m, k, n, dtype=dtype)
            key = "int8:%dx%d" % (k, n)
            try:
                with open(_cache_path()) as fin:
                    persisted = key in json.load(fin)
            except (OSError, ValueError):
                persisted = False
            if not persisted:
                failed += 1
            print(json.dumps(dict(decision, shape=[m, k, n],
                                  persisted=persisted,
                                  cache=_cache_path())))
        return 1 if failed else 0
    for spec in args.shapes.split(","):
        m, n, k = (int(x) for x in spec.lower().split("x"))
        blocks = autotune_matmul(m, n, k, dtype=dtype, iters=args.iters)
        key = "%s:%d" % (str(jnp.dtype(dtype)), _size_bucket(m, n, k))
        try:  # read the file back: proves the winner actually persisted
            with open(_cache_path()) as fin:
                persisted = key in json.load(fin)
        except (OSError, ValueError):
            persisted = False
        if not persisted:
            failed += 1
        print(json.dumps({"shape": [m, n, k], "dtype": args.dtype,
                          "blocks": list(blocks),
                          "persisted": persisted,
                          "cache": _cache_path()}))
    # nonzero when nothing ran/persisted (e.g. no candidate fits or the
    # Pallas kernels are unavailable on this backend)
    return 1 if failed else 0


def _matmul_scan_time(product, a, lengths=(50, 350), repeats=4):
    """Device sec/iter of ``product(a)`` via two-length serialized
    scans with a host-read fence (``block_until_ready`` is a no-op on
    the tunneled backend, and single-dispatch wall time is RTT)."""
    import time

    def loop(length):
        @jax.jit
        def run(a0):
            def body(carry, _):
                out = product(carry)
                # un-foldable epsilon dependence serializes iterations
                return carry + (jnp.sum(out) * 1e-38).astype(
                    carry.dtype), ()
            return jnp.sum(lax.scan(body, a0, None,
                                    length=length)[0])
        return run

    best = {}
    for length in lengths:
        run = loop(length)
        float(run(a))  # compile + warm
        t = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(run(a))
            t = min(t, time.perf_counter() - t0)
        best[length] = t
    return (best[lengths[1]] - best[lengths[0]]) \
        / (lengths[1] - lengths[0])


def autotune_matmul(m, n, k, dtype=jnp.bfloat16, iters=4):
    """Benchmark candidate block sizes AND the XLA dot for this shape
    bucket, persist the winner with a ``beats_xla`` verdict (reference
    ``backends.py:623-731`` per-device GEMM autotune — the tuned result
    then engages automatically through ``matmul``'s "tuned" gate).
    ``iters`` = timing repeats per measured scan length."""
    rng_a = jnp.ones((m, k), dtype) * 0.01
    b = jnp.ones((k, n), dtype) * 0.01

    best, best_dt = None, float("inf")
    for bm, bn, bk in _CANDIDATES:
        if bm > m or bn > n or bk > k:
            continue
        try:
            dt = _matmul_scan_time(
                lambda v, bm=bm, bn=bn, bk=bk: pallas_matmul(
                    v, b, out_dtype=jnp.float32, bm=bm, bn=bn,
                    bk=bk).astype(dtype), rng_a, repeats=iters)
        except Exception:
            continue
        if dt < best_dt:
            best, best_dt = (bm, bn, bk), dt
    if best is None:
        # no viable candidate (e.g. off-TPU): skip the XLA baseline
        # too — there is nothing to compare it against
        return _DEFAULT_BLOCKS
    xla_dt = _matmul_scan_time(
        lambda v: lax.dot_general(
            v, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dtype), rng_a,
        repeats=iters)
    entry = {
        "blocks": list(best), "seconds": best_dt,
        "xla_seconds": xla_dt,
        # require a clear margin: a tie-level "win" (sub-noise) must
        # not flip a product matmul onto the kernel
        "beats_xla": best_dt < 0.97 * xla_dt}
    if not _sane_entry(entry):
        # the slope estimator went underwater (tunnel jitter can make
        # the long scan finish "faster" than the short one): a
        # physically impossible number must never be persisted as a
        # tuning verdict — keep the previous entry, re-tune later
        logging.getLogger("gemm.autotune").warning(
            "autotune %dx%dx%d measured an impossible timing "
            "(pallas %.3g s, xla %.3g s); verdict NOT persisted — "
            "re-run autotune for this shape", m, n, k, best_dt, xla_dt)
        return best
    cache = _load_cache()
    cache["%s:%d" % (str(jnp.dtype(dtype)),
                     _size_bucket(m, n, k))] = entry
    _persist_cache(cache)
    return best
