"""Activation functions and their output-form derivatives.

The Znicz forward units (All2AllTanh/Sigmoid/RELU/StrictRELU/Softmax —
named in ``BASELINE.json`` and the reference docs) apply these after the
GEMM. Derivatives are expressed **in terms of the activation output** so the
backward units need only the forward result, matching the reference backprop
unit contract (gradient units receive ``output`` + ``err_output``).

The reference scales tanh as ``1.7159 * tanh(0.6666 * x)`` (LeCun's
recommendation, used throughout Znicz); we keep those constants for accuracy
parity with the published MNIST numbers.
"""

import jax.numpy as jnp
from jax import nn as jnn

TANH_A = 1.7159
TANH_B = 0.6666


def linear(x):
    return x


def linear_deriv(y):
    return jnp.ones_like(y)


def tanh(x):
    """Scaled tanh: ``1.7159 * tanh(0.6666 x)`` (Znicz All2AllTanh)."""
    return TANH_A * jnp.tanh(TANH_B * x)


def tanh_deriv(y):
    # d/dx A*tanh(Bx) = A*B*(1 - tanh^2) = B/A * (A^2 - y^2)
    return (y * y - TANH_A * TANH_A) * (-TANH_B / TANH_A)


def sigmoid(x):
    return jnn.sigmoid(x)


def sigmoid_deriv(y):
    return y * (1.0 - y)


def relu(x):
    """Znicz RELU is the smooth variant ``log(1 + exp(x))`` (softplus)."""
    return jnn.softplus(x)


def relu_deriv(y):
    # y = log(1+e^x) ⇒ dy/dx = 1 - e^-y
    return 1.0 - jnp.exp(-y)


def strict_relu(x):
    return jnn.relu(x)


def strict_relu_deriv(y):
    return (y > 0).astype(y.dtype)


def softmax(x):
    return jnn.softmax(x, axis=-1)


ACTIVATIONS = {
    "linear": (linear, linear_deriv),
    "tanh": (tanh, tanh_deriv),
    "sigmoid": (sigmoid, sigmoid_deriv),
    "relu": (relu, relu_deriv),
    "strict_relu": (strict_relu, strict_relu_deriv),
}
