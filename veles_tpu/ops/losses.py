"""Evaluator math: losses, error signals, and classification metrics.

The Znicz EvaluatorSoftmax / EvaluatorMSE units compute the training error
signal fed to the gradient-descent chain plus host-visible metrics
(n_err, confusion matrix, max error). The masked variants are the single
source of truth — EvaluatorSoftmax (graph mode) and the fused train step
(``parallel/step.py``) both call them, which is what keeps the two modes
numerically identical. ``mask`` handles short final minibatches under jit's
static shapes; ``valid`` is passed in so a data-parallel caller can supply
the *global* valid count (psum over the mesh) and get exact full-batch
gradients.
"""

import jax.numpy as jnp
from jax import nn as jnn


def masked_softmax_xent(logits, labels, mask, valid):
    """Fused masked softmax cross-entropy.

    Returns ``(err, loss_sum, n_err, pred)`` where ``err`` is
    d(sum xent / valid)/d(logits) = (softmax - onehot)·mask/valid — the
    signal Znicz's EvaluatorSoftmax emits to the GD chain — and
    ``loss_sum`` is the *unnormalized* masked xent sum so distributed
    callers can psum it before dividing by the global ``valid``.
    """
    n_classes = logits.shape[-1]
    onehot = jnp.eye(n_classes, dtype=logits.dtype)[labels]
    logp = logits - jnp.max(logits, axis=-1, keepdims=True)
    logp = logp - jnp.log(jnp.sum(jnp.exp(logp), axis=-1, keepdims=True))
    loss_sum = -jnp.sum(jnp.sum(onehot * logp, axis=-1) * mask)
    err = (jnp.exp(logp) - onehot) * (mask / valid)[:, None]
    pred = jnp.argmax(logits, axis=-1)
    n_err = jnp.sum(((pred != labels) & (mask > 0)).astype(jnp.int32))
    return err, loss_sum, n_err, pred


def softmax_cross_entropy(logits, labels, n_classes=None, mask=None):
    """Single-host convenience wrapper: returns
    (err_logits, loss, n_err, max_confidence)."""
    if mask is None:
        mask = jnp.ones(logits.shape[0], logits.dtype)
    valid = jnp.maximum(jnp.sum(mask), 1.0)
    err, loss_sum, n_err, _ = masked_softmax_xent(logits, labels, mask,
                                                  valid)
    max_conf = jnp.max(jnn.softmax(logits, axis=-1))
    return err, loss_sum / valid, n_err, max_conf


def confusion_matrix(logits, labels, n_classes, mask=None):
    """Dense confusion-matrix increment (Znicz evaluator option)."""
    pred = jnp.argmax(logits, axis=-1)
    idx = labels * n_classes + pred
    weights = (jnp.ones_like(labels, dtype=jnp.int32) if mask is None
               else mask.astype(jnp.int32))
    flat = jnp.zeros((n_classes * n_classes,), jnp.int32).at[idx].add(
        weights)
    return flat.reshape(n_classes, n_classes)


def masked_mse(output, target, mask, valid):
    """Masked MSE: returns (err_output, loss_sum, max_err); ``loss_sum``
    unnormalized for the same distributed reason as masked_softmax_xent."""
    diff = (output - target) * mask.reshape(
        (-1,) + (1,) * (output.ndim - 1))
    loss_sum = jnp.sum(diff.reshape(diff.shape[0], -1) ** 2)
    err = diff * (2.0 / valid)
    return err, loss_sum, jnp.max(jnp.abs(diff))


def mse(output, target):
    """Returns (err_output, loss, max_err) — Znicz EvaluatorMSE contract."""
    batch = output.shape[0]
    mask = jnp.ones(batch, output.dtype)
    err, loss_sum, max_err = masked_mse(output, target, mask,
                                        jnp.asarray(float(batch)))
    return err, loss_sum / batch, max_err
