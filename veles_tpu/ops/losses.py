"""Evaluator math: losses, error signals, and classification metrics.

The Znicz EvaluatorSoftmax / EvaluatorMSE units compute the training error
signal fed to the gradient-descent chain plus host-visible metrics
(n_err, confusion matrix, max error). Here each is one pure function
designed to live inside the jitted tick: metrics come back as device scalars
/ small arrays and are read on host only at epoch boundaries.
"""

import jax.numpy as jnp
from jax import nn as jnn


def softmax_cross_entropy(logits, labels, n_classes=None):
    """Returns (err_logits, loss, n_err, max_confidence).

    ``err_logits`` is d(mean xent)/d(logits) = (softmax - onehot)/batch —
    exactly the signal Znicz's EvaluatorSoftmax emits to the GD chain.
    """
    if n_classes is None:
        n_classes = logits.shape[-1]
    batch = logits.shape[0]
    probs = jnn.softmax(logits, axis=-1)
    onehot = jnn.one_hot(labels, n_classes, dtype=logits.dtype)
    logp = jnn.log_softmax(logits, axis=-1)
    loss = -jnp.mean(jnp.sum(onehot * logp, axis=-1))
    err = (probs - onehot) / batch
    pred = jnp.argmax(logits, axis=-1)
    n_err = jnp.sum((pred != labels).astype(jnp.int32))
    max_conf = jnp.max(probs)
    return err, loss, n_err, max_conf


def confusion_matrix(logits, labels, n_classes):
    """Dense confusion-matrix increment (Znicz evaluator option)."""
    pred = jnp.argmax(logits, axis=-1)
    idx = labels * n_classes + pred
    flat = jnp.zeros((n_classes * n_classes,), jnp.int32).at[idx].add(1)
    return flat.reshape(n_classes, n_classes)


def mse(output, target):
    """Returns (err_output, loss, max_err) — Znicz EvaluatorMSE contract."""
    batch = output.shape[0]
    diff = output - target
    loss = jnp.mean(jnp.sum(
        diff.reshape(batch, -1) ** 2, axis=-1))
    err = diff * (2.0 / batch)
    max_err = jnp.max(jnp.abs(diff))
    return err, loss, max_err
