"""Device benchmark: the computing-power measurement.

Reference ``accelerated_units.py:706-824`` (DeviceBenchmark): time a
standard GEMM workload and report ``1000/dt`` arbitrary "power" units —
the number a slave sends in its fleet handshake so the master can
power-weight job balancing (``workflow.py:613-619``). Here the workload
is a jitted bfloat16 matmul chain on whatever device JAX resolves.
"""

import time

import jax
import jax.numpy as jnp


def device_benchmark(size=1024, depth=4, iters=3):
    """Measured device power in the reference's 1000/dt units."""

    @jax.jit
    def chain(x):
        for _ in range(depth):
            x = jnp.matmul(x, x, preferred_element_type=jnp.float32)
            x = x.astype(jnp.bfloat16) / jnp.float32(size)
        return x

    x = jnp.ones((size, size), jnp.bfloat16)
    chain(x).block_until_ready()  # compile outside the timing
    t0 = time.perf_counter()
    for _ in range(iters):
        out = chain(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    return 1000.0 / max(dt, 1e-9)
