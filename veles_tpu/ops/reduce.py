"""Matrix reductions.

Replaces the reference's generic reduction scaffold
(``ocl/matrix_reduce.cl``, ``cuda/matrix_reduce.cu``) which Znicz used for
bias gradients, normalization statistics and Kohonen winner search. On TPU
these lower directly to VPU reduction trees via lax; no hand scheduling is
needed or beneficial. Kept as named entry points so unit code expresses
intent (and so a Pallas fused variant can slot in later).
"""

import jax.numpy as jnp


def reduce_sum(x, axis=0):
    return jnp.sum(x, axis=axis)


def reduce_mean(x, axis=0):
    return jnp.mean(x, axis=axis)


def reduce_max(x, axis=0):
    return jnp.max(x, axis=axis)


def argmin_rows(x):
    """Winner search across rows (Kohonen SOM uses this shape)."""
    return jnp.argmin(x, axis=-1)
