"""Minibatch gather from a device-resident full-batch dataset.

Replaces ``cuda/fullbatch_loader.cu`` / ``ocl/fullbatch_loader.cl``
(``fill_minibatch_data_labels``): the reference keeps the entire dataset on
device and gathers shuffled minibatch samples + labels by index. On TPU this
is a ``jnp.take`` along axis 0 — XLA emits an efficient dynamic-gather — and
it composes into the jitted train tick so data never round-trips to host.

Normalization (the kernel fused a scale/shift) is applied in the same traced
function so XLA fuses it into the gather's consumer.
"""

import jax.numpy as jnp


def gather_minibatch(data, indices, labels=None, scale=None, shift=None):
    """Gather ``data[indices]`` (+ labels), with optional affine normalize.

    Returns (batch,) or (batch, labels) tuple mirroring the reference
    kernel's dual outputs.
    """
    batch = jnp.take(data, indices, axis=0)
    if scale is not None:
        batch = batch * scale
    if shift is not None:
        batch = batch + shift
    if labels is None:
        return batch
    return batch, jnp.take(labels, indices, axis=0)
