"""veles_tpu.ops: the TPU-native op library (the Znicz-kernel equivalent).

Each module replaces a family of reference OpenCL/CUDA kernels with a JAX/
Pallas implementation designed for the MXU/VPU rather than translated from
the GPU sources:

- ``gemm``      — reference ``ocl/matrix_multiplication*.cl``, ``ocl/gemm.cl``
- ``reduce``    — reference ``ocl/matrix_reduce.cl``, ``cuda/matrix_reduce.cu``
- ``gather``    — reference ``cuda/fullbatch_loader.cu`` (minibatch gather)
- ``rng``       — reference ``ocl/random.cl`` (xorshift1024*) → threefry/pallas PRNG
- ``activations``/``losses`` — the Znicz forward/evaluator math
"""

from veles_tpu.ops.gemm import matmul  # noqa: F401
from veles_tpu.ops import activations, losses  # noqa: F401
from veles_tpu.ops.reduce import reduce_sum, reduce_mean, reduce_max  # noqa: F401
from veles_tpu.ops.gather import gather_minibatch  # noqa: F401
from veles_tpu.ops.rng import uniform, normal, fill_uniform  # noqa: F401
