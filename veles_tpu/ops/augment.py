"""In-jit data augmentation transforms.

Single source of the augmentation math: BOTH execution engines — the
graph path's ``FullBatchImageLoader._augment_jit`` and the fused tick's
``apply_augment`` — trace these functions, so "fused == graph numerics"
is structural, not a comment to keep in sync.
"""

import jax
import jax.numpy as jnp


def mirror_batch(batch, seed):
    """Per-sample random horizontal mirror of an NHWC batch, keyed by a
    scalar ``seed`` (the loader draws seeds host-side in graph-mode
    order; replaces the reference's N-fold ``samples_inflation``)."""
    key = jax.random.key(seed)
    flip = jax.random.bernoulli(key, 0.5, (batch.shape[0],))
    mirrored = jnp.flip(batch, axis=2)  # horizontal (W axis)
    return jnp.where(flip[:, None, None, None], mirrored, batch)


def shift_batch(batch, seed, max_shift=1):
    """Per-sample random integer translation of an NHWC batch by
    [-max_shift, +max_shift] pixels in H and W, zero-filled — the
    reference ImageLoader's random crop-offset augmentation
    (``loader/image.py`` crop with random offsets) as one in-jit
    gather."""
    n, height, width = batch.shape[0], batch.shape[1], batch.shape[2]
    key = jax.random.key(seed)
    kh, kw = jax.random.split(key)
    dh = jax.random.randint(kh, (n,), -max_shift, max_shift + 1)
    dw = jax.random.randint(kw, (n,), -max_shift, max_shift + 1)
    rows = jnp.arange(height)[None, :] - dh[:, None]      # (N, H) src
    cols = jnp.arange(width)[None, :] - dw[:, None]       # (N, W) src
    row_ok = (rows >= 0) & (rows < height)
    col_ok = (cols >= 0) & (cols < width)
    rows = jnp.clip(rows, 0, height - 1)
    cols = jnp.clip(cols, 0, width - 1)
    out = batch[jnp.arange(n)[:, None, None],
                rows[:, :, None], cols[:, None, :], :]
    mask = (row_ok[:, :, None] & col_ok[:, None, :])[..., None]
    return jnp.where(mask, out, jnp.zeros((), batch.dtype))


def shift1_batch(batch, seed):
    """``shift_batch`` pinned to +-1 px (the "shift1" transform name)."""
    return shift_batch(batch, seed, max_shift=1)


#: transform name -> (batch, seed) fn: the loaders' ``jit_transform``
#: names resolve here in BOTH engines (graph fill and fused tick)
TRANSFORMS = {"mirror": mirror_batch, "shift1": shift1_batch}
