"""In-jit data augmentation transforms.

Single source of the augmentation math: BOTH execution engines — the
graph path's ``FullBatchImageLoader._augment_jit`` and the fused tick's
``apply_augment`` — trace these functions, so "fused == graph numerics"
is structural, not a comment to keep in sync.
"""

import jax
import jax.numpy as jnp


def mirror_batch(batch, seed):
    """Per-sample random horizontal mirror of an NHWC batch, keyed by a
    scalar ``seed`` (the loader draws seeds host-side in graph-mode
    order; replaces the reference's N-fold ``samples_inflation``)."""
    key = jax.random.key(seed)
    flip = jax.random.bernoulli(key, 0.5, (batch.shape[0],))
    mirrored = jnp.flip(batch, axis=2)  # horizontal (W axis)
    return jnp.where(flip[:, None, None, None], mirrored, batch)
