"""Attention ops: flash attention and ring attention (sequence parallel).

No reference counterpart — VELES predates attention (SURVEY §5
"Long-context: absent") — but long context is first-class here. Two tiers:

- ``attention``: single-device fused attention. Uses the Pallas TPU flash
  kernel for real workloads, falling back to ``jax.nn.dot_product_attention``
  (XLA) for small/ragged shapes and non-TPU backends.
- ``ring_attention``: blockwise attention over a ``seq``-sharded mesh axis.
  Each device holds one query block; K/V blocks rotate around the ring via
  ``lax.ppermute`` over ICI while a running online-softmax (m, l, o)
  accumulator absorbs each visiting block — compute overlaps transfer and
  no device ever materializes the full sequence. This is the
  RingAttention/blockwise-parallel pattern; causal masking uses block
  positions so fully-masked pairs still do one cheap fused pass.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax


def attention(q, k, v, causal=False, scale=None):
    """Fused single-device attention. Shapes: (B, T, H, D)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_pallas_flash(q, k):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention)
        # pallas kernel wants (B, H, T, D)
        out = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal, sm_scale=scale)
        return out.transpose(0, 2, 1, 3)
    return jax.nn.dot_product_attention(
        q, k, v, scale=scale, is_causal=causal)


#: None = auto (the measured >=4096 gate); True/False pin the flash
#: kernel for every call — the bench's interleaved on/off comparison
FORCE_FLASH = None


def _use_pallas_flash(q, k):
    if FORCE_FLASH is not None:
        return FORCE_FLASH
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    # MEASURED crossover on the v5e (two-length device timing, causal,
    # hd=128): XLA's attention wins below ~4k sequence (0.08 vs
    # 0.34 ms at S=512, 1.38 vs 1.74 ms at S=2048); the flash kernel
    # takes over once the S x S score materialization dominates
    # (1.06x at S=4096, 1.21x at S=8192). It also tiles (T, D) onto
    # (128, 128) MXU blocks, so head_dim must divide 128.
    return (q.shape[1] >= 4096 and k.shape[1] >= 4096
            and q.shape[-1] % 128 == 0)


def attention_block(x, w_qkv, b_qkv, w_out, b_out, heads, causal,
                    residual=False, precision_level=None):
    """The complete self-attention block — fused qkv projection →
    multi-head attention → out projection (→ residual add) — under the
    SAME engine precision policy as the dense/conv paths (``ops/gemm.py
    compute_operands``): level 0 runs the projections and the attention
    core in bf16 with f32 matmul accumulation (~15% faster forward than
    f32 operands, measured), levels 1/2 keep f32 with HIGH/HIGHEST.
    ONE implementation — the residual included, like ``ffn_block`` —
    serves the graph unit (``nn/attention.py``), its vjp backward, and
    the fused engine — the modes stay bit-identical by construction."""
    from veles_tpu.ops.gemm import compute_operands

    batch, t, embed = x.shape
    head_dim = embed // heads
    (xc, wqkv, wout), precision = compute_operands(
        x, w_qkv, w_out, precision_level=precision_level)
    qkv = lax.dot_general(
        xc, wqkv, (((2,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32) + b_qkv
    q, k, v = jnp.split(qkv.astype(xc.dtype), 3, axis=-1)
    shape = (batch, t, heads, head_dim)
    q, k, v = q.reshape(shape), k.reshape(shape), v.reshape(shape)
    if precision is lax.Precision.DEFAULT:
        out = attention(q, k, v, causal=causal)
    else:
        # the accuracy tiers (levels 1/2): jax.nn.dot_product_attention
        # exposes no precision knob, so the core runs as explicit dots
        # carrying the requested HIGH/HIGHEST passes
        out = _precise_attention(q, k, v, causal, precision)
    out = lax.dot_general(
        out.reshape(batch, t, embed).astype(xc.dtype), wout,
        (((2,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32) + b_out
    return x + out if residual else out


#: activations usable inside the FFN block. gelu is jax.nn's default
#: tanh approximation — the native runtime (native/src/units.cc FfnUnit)
#: implements the same polynomial so exported packages stay in tolerance.
_FFN_ACTIVATIONS = {
    "gelu": jax.nn.gelu,
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "linear": lambda h: h,
}


def ffn_block(x, w1, b1, w2, b2, activation="gelu", residual=True,
              precision_level=None):
    """Position-wise transformer feed-forward block —
    ``act(x @ w1 + b1) @ w2 + b2`` with an optional residual add — under
    the SAME engine precision policy as the attention/dense/conv paths
    (``ops/gemm.py compute_operands``): level 0 runs both projections in
    bf16 with f32 matmul accumulation; the bias adds, activation and
    residual stay f32. ONE implementation serves the graph unit
    (``nn/attention.TokenFFN``), its vjp backward, and the fused engine —
    the modes stay bit-identical by construction.

    No reference counterpart (VELES predates transformers); this extends
    the sequence-model tier the same way SelfAttention does."""
    from veles_tpu.ops.gemm import compute_operands

    act = _FFN_ACTIVATIONS[activation]
    (xc, w1c, w2c), precision = compute_operands(
        x, w1, w2, precision_level=precision_level)
    h = lax.dot_general(
        xc, w1c, (((x.ndim - 1,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32) + b1
    out = lax.dot_general(
        act(h).astype(xc.dtype), w2c,
        (((h.ndim - 1,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32) + b2
    return x + out if residual else out


def _precise_attention(q, k, v, causal, precision):
    """Reference-math attention with an explicit lax precision on the
    score and value matmuls (the level-1/2 contract); f32 softmax."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, precision=precision,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v,
                      precision=precision,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# -- ring attention -----------------------------------------------------------

def _block_attend(q, k, v, scale, mask_value, causal, q_pos, kv_pos):
    """One (q-block x kv-block) pass returning unnormalized (o, m, l):
    o = exp(s - m) @ v row-accumulator, m = row max, l = row sum."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qi = q_pos[:, None]
        ki = kv_pos[None, :]
        s = jnp.where((ki <= qi)[None, None, :, :], s, mask_value)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, l


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   mask_value=-1e30):
    """Sequence-parallel attention inside shard_map: ``q/k/v`` are the
    LOCAL sequence blocks (B, T_local, H, D); the full sequence is
    ``T_local * axis_size`` long, laid out in ring order along
    ``axis_name``. Returns the local block of the attention output."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    from veles_tpu.parallel.mesh import axis_size as _axis_size
    axis_size = _axis_size(axis_name)
    my_index = lax.axis_index(axis_name)
    t_local = q.shape[1]
    q_pos = my_index * t_local + jnp.arange(t_local)

    batch, _, heads, _ = q.shape
    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full((batch, heads, t_local), mask_value, jnp.float32)
    l = jnp.zeros((batch, heads, t_local), jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(carry, step):
        o, m, l, k_blk, v_blk = carry
        src_index = (my_index - step) % axis_size
        kv_pos = src_index * t_local + jnp.arange(t_local)
        o_i, m_i, l_i = _block_attend(q, k_blk, v_blk, scale, mask_value,
                                      causal, q_pos, kv_pos)
        # online-softmax merge of the visiting block
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        l = l * alpha + l_i * beta
        o = (o * alpha.transpose(0, 2, 1)[..., None]
             + o_i * beta.transpose(0, 2, 1)[..., None])
        # rotate K/V around the ring (overlaps with next block's compute)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, m_new, l, k_blk, v_blk), None

    # lax.scan, not fori_loop: scan is reverse-differentiable, so ring
    # attention works inside jax.grad (ring-parallel TRAINING) at the
    # cost of per-step residuals. The running max starts at mask_value
    # (not -inf): a -inf start makes exp(m - m_new) produce inf*0=nan
    # in the backward pass for fully-masked first blocks.
    (o, m, l, _, _), _ = lax.scan(body, (o, m, l, k, v),
                                  jnp.arange(axis_size))
    l = jnp.maximum(l, 1e-20)  # fully-masked rows (causal first block)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def make_ring_attention(mesh, axis_name="seq", causal=False):
    """shard_map-wrapped ring attention over ``mesh``: takes/returns
    sequence-sharded (B, T, H, D) arrays."""
    from jax.sharding import PartitionSpec as P
    from veles_tpu.parallel.mesh import shard_map

    spec = P(None, axis_name, None, None)
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal)
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))


# -- Ulysses (all-to-all) sequence parallelism --------------------------------

def ulysses_attention(q, k, v, axis_name, causal=False, scale=None):
    """All-to-all sequence parallelism (the DeepSpeed-Ulysses pattern)
    inside shard_map: ``q/k/v`` are LOCAL sequence blocks
    (B, T_local, H, D). One ``all_to_all`` swaps the sequence sharding
    for HEAD sharding — each device then holds the FULL sequence for
    ``H / axis_size`` heads and runs ordinary fused attention locally —
    and the inverse all_to_all restores the sequence layout.

    Trade-off vs :func:`ring_attention`: four collectives per call
    (q/k/v in, output back) instead of ``2 * axis_size`` ppermute
    rounds (better for fat ICI all-to-all and moderate sequence
    lengths), but it requires
    ``heads % axis_size == 0`` and materializes the full sequence per
    device for its head slice (HBM scales with T, not T/n)."""
    from veles_tpu.parallel.mesh import axis_size as _axis_size
    n = _axis_size(axis_name)
    heads = q.shape[2]
    if heads % n:
        raise ValueError("ulysses needs heads (%d) divisible by the "
                         "%r axis size (%d)" % (heads, axis_name, n))

    def seq_to_heads(x):  # (B, T/n, H, D) -> (B, T, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    out = attention(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
                    causal=causal, scale=scale)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def make_ulysses_attention(mesh, axis_name="seq", causal=False):
    """shard_map-wrapped Ulysses attention over ``mesh``: takes/returns
    sequence-sharded (B, T, H, D) arrays (same contract as
    :func:`make_ring_attention` — the two are drop-in alternatives)."""
    from jax.sharding import PartitionSpec as P
    from veles_tpu.parallel.mesh import shard_map

    spec = P(None, axis_name, None, None)
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           causal=causal)
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
