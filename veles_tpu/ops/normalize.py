"""Mean/dispersion normalization op.

Replaces ``ocl/mean_disp_normalizer.cl`` / ``cuda/mean_disp_normalizer.cu``:
``out = (in - mean) * rdisp`` applied per feature. Pure elementwise — XLA
fuses it into whatever consumes it, so the right TPU design is a plain
traced function, not a kernel.
"""


def mean_disp_normalize(x, mean, rdisp):
    """(x - mean) * rdisp, broadcasting stats over the batch axis."""
    return (x - mean) * rdisp


def compute_mean_disp(data, eps=1e-8):
    """Training-set statistics: mean and reciprocal dispersion
    (max-min based, as the reference MeanDispNormalizer defines it)."""
    import jax.numpy as jnp
    mean = jnp.mean(data, axis=0)
    disp = jnp.max(data, axis=0) - jnp.min(data, axis=0)
    rdisp = 1.0 / jnp.maximum(disp, eps)
    return mean, rdisp
